"""Tokenizer tests with a tiny constructed BPE vocab; round-trip always holds
regardless of merges (byte-level)."""

import json

import pytest

from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer, bytes_to_unicode


@pytest.fixture
def tok(tmp_path):
    b2u = bytes_to_unicode()
    # base vocab: all 256 byte symbols + a couple of merges + eos
    symbols = [b2u[b] for b in range(256)]
    merges = [("h", "e"), ("l", "l"), ("he", "ll"), ("hell", "o")]
    for a, b in merges:
        symbols.append(a + b)
    symbols.append("<|endoftext|>")
    vocab = {s: i for i, s in enumerate(dict.fromkeys(symbols))}
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in merges)
    )
    return GPTTokenizer.from_pretrained(str(tmp_path))


def test_roundtrip(tok):
    for text in ["hello world", "hello", "a b  c\nd", "héllo ☂"]:
        assert tok.decode(tok.encode(text)) == text


def test_merges_applied(tok):
    ids = tok.encode("hello")
    # 'hello' fully merges into one token
    assert len(ids) == 1
    assert tok.decoder[ids[0]] == "hello"


def test_eos(tok):
    assert tok.eos_token_id == tok.encoder["<|endoftext|>"]


# ---------------------------------------------------------------------------
# DebertaV2 sentencepiece-style tokenizer
# ---------------------------------------------------------------------------

from paddlefleetx_tpu.data.tokenizers.debertav2_tokenizer import (  # noqa: E402
    DebertaV2Tokenizer,
)

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "deberta uses disentangled attention",
    "sentencepiece segments words into pieces",
]


@pytest.fixture
def dtok():
    return DebertaV2Tokenizer.from_tiny_corpus(CORPUS)


def test_deberta_special_layout(dtok):
    # [PAD]=0, [CLS]=1, [SEP]=2, [UNK]=3; [MASK] appended at the top
    assert dtok.pad_id == 0
    assert dtok.cls_id == 1
    assert dtok.sep_id == 2
    assert dtok.vocab["[UNK]"] == 3
    assert dtok.mask_id == dtok.vocab_size - 1


def test_deberta_roundtrip(dtok):
    for text in CORPUS:
        enc = dtok.encode(text)
        assert enc["input_ids"][0] == dtok.cls_id
        assert enc["input_ids"][-1] == dtok.sep_id
        assert dtok.decode(enc["input_ids"]) == text


def test_deberta_pair_and_padding(dtok):
    enc = dtok.encode("the quick fox", "the lazy dog", max_length=16, padding=True)
    ids, types, mask = enc["input_ids"], enc["token_type_ids"], enc["attention_mask"]
    assert len(ids) == len(types) == len(mask) == 16
    n_sep = sum(1 for i in ids if i == dtok.sep_id)
    assert n_sep == 2
    first_sep = ids.index(dtok.sep_id)
    assert all(t == 0 for t in types[: first_sep + 1])
    pad_start = mask.index(0)
    assert all(t == 1 for t in types[first_sep + 1 : pad_start] if True)
    assert all(i == dtok.pad_id for i in ids[pad_start:])


def test_deberta_truncation(dtok):
    enc = dtok.encode(
        "the quick brown fox jumps over the lazy dog", max_length=6
    )
    assert len(enc["input_ids"]) == 6
    assert enc["input_ids"][0] == dtok.cls_id
    assert enc["input_ids"][-1] == dtok.sep_id


def test_deberta_save_load_stable(dtok, tmp_path):
    p = str(tmp_path / "deberta_vocab.json")
    dtok.save(p)
    tok2 = DebertaV2Tokenizer.from_file(p)
    text = CORPUS[1]
    assert dtok.encode(text) == tok2.encode(text)


def test_t5_sentinel_descending():
    """extra_id_0 must be the HIGHEST id (reference/HF layout)."""
    from paddlefleetx_tpu.data.tokenizers.t5_tokenizer import T5Tokenizer

    t = T5Tokenizer.from_tiny_corpus(CORPUS, num_extra_ids=10)
    assert t.extra_id(0) == t.vocab_size - 1
    assert t.extra_id(9) == t.vocab_size - 10


def test_native_bpe_matches_python(tok, tmp_path):
    """The C++ merge engine (data/cpp/bpe.cpp) produces exactly the Python
    ids on mixed text, including unicode and whitespace runs."""
    texts = [
        "hello hello world",
        "  spaces\tand\nnewlines  ",
        "unicode: café 你好 \U0001f600!",
        "numbers 12345 and punct!!! ...",
        "hellohellohello",
    ]
    if tok._native is None:
        import pytest

        pytest.skip("no native build available")
    for t in texts:
        fast = tok.encode(t)
        # force pure-Python: temporarily drop the native engine
        native, tok._native = tok._native, None
        tok._id_cache.clear()
        slow = tok.encode(t)
        tok._native = native
        assert fast == slow, (t, fast, slow)
        assert tok.decode(fast) == t


def test_native_bpe_specials_fall_back(tok):
    """Special tokens (not byte-mappable) keep working via the Python path."""
    if tok._native is None:
        import pytest

        pytest.skip("no native build available")
    ids = tok.encode("hello")
    assert tok.decode(ids) == "hello"
    assert tok.eos_token_id is not None
