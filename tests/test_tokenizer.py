"""Tokenizer tests with a tiny constructed BPE vocab; round-trip always holds
regardless of merges (byte-level)."""

import json

import pytest

from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer, bytes_to_unicode


@pytest.fixture
def tok(tmp_path):
    b2u = bytes_to_unicode()
    # base vocab: all 256 byte symbols + a couple of merges + eos
    symbols = [b2u[b] for b in range(256)]
    merges = [("h", "e"), ("l", "l"), ("he", "ll"), ("hell", "o")]
    for a, b in merges:
        symbols.append(a + b)
    symbols.append("<|endoftext|>")
    vocab = {s: i for i, s in enumerate(dict.fromkeys(symbols))}
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in merges)
    )
    return GPTTokenizer.from_pretrained(str(tmp_path))


def test_roundtrip(tok):
    for text in ["hello world", "hello", "a b  c\nd", "héllo ☂"]:
        assert tok.decode(tok.encode(text)) == text


def test_merges_applied(tok):
    ids = tok.encode("hello")
    # 'hello' fully merges into one token
    assert len(ids) == 1
    assert tok.decoder[ids[0]] == "hello"


def test_eos(tok):
    assert tok.eos_token_id == tok.encoder["<|endoftext|>"]
