"""Engine-mode inference demo (reference tasks/gpt/inference.py:36-61):
build the module, wrap it in the serving engine, generate a completion
for a prompt — the deploy-path counterpart of tasks/gpt/generation.py.

  python tasks/gpt/inference.py -c configs/gpt/pretrain_gpt_345M_single.yaml \
      [-o Generation.prompt='...'] [-o Generation.tokenizer_dir=out/gpt2]

For serving an exported StableHLO artifact (tools/export.py output) use
``tools/inference.py`` — that path executes the serialized graph itself.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init

from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.core.serving import GenerationServer
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.utils.config import get_config, parse_args
from paddlefleetx_tpu.utils.log import logger


def main(argv=None):
    args = parse_args(argv)
    cfg = get_config(args.config, overrides=args.override)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    gen_cfg = cfg.get("Generation", {})
    tokenizer_dir = gen_cfg.get("tokenizer_dir")
    tok = None
    if tokenizer_dir:
        from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

        tok = GPTTokenizer.from_pretrained(tokenizer_dir)

    server = GenerationServer(cfg, mesh, module, tokenizer=tok)

    prompt_text = gen_cfg.get("prompt", "Hi, GPT2. Tell me who Jack Ma is.")
    if tok is not None:
        out = server.generate_text([prompt_text])[0]
        logger.info(f"Prompt: {prompt_text!r}")
        logger.info(f"Generation: {(prompt_text + out)!r}")
    else:
        ids = [1, 2, 3, 4]
        outs = server.generate_ids([ids])
        logger.info(f"Prompt ids: {ids}")
        logger.info(f"Generated ids: {outs[0]}")


if __name__ == "__main__":
    main()
