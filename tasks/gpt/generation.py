"""Zero-shot generation demo (reference tasks/gpt/generation.py:34-62):
no-engine path — build module, load checkpoint, generate from a prompt."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init

import jax

from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.parallel.seed import get_seed_tracker
from paddlefleetx_tpu.utils.config import get_config, parse_args
from paddlefleetx_tpu.utils.log import logger


def main(argv=None):
    args = parse_args(argv)
    cfg = get_config(args.config, overrides=args.override)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    gen_cfg = cfg.get("Generation", {})
    gen = GenerationConfig(
        max_dec_len=int(gen_cfg.get("max_dec_len", 32)),
        min_dec_len=int(gen_cfg.get("min_dec_len", 1)),
        decode_strategy=gen_cfg.get("decode_strategy", "sampling"),
        temperature=float(gen_cfg.get("temperature", 1.0)),
        top_k=int(gen_cfg.get("top_k", 0)),
        top_p=float(gen_cfg.get("top_p", 1.0)),
        repetition_penalty=float(gen_cfg.get("repetition_penalty", 1.0)),
        eos_token_id=int(gen_cfg.get("eos_token_id", 50256)),
        pad_token_id=int(gen_cfg.get("pad_token_id", 0)),
        num_beams=int(gen_cfg.get("num_beams", 4)),
        length_penalty=float(gen_cfg.get("length_penalty", 1.0)),
        num_beam_groups=int(gen_cfg.get("num_beam_groups", 1)),
        diversity_penalty=float(gen_cfg.get("diversity_penalty", 0.0)),
        forced_bos_token_id=int(gen_cfg.get("forced_bos_token_id", -1)),
        forced_eos_token_id=int(gen_cfg.get("forced_eos_token_id", -1)),
    )

    # mesh serving: params sharded by the logical rules, KV cache
    # heads-sharded over `model` (TP serving, VERDICT r1 item 5)
    from paddlefleetx_tpu.models.gpt.model import ShardingCtx
    from paddlefleetx_tpu.parallel.sharding import (
        make_rules,
        tree_logical_to_sharding,
    )

    rules = make_rules(mesh=mesh)
    ctx = ShardingCtx(mesh, rules) if mesh.size > 1 else None
    params = module.init_params(get_seed_tracker().params_key())
    if ctx is not None:
        shardings = tree_logical_to_sharding(module.logical_axes(), mesh, rules)
        params = jax.device_put(params, shardings)

    tokenizer_dir = gen_cfg.get("tokenizer_dir")
    prompt_text = gen_cfg.get("prompt", "Hi there")
    if tokenizer_dir:
        from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

        tok = GPTTokenizer.from_pretrained(tokenizer_dir)
        ids = tok.encode(prompt_text)
    else:
        tok = None
        ids = [1, 2, 3, 4]

    # bucketed serving: pad the prompt to a fixed-width bucket so repeated
    # calls with different prompt lengths reuse one compiled artifact
    from paddlefleetx_tpu.models.gpt.generation import pad_prompts

    bucket = int(gen_cfg.get("pad_to_multiple", 32))
    prompt, prompt_lens = pad_prompts([ids], gen.pad_token_id, multiple=bucket)

    # jitted so GSPMD plans the whole decode once (and eager sharding
    # constraints never see a sub-divisible batch)
    with mesh:
        out = jax.jit(
            lambda p, x, lens: generate(
                p, x, module.config, gen, key=jax.random.key(0), ctx=ctx,
                prompt_lens=lens,
            )
        )(params, prompt, prompt_lens)
    ids = out[0].tolist()
    logger.info(f"prompt: {prompt_text!r}")
    logger.info(f"generated ids: {ids}")
    if tok is not None:
        logger.info(f"generated text: {tok.decode(ids)!r}")


if __name__ == "__main__":
    main()
