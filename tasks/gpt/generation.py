"""Zero-shot generation demo (reference tasks/gpt/generation.py:34-62):
no-engine path — build module, load checkpoint, generate from a prompt."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import jax

from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.parallel.seed import get_seed_tracker
from paddlefleetx_tpu.utils.config import get_config, parse_args
from paddlefleetx_tpu.utils.log import logger


def main(argv=None):
    args = parse_args(argv)
    cfg = get_config(args.config, overrides=args.override)
    init_dist_env(cfg)
    module = build_module(cfg)
    params = module.init_params(get_seed_tracker().params_key())

    gen_cfg = cfg.get("Generation", {})
    gen = GenerationConfig(
        max_dec_len=int(gen_cfg.get("max_dec_len", 32)),
        min_dec_len=int(gen_cfg.get("min_dec_len", 1)),
        decode_strategy=gen_cfg.get("decode_strategy", "sampling"),
        temperature=float(gen_cfg.get("temperature", 1.0)),
        top_k=int(gen_cfg.get("top_k", 0)),
        top_p=float(gen_cfg.get("top_p", 1.0)),
        repetition_penalty=float(gen_cfg.get("repetition_penalty", 1.0)),
        eos_token_id=int(gen_cfg.get("eos_token_id", 50256)),
        pad_token_id=int(gen_cfg.get("pad_token_id", 0)),
    )

    tokenizer_dir = gen_cfg.get("tokenizer_dir")
    prompt_text = gen_cfg.get("prompt", "Hi there")
    if tokenizer_dir:
        from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

        tok = GPTTokenizer.from_pretrained(tokenizer_dir)
        prompt = jax.numpy.asarray([tok.encode(prompt_text)])
    else:
        tok = None
        prompt = jax.numpy.asarray([[1, 2, 3, 4]])

    out = generate(params, prompt, module.config, gen, key=jax.random.key(0))
    ids = out[0].tolist()
    logger.info(f"prompt: {prompt_text!r}")
    logger.info(f"generated ids: {ids}")
    if tok is not None:
        logger.info(f"generated text: {tok.decode(ids)!r}")


if __name__ == "__main__":
    main()
