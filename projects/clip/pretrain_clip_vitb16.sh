#!/usr/bin/env bash
# CLIP ViT-B/16 contrastive image-text pretrain
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/multimodal/clip/clip_vitb16_pt_1n8c.yaml "$@"
