#!/usr/bin/env bash
# GPT-175B mp8 x pp16 interleaved-1F1B pretrain (reference
# pretrain_gpt_175B_mp8_pp16.sh); run on every host with PFX_COORDINATOR_ADDRESS set
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/gpt/pretrain_gpt_175B_mp8_pp16.yaml "$@"
