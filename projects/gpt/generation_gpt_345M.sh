#!/usr/bin/env bash
# Zero-shot text generation demo (reference tasks/gpt/generation.py path)
set -e
cd "$(dirname "$0")/../.."
python tasks/gpt/generation.py -c configs/gpt/pretrain_gpt_345M_single.yaml "$@"
