#!/usr/bin/env bash
# Quant-aware training for GPT-345M over mp8 (reference projects/gpt/qat_gpt_345M_mp8.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/gpt/qat_gpt_345M_mp8.yaml "$@"
