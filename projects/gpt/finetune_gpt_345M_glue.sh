#!/usr/bin/env bash
# GPT-345M GLUE finetune (reference projects/gpt/finetune_gpt_345M_single_card_glue.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/gpt/finetune_gpt_345M_glue.yaml "$@"
