#!/usr/bin/env bash
# GPT-345M single-chip pretrain (reference projects/gpt/pretrain_gpt_345M_single_card.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/gpt/pretrain_gpt_345M_single.yaml "$@"
