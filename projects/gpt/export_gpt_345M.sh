#!/usr/bin/env bash
# Export GPT-345M to a StableHLO inference artifact (reference export_gpt_345M_single_card.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/export.py -c configs/gpt/pretrain_gpt_345M_single.yaml "$@"
