#!/usr/bin/env bash
# GPT-6.7B ZeRO-sharding-16 pretrain (reference pretrain_gpt_6.7B_sharding16.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/gpt/pretrain_gpt_6.7B_sharding16.yaml "$@"
