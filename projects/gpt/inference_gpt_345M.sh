#!/usr/bin/env bash
# Run inference from the exported artifact (reference projects/gpt/inference_gpt_345M_single_card.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/inference.py -c configs/gpt/pretrain_gpt_345M_single.yaml "$@"
