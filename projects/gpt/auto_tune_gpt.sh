#!/usr/bin/env bash
# Mesh-layout sweep (reference auto-parallel tuner analogue, tools/auto.py --tune)
set -e
cd "$(dirname "$0")/../.."
python tools/auto.py -c configs/gpt/pretrain_gpt_345M_single.yaml --tune "$@"
