#!/usr/bin/env bash
# GPT-1.3B tensor-parallel-8 pretrain (reference pretrain_gpt_1.3B_dp8.sh;
# TPU layout: model axis 8 over ICI)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/gpt/pretrain_gpt_1.3B_mp8.yaml "$@"
