#!/usr/bin/env bash
# DebertaV2-base MLM pretrain (see projects/debertav2/docs/pretrain_base.md)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/debertav2/pretrain_debertav2_base.yaml "$@"
