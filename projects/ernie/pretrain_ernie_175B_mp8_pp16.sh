#!/usr/bin/env bash
# ERNIE 175B-class mp8 x pp16 1F1B pretrain (reference
# projects/ernie/pretrain_ernie_base_175B_mp8_pp16.sh); run on every host
# with PFX_COORDINATOR_ADDRESS set
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/ernie/pretrain_ernie_175B_mp8_pp16.yaml "$@"
