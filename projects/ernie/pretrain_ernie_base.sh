#!/usr/bin/env bash
# ERNIE-base MLM+NSP pretrain (reference projects/ernie/pretrain_ernie_base.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/ernie/pretrain_ernie_base.yaml "$@"
