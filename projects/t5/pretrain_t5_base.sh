#!/usr/bin/env bash
# T5-base span-corruption pretrain (beyond the reference: it ships T5 as a
# model library only; here the family trains end-to-end)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/t5/pretrain_t5_base.yaml "$@"
