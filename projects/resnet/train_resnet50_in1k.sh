#!/usr/bin/env bash
# ResNet50 ImageNet-1k supervised training
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/resnet/resnet50_in1k_1n8c.yaml "$@"
