#!/usr/bin/env bash
# HelixFold (AlphaFold2-style) initial training with DAP/BP over the sep axis
# (reference projects/protein_folding/helixfold/README)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/protein/helixfold_initial.yaml "$@"
