#!/usr/bin/env bash
# ViT-B/16 ImageNet-1k pretrain (reference projects/vit/ViT_base_patch16_224_pt_in1k_1n8c.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/vit/ViT_base_patch16_224_pt_in1k_1n8c_dp.yaml "$@"
