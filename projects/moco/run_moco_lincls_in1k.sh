#!/usr/bin/env bash
# Linear-classification probe on a frozen MoCo backbone (reference run_mocov*_lincls_in1k.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/moco/moco_lincls_in1k_1n8c.yaml "$@"
