#!/usr/bin/env bash
# MoCo v2 contrastive pretrain (reference projects/moco/run_mocov2_pretrain_in1k.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/moco/mocov2_pt_in1k_1n8c.yaml "$@"
