#!/usr/bin/env bash
# MoCo v1 contrastive pretrain (reference projects/moco/run_mocov1_pretrain_in1k.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/moco/mocov1_pt_in1k_1n8c.yaml "$@"
