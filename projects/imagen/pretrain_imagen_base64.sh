#!/usr/bin/env bash
# Imagen base 64x64 text-to-image diffusion pretrain (reference
# projects/imagen/run_imagen_text2im_64x64.sh)
set -e
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/imagen/imagen_text2im_64_base.yaml "$@"
