# Developer entry points

.PHONY: lint test-fast test-mid test-std test-all test-fault test-serve-drill test-data-drill test-obs test-paged test-prefix test-spec test-trace test-router test-elastic test-disagg test-parallel test-fleet-obs test-decode-overlap test-kv-tier test-tenant test-ha test-goodput bench bench-check

# stdlib AST lint gate (no ruff/flake8 in the image): unused imports,
# bare except, eval/exec, tabs, trailing whitespace, mutable defaults
lint:
	python tools/lint.py

# <5-min gate on a 1-core CPU-mesh box: units + core model/sharding + one
# pipeline parity case
FAST_FILES = tests/test_config.py tests/test_tokenizer.py tests/test_data.py \
             tests/test_optims.py tests/test_rigid.py tests/test_glue.py \
             tests/test_lm_eval.py tests/test_configs_launch.py \
             tests/test_gpt_model.py tests/test_mesh_sharding.py \
             tests/test_serving.py tests/test_request_queue.py \
             tests/test_chunked_ce.py tests/test_lint.py \
             tests/test_telemetry.py tests/test_tracing.py \
             tests/test_bench_helpers.py tests/test_bench_cases.py \
             tests/test_router.py tests/test_controller.py \
             tests/test_prefix_cache.py tests/test_shard_map_compat.py \
             tests/test_fleet_obs.py tests/test_tenancy.py \
             tests/test_fleet_journal.py

# lint runs inside the gate via tests/test_lint.py::test_repo_is_clean
test-fast:
	python -m pytest $(FAST_FILES) -q -m "not slow" -x
	python -m pytest "tests/test_pipeline.py::test_pipeline_1f1b_train_loss_and_grads[2-extra1-4-1]" -q

# mid tier: fast gate + the per-family model/engine suites, still skipping
# the heaviest compile files — the iteration loop for model-family work
# (~4 min warm on 1 core; cold compiles land in tests/.jax_cache, so the
# first run of any tier pays ~3x once)
MID_EXTRA = tests/test_engine.py tests/test_generation.py tests/test_moe.py \
            tests/test_ernie.py tests/test_t5.py tests/test_vit.py \
            tests/test_vision.py tests/test_auto_tune.py tests/test_check.py \
            tests/test_compression_profiler.py tests/test_hf_convert.py \
            tests/test_long_context.py tests/test_paged_cache.py \
            tests/test_continuous_batching.py tests/test_speculative.py \
            tests/test_kv_handoff.py tests/test_tenant_sched.py
test-mid:
	python -m pytest $(FAST_FILES) $(MID_EXTRA) -q -m "not slow" -x
	python -m pytest "tests/test_pipeline.py::test_pipeline_1f1b_train_loss_and_grads[2-extra1-4-1]" -q
	# flash kernel parity (split/fused schedules, bf16 accuracy, config
	# plumb) is a default-gate safety net despite the file's slow mark
	# (~25s warm in interpret mode)
	python -m pytest tests/test_flash_attention.py -q

# standard suite: everything except Pallas interpret-mode / big-compile
# files (marked slow)
test-std:
	python -m pytest tests/ -q -m "not slow"

test-all:
	python -m pytest tests/ -q

# fault-tolerance drills: PFX_FAULT crash-resume parity through the real
# CLI + the resilience/checkpoint-integrity units (docs/fault_tolerance.md)
test-fault:
	python -m pytest tests/test_fault_tolerance.py tests/test_fault_injection.py -q

# serving robustness drills: request-queue units + subprocess traffic
# drills (flood / SIGTERM drain / gen_crash / gen_hang watchdog) through
# the real tools/serve.py CLI (docs/serving.md runbook)
test-serve-drill:
	python -m pytest tests/test_request_queue.py tests/test_serve_drills.py -q

# data-pipeline drills: loader/sampler/index-cache units + subprocess
# fault drills (corrupt_sample skip budget / io_stall watchdog / index-map
# build race / rollback-rewind replay) through the real tools/train.py CLI
# (docs/data_pipeline.md runbook)
test-data-drill:
	python -m pytest tests/test_data.py tests/test_data_drills.py "tests/test_fault_injection.py::test_nan_rollback_rewind_replay_parity" -q

# observability gate: telemetry registry/span/MFU/flight-recorder units,
# the training observatory (per-layer-group stats, non-finite provenance,
# memory watermarks, compile watcher, tools/report.py), the serving
# metrics surfaces, and the Prometheus-exposition + flight recorder
# drills through the real tools/serve.py CLI (docs/observability.md)
test-obs:
	python -m pytest tests/test_telemetry.py tests/test_model_stats.py tests/test_serving.py tests/test_request_queue.py -q -m "not slow"
	python -m pytest tests/test_serve_drills.py -q -k "metrics or gen_hang"

# deep-dive tracing gate: trace-context/buffer/export + SLO units, the
# decision-log replay agreement suite, and the /debug + SLO-breach
# drills through the real tools/serve.py CLI (docs/observability.md
# "Deep-dive tracing" + the runbook)
test-trace:
	python -m pytest tests/test_tracing.py tests/test_telemetry.py -q -m "not slow"
	python -m pytest tests/test_serve_drills.py -q -k "metrics or slo"
	python -m pytest "tests/test_paged_drills.py::test_continuous_mid_decode_eviction_frees_blocks_token_identical" -q

# fleet-observability gate: wall-clock-anchor/span-summary/federation/
# fleet-report units, the cross-process stitch + federation-agreement
# drill through the real router+prefill+decode CLIs, and the lint
# E10/E11/E12 tables (docs/observability.md "Fleet tracing" +
# "Fleet metrics federation")
test-fleet-obs:
	python -m pytest tests/test_fleet_obs.py tests/test_tracing.py tests/test_lint.py -q -m "not slow"
	python -m pytest tests/test_fleet_obs_drills.py -q
	python tools/lint.py

# paged-serving gate: block allocator + paged-attention kernel units,
# the continuous-batching engine/scheduler parity + eviction suite, and
# the subprocess drills through tools/serve.py --scheduler continuous
# (docs/serving.md scheduler section; drills reuse the warm
# tests/.jax_cache like every other drill family)
test-paged:
	python -m pytest tests/test_paged_cache.py tests/test_continuous_batching.py tests/test_paged_drills.py -q

# dispatch-ahead decode overlap gate (docs/decode_path.md
# "Dispatch-ahead decode"): the decision-log replay-equality +
# mid-overlap ArenaReset units, then the two-process serve+router drill
# asserting a streamed /generate arrives in >= 2 flushes with monotone
# token indices and an intact stitched trace
test-decode-overlap:
	python -m pytest tests/test_decode_overlap.py -q

# shared-prefix KV reuse gate: refcount/radix-index/COW host units, the
# engine-level reuse + chunked-prefill parity suite (prefix hits, COW
# divergence, eviction-under-pressure, ArenaReset index rebuild, the
# decision-log replay contract), the prefix CLI drill, and the
# prefix-heavy decode-bench A/B contract (docs/serving.md "Prefix
# cache")
test-prefix:
	python -m pytest tests/test_prefix_cache.py -q
	python -m pytest tests/test_continuous_batching.py -q -k "prefix or chunked or cow or accounting or arena_reset or pressure"
	python -m pytest "tests/test_paged_drills.py::test_prefix_cache_and_chunked_prefill_through_real_cli" -q
	python -m pytest tests/test_bench_contract.py -q -k "decode_happy"

# fleet KV-durability gate: the host-RAM spill tier (store units,
# spill -> readmit parity, spill_corrupt degrade-to-recompute,
# ArenaReset invalidation, exact decision-log replay), peer-to-peer
# prefix migration (export/adopt cross-engine, torn-payload whole
# rejection, the PFXH1 truncation fuzz), prefix-affinity routing units,
# and the slow+fault rolling-drain CLI drills — migrate-under-stall
# adoption and the wedged-receiver drain-deadline floor — plus the
# spill decode-bench A/B contract (docs/serving.md "KV lifecycle")
test-kv-tier:
	python -m pytest tests/test_kv_tier.py tests/test_kv_handoff.py -q
	python -m pytest tests/test_bench_contract.py -q -k "decode_happy"

# serving goodput-ledger gate: time/token ledger closure units (exact
# token closure + <=1% time closure under a seeded adversarial mix),
# the fault-marked closure + fleet-profiling drills through the real
# serve/router CLIs, the train-ledger record surface, and the
# dispatch-ahead goodput_frac bench contract (docs/observability.md
# "Goodput ledger" + "On-demand profiling")
test-goodput:
	python -m pytest tests/test_goodput.py tests/test_tracing.py -q -m "not slow"
	python -m pytest "tests/test_engine.py::test_metrics_file_stream" -q
	python -m pytest tests/test_bench_contract.py -q -k "decode_happy"
	python tools/lint.py

# multi-tenant isolation gate: tenancy units (quotas/DRR/label cap/header
# propagation), scheduler fairness + preemption parity, then the real-CLI
# drills (two-tenant flood, preempt-storm token identity, SSE honest
# close) — docs/serving.md "Multi-tenant isolation"
test-tenant:
	python -m pytest tests/test_tenancy.py tests/test_tenant_sched.py -q
	python -m pytest tests/test_tenant_drills.py -q

# speculative-decoding + KV-quant gate: drafter/accept units, greedy
# parity (contiguous + paged, incl. full-rejection iterations), int8
# kernel tolerance + arena-bytes halving, the sampled
# distribution-preservation statistical test, serving-config routing,
# and the spec/kvint8 decode-bench A/B contract (docs/decode_path.md)
test-spec:
	python -m pytest tests/test_speculative.py -q
	python -m pytest tests/test_bench_contract.py -q -k "decode"

# multi-host router gate: router-core units against stub replicas (no
# model), the KV-handoff codec + export/adopt parity suite, and the
# multi-process drills — rolling drain under flood, SIGKILL failover,
# disaggregated prefill/decode parity — through the real tools/serve.py
# + tools/router.py CLIs (docs/serving.md "Multi-host serving")
test-router:
	python -m pytest tests/test_router.py tests/test_kv_handoff.py tests/test_router_drills.py -q

# elastic-control-plane gate: controller/supervisor units against stub
# cores + injected clocks, the router-core remote-drain/auth/rejoin
# units, and the chaos drills through the real CLIs — authenticated
# remote drain + /debug gating, crash-loop quarantine within the flap
# budget, SIGKILL-under-flood supervisor restart + router re-admission,
# SLO-breach scale-up + burn recovery (docs/serving.md "Elastic control
# plane")
test-elastic:
	python -m pytest tests/test_controller.py tests/test_router.py tests/test_elastic_drills.py -q

# control-plane survivability: fleet-journal units (torn-tail fuzz,
# replay exact-fold, adoption identity, tenant bucket restore) + the
# SIGKILL-the-router / journal-loss chaos drills
# (docs/serving.md "Control-plane recovery")
test-ha:
	python -m pytest tests/test_fleet_journal.py -q
	python -m pytest tests/test_ha_drills.py -q

# disaggregated-fabric gate: role-aware pool-supervision units +
# handoff-failover/direct-transfer units (stub replicas, no model), the
# prefix-on-prefill-export parity suite, the PR 10 proxy parity drill,
# and the chaos drills through the real CLIs — direct byte-bypass +
# transport parity, handoff_drop/adopt_crash failover, SIGKILL of both
# pool corpses under supervised flood (docs/serving.md "Disaggregated
# operations")
test-disagg:
	python -m pytest tests/test_controller.py tests/test_router.py tests/test_kv_handoff.py -q
	python -m pytest tests/test_disagg_drills.py -q
	python -m pytest "tests/test_router_drills.py::test_disaggregated_prefill_decode_parity_via_router" -q

# multi-chip parallelism gate: the shard_map-port surface in one run —
# compat-adapter units, 1F1B pipeline parity (loss+grads, virtual
# stages, bf16), ring/zigzag long-context parity (incl. the nested
# pp2 x sep2 subprocess case), sharding-rule/ZeRO families, the
# six-layout engine parity sweep, the 2-process jax.distributed e2e,
# and every golden-doc walkthrough incl. the slow-marked ones
# (docs/parallelism.md)
test-parallel:
	python -m pytest tests/test_shard_map_compat.py tests/test_pipeline.py tests/test_long_context.py tests/test_mesh_sharding.py tests/test_distributed.py -q
	python -m pytest "tests/test_engine.py::test_layout_loss_parity_first_step" -q
	python -m pytest tests/test_golden_docs.py -q

bench:
	python benchmarks/run_benchmark.py

# bench-trajectory gate: newest two BENCH_r*.json compared, >10%
# regression of any shared metric fails; backend-unreachable rows are
# skipped loudly (tools/bench_check.py)
bench-check:
	python tools/bench_check.py
