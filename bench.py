"""Benchmark: GPT-345M pretrain throughput (tokens/s) on the local device(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: reference PaddleFleetX GPT-345M single-card pretrain ~16,260
tokens/s on 1x V100-32G (BASELINE.md / projects/gpt/docs/single_card.md:40-49).

Contract hardening (round 4): the benchmark itself runs in a CHILD process;
the parent is pure Python (no jax import), so it stays responsive to the
driver's SIGTERM no matter what the axon tunnel does, and it ALWAYS emits
the one JSON line — the child's real number, or an honest value:0.0 — before
exiting.  Round 3's BENCH was rc=124 with no output because the in-process
probe window (40 min) overran the driver's capture timeout.
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TOKENS_PER_S = 16260.0
METRIC = "gpt345m_pretrain_throughput_per_chip"

# long-context ring-attention row (shard_map-port PR): seq >= 4096 through
# parallel/ring_attention.py with the zigzag causal layout on a sep-axis
# ring over every local device.  No published reference number exists (the
# reference has no context-parallel path at all — SURVEY §5.7, max trained
# context 1024), so the row reports an absolute rate with vs_baseline null.
RING_METRIC = "ring_attention_seq4096_throughput_per_chip"


def _backend_alive(timeout_s: float = None) -> bool:
    """Probe jax backend init in a subprocess: the axon TPU tunnel can hang
    indefinitely when the chip is unreachable, and merely importing-and-
    calling jax.devices() in-process would wedge the whole benchmark."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 90))
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def model_flops_per_token(hidden: int, layers: int, vocab: int, seq: int) -> float:
    """Model FLOPs per token, fwd + 2x bwd, WITH the seq-dependent
    attention-score term (causal attention counted at half the score
    matrix) — kept for benchmarks/bench_extra.py's detailed view.  The
    headline row's ``mfu``/``tokens_per_sec`` fields instead come from
    the repo-wide analytic 6·N estimator
    (paddlefleetx_tpu.utils.telemetry.model_flops_per_token), the same
    one the engine's step records and bench_decode.py use, so every
    BENCH_*.json trajectory is normalized by ONE definition."""
    h, L, v = int(hidden), int(layers), int(vocab)
    ffn = 4 * h
    per = L * (2 * h * 3 * h + 2 * seq * h + 2 * h * h + 4 * h * ffn) + 2 * h * v
    return per * 3.0


def host_fence(out):
    """Wait for ALL device work behind ``out`` by fetching ONE element.

    The axon runtime's ``jax.block_until_ready`` has been observed
    returning while device work is still pending (see the loss host-fetch
    in _child below; the 2026-07-31 19:00Z decode rows showing 19M-160M
    "tok/s" were this exact artifact) — a device->host copy is the only
    fence that cannot lie.  The one-element slice depends on the full
    output buffer, so the 2-4 byte transfer completes only after the
    whole computation; shared by bench_decode.py and kernel_bench.py so
    there is exactly one audited fence implementation."""
    import jax
    import numpy as np

    leaf = jax.tree_util.tree_leaves(out)[0]
    return np.asarray(leaf.ravel()[:1])


@contextlib.contextmanager
def knob_env(knobs):
    """Context manager: set trace-time env knobs (PFX_FLASH_*/PFX_DECODE_*)
    for a bench section, clearing jax's trace caches on BOTH edges, and
    restore the prior values (pop if previously unset) on exit — even on
    error.  The single audited copy of the save/mutate/restore hygiene
    (ADVICE r5: a sweep that leaves its last combo exported poisons any
    in-process caller that traces afterwards); child-process only, like
    host_fence — the parent never imports jax (jax is imported lazily in
    the generator body, which only runs when a child enters the cm)."""
    import jax

    saved = {k: os.environ.get(k) for k in knobs}
    try:
        os.environ.update({k: str(v) for k, v in knobs.items()})
        jax.clear_caches()  # env knobs are read at trace time
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        jax.clear_caches()


def wait_for_backend() -> bool:
    """Re-poll the TPU backend inside a bounded window.  Default is 120 s:
    short enough to stay well inside the driver's capture budget (round 3
    lost the whole artifact to a 40-min window), long enough to ride out a
    brief tunnel blip.  Set BENCH_PROBE_WINDOW_S higher for patient manual
    runs when the tunnel is flapping."""
    window_s = float(os.environ.get("BENCH_PROBE_WINDOW_S", 120))
    deadline = time.time() + window_s
    while True:
        if _backend_alive():
            return True
        if time.time() >= deadline:
            return False
        time.sleep(min(30, max(1, deadline - time.time())))


# the one fixed shape every cpu-fallback lap runs (identical across laps
# so `make bench-check` compares like with like; finishes in well under a
# minute on one core where the real 345M shape cannot)
CPU_FALLBACK_SHAPE = {
    "BENCH_VOCAB": "8192",
    "BENCH_HIDDEN": "256",
    "BENCH_LAYERS": "4",
    "BENCH_HEADS": "8",
    "BENCH_SEQ": "256",
    "BENCH_BATCH": "4",
    "BENCH_STEPS": "4",
}


def ensure_backend_or_fallback() -> str:
    """Dead-backend fallback (ROADMAP open item: BENCH_r02..r05 were
    four flat-zero "tpu backend unreachable" laps after r01 measured a
    real number — four laps of noise that `make bench-check` could only
    skip).  When the default/pinned TPU backend does not answer within
    the probe window, repoint the child at the CPU backend and RUN the
    benchmark there: an honest row on the backend that exists (the row
    carries ``platform`` so tools/bench_check.py compares like with
    like) beats a value-0.0 placeholder.  Returns the fallback note
    ("" when no fallback was needed).  Child-process only (the parent
    never imports jax)."""
    platform = os.environ.get("PFX_PLATFORM", "").lower()
    if platform not in ("", "tpu", "axon"):
        return ""  # explicitly pinned elsewhere (cpu smoke): no probe
    if wait_for_backend():
        return ""
    os.environ["PFX_PLATFORM"] = "cpu"
    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()
    # the REAL 345M shape cannot finish on one CPU core inside the
    # parent's BENCH_DEADLINE_S window (compile alone is minutes) — the
    # fallback would then time out into the exact value-0.0 placeholder
    # it exists to eliminate.  Pin ONE fixed small shape for every
    # fallback lap (setdefault: explicit operator knobs still win), so
    # cpu laps are comparable with EACH OTHER and finish in seconds;
    # the row's unit names the shrink so it never reads as chip-scale.
    for knob, val in CPU_FALLBACK_SHAPE.items():
        os.environ.setdefault(knob, val)
    note = (
        "bench: tpu backend unreachable after the probe window; "
        "falling back to the cpu backend with the fixed fallback shape "
        '— the row is labeled platform="cpu" and is only compared '
        "against other cpu laps"
    )
    print(note, file=sys.stderr, flush=True)
    return note


def _honest_row(reason: str) -> dict:
    return {
        "metric": METRIC,
        "value": 0.0,
        "unit": f"tokens/s/chip ({reason})",
        "vs_baseline": 0.0,
    }


def _honest_ring_row(reason: str) -> dict:
    # vs_baseline null: no published reference number for long-context CP
    return {
        "metric": RING_METRIC,
        "value": 0.0,
        "unit": f"tokens/s/chip ({reason})",
        "vs_baseline": None,
    }


# the ring case's cpu-fallback shrink: the SEQUENCE stays >= 4096 (that is
# the case — long context), only heads/dim/steps shrink so a 1-core lap
# finishes inside the deadline; identical across laps for bench_check
RING_CPU_FALLBACK_SHAPE = {
    "BENCH_RING_HEADS": "4",
    "BENCH_RING_DIM": "32",
    "BENCH_RING_STEPS": "2",
}


# ----------------------------------------------------------------------
# Parent harness: spawn the child benchmark, relay its JSON lines, and
# guarantee the expected metric rows come out even on SIGTERM / deadline.
# Shared by bench.py and benchmarks/bench_extra.py (which imports it).
def run_child_with_honest_fallback(
    child_argv, deadline_s, emit_missing, env=None, on_row=None
) -> int:
    """Run `child_argv`, relaying its stdout.  `emit_missing(seen, reason)`
    is called with the set of metric names the child DID print whenever the
    run ends abnormally (signal, deadline, bad exit, no output) and must
    print honest fallback rows for everything still missing.  The parent
    never imports jax, so it stays responsive to the driver's SIGTERM no
    matter what the axon tunnel does.  ``on_row`` (optional) sees every
    parsed metric row — bench.py's parent uses it to learn the first
    child's fallback platform so the ring child can skip a duplicate
    dead-TPU probe window."""
    seen: set = set()

    child = subprocess.Popen(child_argv, stdout=subprocess.PIPE, text=True, env=env)

    def _reader():
        # relay the child's stdout as it streams; remember metric rows
        for line in child.stdout:
            line = line.rstrip("\n")
            if not line:
                continue
            try:
                row = json.loads(line)
                if isinstance(row, dict) and "metric" in row:
                    seen.add(row["metric"])
                    if on_row is not None:
                        on_row(row)
            except ValueError:
                pass
            print(line, flush=True)

    t = threading.Thread(target=_reader, daemon=True)
    t.start()

    def _quiesce():
        # emission is about to start: a late follow-up signal (driver
        # kill-then-escalate) must not re-enter the handler and print
        # duplicate fallback rows
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGINT, signal.SIG_IGN)

    def _bail(reason: str) -> int:
        _quiesce()
        try:
            child.kill()
        except OSError:
            pass
        # drain the pipe BEFORE deciding what's missing: the child may have
        # printed its real row in the same instant — emitting a fallback on
        # top would break the one-line-per-metric contract
        t.join(timeout=10)
        emit_missing(seen, reason)
        return 0

    def _on_term(signum, frame):
        # the driver's clock ran out: emit the honest line(s) NOW and exit 0
        # so the capture parses (a propagated kill would record rc!=0,
        # parsed:null — round 3's failure mode)
        _bail(f"killed by signal {signum} before completion")
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    start = time.time()
    while True:
        rc = child.poll()
        if rc is not None:
            _quiesce()
            t.join(timeout=10)
            emit_missing(seen, f"child exited rc={rc} with no JSON")
            return 0
        if time.time() - start > deadline_s:
            return _bail(f"self-deadline {deadline_s:.0f}s exceeded")
        time.sleep(0.5)


def _parent() -> int:
    def emit_missing(seen, reason):
        if METRIC not in seen:
            print(json.dumps(_honest_row(reason)), flush=True)

    child_platform = {}

    def on_row(row):
        if row.get("platform"):
            child_platform["seen"] = row["platform"]

    rc = run_child_with_honest_fallback(
        [sys.executable, os.path.abspath(__file__), "--child"],
        float(os.environ.get("BENCH_DEADLINE_S", 600)),
        emit_missing,
        on_row=on_row,
    )

    if os.environ.get("BENCH_RING", "1") != "1":
        return rc

    def emit_missing_ring(seen, reason):
        if RING_METRIC not in seen:
            print(json.dumps(_honest_ring_row(reason)), flush=True)

    # if the headline child already fell back to cpu (dead TPU), pin the
    # ring child there too so it skips a second full probe window —
    # ensure_backend_or_fallback never probes an explicitly-pinned non-TPU
    # platform
    ring_env = None
    if child_platform.get("seen") == "cpu" and os.environ.get(
        "PFX_PLATFORM", ""
    ).lower() in ("", "tpu", "axon"):
        ring_env = dict(os.environ)
        ring_env["PFX_PLATFORM"] = "cpu"

    rc_ring = run_child_with_honest_fallback(
        [sys.executable, os.path.abspath(__file__), "--child-ring"],
        float(os.environ.get("BENCH_RING_DEADLINE_S", 600)),
        emit_missing_ring,
        env=ring_env,
    )
    return rc or rc_ring


# ----------------------------------------------------------------------
def _child() -> None:
    # honor PFX_PLATFORM before ANY backend init (the axon sitecustomize
    # overrides a bare JAX_PLATFORMS env var) so the probe gate below and
    # the backend the benchmark actually initializes agree
    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()

    # probe unless explicitly pinned to a non-TPU platform (a pinned
    # PFX_PLATFORM=tpu must still be guarded — it is the hang case);
    # an unreachable TPU now falls back to benchmarking the backend
    # that EXISTS instead of emitting a value-0.0 placeholder lap
    fallback = ensure_backend_or_fallback()

    import jax
    import numpy as np

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    n_dev = jax.device_count()
    batch = int(os.environ.get("BENCH_BATCH", 16)) * n_dev
    seq = int(os.environ.get("BENCH_SEQ", 1024))
    steps = int(os.environ.get("BENCH_STEPS", 10))

    cfg = AttrDict.from_nested(
        {
            "Global": {
                "global_batch_size": batch,
                "micro_batch_size": batch // n_dev,
                "seed": 1024,
                # hardware RNG for dropout masks: ~15% step-time win over
                # threefry on TPU, no effect on loss statistics
                "prng_impl": os.environ.get("BENCH_PRNG", "rbg"),
            },
            "Engine": {
                "max_steps": steps,
                "eval_freq": 0,
                "logging_freq": 10**9,
                "mix_precision": {"enable": True, "dtype": "bfloat16"},
                "save_load": {"save_steps": 0},
            },
            "Model": {
                "module": "GPTModule",
                # BENCH_* shrink knobs are for CI smoke of the bench
                # contract only; the real case is the reference 345M shape
                "vocab_size": int(os.environ.get("BENCH_VOCAB", 50304)),
                "hidden_size": int(os.environ.get("BENCH_HIDDEN", 1024)),
                "num_layers": int(os.environ.get("BENCH_LAYERS", 24)),
                "num_attention_heads": int(os.environ.get("BENCH_HEADS", 16)),
                "max_position_embeddings": seq,
                "hidden_dropout_prob": float(os.environ.get("BENCH_DROPOUT", 0.1)),
                "attention_probs_dropout_prob": float(os.environ.get("BENCH_DROPOUT", 0.1)),
                "attn_impl": os.environ.get("BENCH_ATTN", "flash"),
                # 16GB v5e HBM can't hold the full activation set (37G), but
                # blanket full-layer remat wastes a whole extra forward;
                # "selective" saves the measured-best named set (qkv +
                # attn_out + attn_lse) and recomputes the cheap rest
                "use_recompute": os.environ.get("BENCH_RECOMPUTE", "1") == "1",
                "recompute_granularity": os.environ.get("BENCH_REMAT", "selective"),
                "use_fused_ln": os.environ.get("BENCH_FUSED_LN", "1") == "1",
                # streams the vocab through the CE so the fp32 logits buffer
                # never materializes (ops/chunked_ce.py) — try with bigger
                # BENCH_BATCH once enabled
                "use_chunked_ce": os.environ.get("BENCH_CHUNKED_CE", "0") == "1",
                "scan_unroll": int(os.environ.get("BENCH_SCAN_UNROLL", 1)),
                # measured on-chip 2026-07-31 via the end-to-end headline
                # A/B (the trustworthy loss-host-fetch timing): 34,940
                # tok/s with fused/512 vs 33,757 with the old split/256 —
                # +3.5%.  Fall back to the auto block ladder when 512
                # does not divide the (override) seq, so shrink-knob CI
                # smokes and odd seqs keep flash support.
                "flash_block": int(os.environ.get(
                    "BENCH_FLASH_BLOCK", 512 if seq % 512 == 0 else 0)),
                "flash_bwd": os.environ.get("BENCH_FLASH_BWD", "fused"),
            },
            "Distributed": {},
            "Optimizer": {
                "name": "FusedAdamW",
                "weight_decay": 0.01,
                "beta1": 0.9,
                "beta2": 0.95,
                "lr": {"name": "Constant", "learning_rate": 1e-4},
                "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
            },
        }
    )
    cfg = process_configs(cfg, num_devices=n_dev)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    rng = np.random.default_rng(0)
    vocab = int(cfg.Model.vocab_size)
    host_batch = {
        "tokens": rng.integers(0, vocab, (batch, seq)).astype(np.int64),
        "labels": rng.integers(0, vocab, (batch, seq)).astype(np.int64),
        "loss_mask": np.ones((batch, seq), np.float32),
        "position_ids": np.tile(np.arange(seq), (batch, 1)),
    }

    with mesh:
        engine = Engine(cfg, module, mesh)
        dev_batch = engine._put_batch(host_batch)
        # warmup (compile)
        for _ in range(3):
            engine.state, m = engine.train_step(engine.state, dev_batch)
        float(m["loss"])  # host fetch: drains the warmup chain (see below)
        t0 = time.time()
        for _ in range(steps):
            engine.state, m = engine.train_step(engine.state, dev_batch)
        # force a device->host fetch of the final loss: on the axon remote
        # runtime block_until_ready alone has been observed returning while
        # the donated-state chain is still in flight (timing would then
        # measure dispatch, not execution)
        final_loss = float(m["loss"])
        dt = time.time() - t0

    if not np.isfinite(final_loss):
        # same honest-failure contract as the unreachable-backend path:
        # always ONE parseable JSON line, never a traceback
        print(json.dumps(_honest_row(f"non-finite bench loss {final_loss}")), flush=True)
        return

    tokens_per_s = batch * seq * steps / dt

    # hardware normalization via the repo-wide estimator (6·N per token)
    # and per-device-kind peak table — BENCH_PEAK_TFLOPS / PFX_PEAK_FLOPS
    # override, in that order (docs/observability.md)
    from paddlefleetx_tpu.utils import telemetry

    mc = cfg.Model
    flops_tok = telemetry.model_flops_per_token(
        vocab_size=mc.vocab_size, hidden_size=mc.hidden_size,
        num_layers=mc.num_layers,
    )
    env_peak = os.environ.get("BENCH_PEAK_TFLOPS")
    peak = (float(env_peak) * 1e12 if env_peak
            else telemetry.peak_flops(default=197e12))  # v5e bf16
    mfu = tokens_per_s / n_dev * flops_tok / peak

    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(tokens_per_s / n_dev, 1),
                # the fallback suffix keeps a shrunk-shape cpu lap from
                # ever reading as chip-scale evidence (value > 0, so
                # bench_check still compares it against other cpu laps)
                "unit": ("tokens/s/chip (cpu-fallback shape)" if fallback
                         else "tokens/s/chip"),
                "vs_baseline": round(tokens_per_s / n_dev / BASELINE_TOKENS_PER_S, 3),
                "tokens_per_sec": round(tokens_per_s, 1),
                # 6 digits: CPU smoke shapes under forced multi-device
                # hosts land near 1e-5 and must not round to a dishonest 0
                "mfu": round(mfu, 6),
                # CPU smoke rows must never read as chip evidence
                "platform": jax.default_backend(),
            }
        ),
        flush=True,
    )


def _child_ring() -> None:
    """Long-context ring-attention case: fwd+bwd of
    parallel/ring_attention.py at BENCH_RING_SEQ (>= 4096) rows, zigzag
    causal layout, K/V rotating a sep-axis ring over every local device.

    Multi-device gated: a ring of one is dense attention, not the ported
    collective path — a 1-device backend emits an honest platform-labeled
    zero row naming the gate instead of a dishonest dense number.  On an
    unreachable TPU the case follows the ensure_backend_or_fallback
    contract: repoint at the cpu backend, force a virtual 4-device host
    (the flag must land before jax initializes), shrink heads/dim — never
    the sequence — and label the row."""
    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()
    fallback = ensure_backend_or_fallback()
    platform = os.environ.get("PFX_PLATFORM", "").lower()
    if platform == "cpu":
        # a cpu lap (fallback or pinned smoke) has one real device: the
        # ring needs a sep axis, so force virtual host devices BEFORE the
        # first in-process jax import (no-op when the caller already did)
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            )
        for knob, val in RING_CPU_FALLBACK_SHAPE.items():
            os.environ.setdefault(knob, val)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
    from paddlefleetx_tpu.parallel.ring_attention import (
        ring_attention,
        zigzag_permutation,
    )

    n_dev = jax.device_count()
    if n_dev < 2:
        print(
            json.dumps(
                {
                    **_honest_ring_row("needs >= 2 devices for the sep ring"),
                    "platform": jax.default_backend(),
                }
            ),
            flush=True,
        )
        return

    seq = int(os.environ.get("BENCH_RING_SEQ", 4096))
    heads = int(os.environ.get("BENCH_RING_HEADS", 16))
    dim = int(os.environ.get("BENCH_RING_DIM", 64))
    batch = int(os.environ.get("BENCH_RING_BATCH", 1))
    steps = int(os.environ.get("BENCH_RING_STEPS", 4))
    chunk = int(os.environ.get("BENCH_RING_CHUNK", 1024))
    # ring = every local device on the sep axis; zigzag needs 2*ring | seq
    ring = n_dev
    while ring > 1 and seq % (2 * ring):
        ring //= 2
    if ring < 2:
        print(
            json.dumps(
                {
                    **_honest_ring_row(
                        f"no ring >= 2 divides seq {seq} on {n_dev} devices"
                    ),
                    "platform": jax.default_backend(),
                }
            ),
            flush=True,
        )
        return
    dtype = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16

    mesh = build_mesh(MeshConfig(sep_degree=ring), jax.devices()[:ring])
    key = jax.random.key(0)
    q = jax.random.normal(key, (batch, seq, heads, dim), dtype)
    k = jax.random.normal(jax.random.fold_in(key, 1), q.shape, dtype)
    v = jax.random.normal(jax.random.fold_in(key, 2), q.shape, dtype)
    perm = zigzag_permutation(seq, ring)
    qz, kz, vz = q[:, perm], k[:, perm], v[:, perm]

    def loss(q, k, v):
        out = ring_attention(
            q, k, v, mesh, causal=True, chunk_k=chunk, positions=perm
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    step = jax.jit(jax.grad(loss, (0, 1, 2)))
    with mesh:
        host_fence(step(qz, kz, vz))  # compile + warmup
        t0 = time.time()
        for _ in range(steps):
            grads = step(qz, kz, vz)
        host_fence(grads)
        dt = time.time() - t0

    tokens_per_s = batch * seq * steps / dt
    print(
        json.dumps(
            {
                "metric": RING_METRIC,
                "value": round(tokens_per_s / ring, 1),
                "unit": (
                    "tokens/s/chip (cpu-fallback shape)"
                    if fallback or jax.default_backend() == "cpu"
                    else "tokens/s/chip"
                ),
                "vs_baseline": None,
                "platform": jax.default_backend(),
                "seq": seq,
                "ring": ring,
                "heads": heads,
                "note": (
                    "fwd+bwd ring attention (zigzag causal layout), "
                    "K/V rotating the sep ring; no published reference "
                    "number (the reference has no context-parallel path)"
                ),
            }
        ),
        flush=True,
    )


def main():
    if "--child-ring" in sys.argv:
        _child_ring()
        return
    if "--child" in sys.argv:
        _child()
        return
    sys.exit(_parent())


if __name__ == "__main__":
    main()
