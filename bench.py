"""Benchmark: GPT-345M pretrain throughput (tokens/s) on the local device(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: reference PaddleFleetX GPT-345M single-card pretrain ~16,260
tokens/s on 1x V100-32G (BASELINE.md / projects/gpt/docs/single_card.md:40-49).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TOKENS_PER_S = 16260.0


def _backend_alive(timeout_s: int = 150) -> bool:
    """Probe jax backend init in a subprocess: the axon TPU tunnel can hang
    indefinitely when the chip is unreachable, and merely importing-and-
    calling jax.devices() in-process would wedge the whole benchmark."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def model_flops_per_token(hidden: int, layers: int, vocab: int, seq: int) -> float:
    """Model FLOPs per token, fwd + 2x bwd (standard MFU convention, no
    remat extra; causal attention counted at half the score matrix).
    Shared by bench.py and benchmarks/bench_extra.py so the two MFU
    numbers stay comparable."""
    h, L, v = int(hidden), int(layers), int(vocab)
    ffn = 4 * h
    per = L * (2 * h * 3 * h + 2 * seq * h + 2 * h * h + 4 * h * ffn) + 2 * h * v
    return per * 3.0


def wait_for_backend() -> bool:
    """Re-poll the TPU backend inside a bounded window (default 40 min,
    BENCH_PROBE_WINDOW_S to override).  The axon tunnel has been observed
    dropping for minutes-to-hours at a time, and round 2's driver-captured
    number was lost to exactly such an outage — a transient outage inside
    the driver's run window must not record 0.0 when patience would have
    produced a real number."""
    window_s = float(os.environ.get("BENCH_PROBE_WINDOW_S", 2400))
    deadline = time.time() + window_s
    while True:
        if _backend_alive():
            return True
        if time.time() >= deadline:
            return False
        time.sleep(min(60, max(1, deadline - time.time())))


def main():
    # honor PFX_PLATFORM before ANY backend init (the axon sitecustomize
    # overrides a bare JAX_PLATFORMS env var) so the probe gate below and
    # the backend the benchmark actually initializes agree
    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()

    # probe unless explicitly pinned to a non-TPU platform (a pinned
    # PFX_PLATFORM=tpu must still be guarded — it is the hang case)
    platform = os.environ.get("PFX_PLATFORM", "").lower()
    if platform in ("", "tpu", "axon"):
        if not wait_for_backend():
            # emit an honest failure line rather than hanging the driver
            print(
                json.dumps(
                    {
                        "metric": "gpt345m_pretrain_throughput_per_chip",
                        "value": 0.0,
                        "unit": "tokens/s/chip (tpu backend unreachable)",
                        "vs_baseline": 0.0,
                    }
                )
            )
            return

    import jax
    import numpy as np

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    n_dev = jax.device_count()
    batch = int(os.environ.get("BENCH_BATCH", 16)) * n_dev
    seq = int(os.environ.get("BENCH_SEQ", 1024))
    steps = int(os.environ.get("BENCH_STEPS", 10))

    cfg = AttrDict.from_nested(
        {
            "Global": {
                "global_batch_size": batch,
                "micro_batch_size": batch // n_dev,
                "seed": 1024,
                # hardware RNG for dropout masks: ~15% step-time win over
                # threefry on TPU, no effect on loss statistics
                "prng_impl": os.environ.get("BENCH_PRNG", "rbg"),
            },
            "Engine": {
                "max_steps": steps,
                "eval_freq": 0,
                "logging_freq": 10**9,
                "mix_precision": {"enable": True, "dtype": "bfloat16"},
                "save_load": {"save_steps": 0},
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 50304,
                "hidden_size": 1024,
                "num_layers": 24,
                "num_attention_heads": 16,
                "max_position_embeddings": seq,
                "hidden_dropout_prob": float(os.environ.get("BENCH_DROPOUT", 0.1)),
                "attention_probs_dropout_prob": float(os.environ.get("BENCH_DROPOUT", 0.1)),
                "attn_impl": os.environ.get("BENCH_ATTN", "flash"),
                # 16GB v5e HBM can't hold the full activation set (37G), but
                # blanket full-layer remat wastes a whole extra forward;
                # "selective" saves the measured-best named set (qkv +
                # attn_out + attn_lse) and recomputes the cheap rest
                "use_recompute": os.environ.get("BENCH_RECOMPUTE", "1") == "1",
                "recompute_granularity": os.environ.get("BENCH_REMAT", "selective"),
                "use_fused_ln": os.environ.get("BENCH_FUSED_LN", "1") == "1",
                # streams the vocab through the CE so the fp32 logits buffer
                # never materializes (ops/chunked_ce.py) — try with bigger
                # BENCH_BATCH once enabled
                "use_chunked_ce": os.environ.get("BENCH_CHUNKED_CE", "0") == "1",
            },
            "Distributed": {},
            "Optimizer": {
                "name": "FusedAdamW",
                "weight_decay": 0.01,
                "beta1": 0.9,
                "beta2": 0.95,
                "lr": {"name": "Constant", "learning_rate": 1e-4},
                "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
            },
        }
    )
    cfg = process_configs(cfg, num_devices=n_dev)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    rng = np.random.default_rng(0)
    host_batch = {
        "tokens": rng.integers(0, 50304, (batch, seq)).astype(np.int64),
        "labels": rng.integers(0, 50304, (batch, seq)).astype(np.int64),
        "loss_mask": np.ones((batch, seq), np.float32),
        "position_ids": np.tile(np.arange(seq), (batch, 1)),
    }

    with mesh:
        engine = Engine(cfg, module, mesh)
        dev_batch = engine._put_batch(host_batch)
        # warmup (compile)
        for _ in range(3):
            engine.state, m = engine._train_step(engine.state, dev_batch)
        float(m["loss"])  # host fetch: drains the warmup chain (see below)
        t0 = time.time()
        for _ in range(steps):
            engine.state, m = engine._train_step(engine.state, dev_batch)
        # force a device->host fetch of the final loss: on the axon remote
        # runtime block_until_ready alone has been observed returning while
        # the donated-state chain is still in flight (timing would then
        # measure dispatch, not execution)
        final_loss = float(m["loss"])
        dt = time.time() - t0

    if not np.isfinite(final_loss):
        # same honest-failure contract as the unreachable-backend path:
        # always ONE parseable JSON line, never a traceback
        print(
            json.dumps(
                {
                    "metric": "gpt345m_pretrain_throughput_per_chip",
                    "value": 0.0,
                    "unit": f"tokens/s/chip (non-finite bench loss {final_loss})",
                    "vs_baseline": 0.0,
                }
            )
        )
        return

    tokens_per_s = batch * seq * steps / dt

    mc = cfg.Model
    flops_tok = model_flops_per_token(
        mc.hidden_size, mc.num_layers, mc.vocab_size, seq
    )
    peak = float(os.environ.get("BENCH_PEAK_TFLOPS", 197)) * 1e12  # v5e bf16
    mfu = tokens_per_s / n_dev * flops_tok / peak

    print(
        json.dumps(
            {
                "metric": "gpt345m_pretrain_throughput_per_chip",
                "value": round(tokens_per_s / n_dev, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(tokens_per_s / n_dev / BASELINE_TOKENS_PER_S, 3),
                "mfu": round(mfu, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
