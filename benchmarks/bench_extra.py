"""Real-chip benchmarks beyond the bench.py headline: the BASELINE.md
north-star configs that fit ONE chip.

Cases (per-chip baselines from the reference's published numbers):
  gpt1p3b    GPT-1.3B pretrain, seq 1024      — ref ~11,500 tok/s/V100-32G
             (projects/gpt/docs/hybrid_parallel.md:100-109, fp16+dp8+recompute)
  vit_b16    ViT-B/16 224 ImageNet pretrain   — ref 7350/16 = 459 img/s/A100
             (projects/vit/README.md:84, A100*N2C16)
  vit_l16    ViT-L/16 384 finetune shape      — ref 519/16 = 32.4 img/s/A100
             (projects/vit/README.md:86)
  ernie_base ERNIE-345M MLM+NSP pretrain      — no published ref number
             (shape: pretrain_ernie_base_345M_single_card.yaml)
  imagen_base64  Imagen base-64 unet1 train   — no published ref number
             (shape: imagen_397M_text2im_64x64.yaml, precomputed embeds)

GPT-6.7B (mp2 pp4 sharding16) does NOT fit one 16 GB chip in any precision
(13.4 GB params + 26.8 GB adam moments at bf16/fp32 mix); recorded as
infeasible-single-chip in BENCH_NOTE.md rather than benchmarked dishonestly.

Each case prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}
and appends it to benchmarks/results_extra.jsonl.  Usage:

  python benchmarks/bench_extra.py [--cases gpt1p3b,vit_b16,vit_l16]
      [--steps N]
"""

import argparse
import json
import os
import sys
import time
import traceback

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _gpt_base_cfg(env: str, n_dev: int, steps: int, *, batch: int, seq: int,
                  hidden: int, layers: int):
    """Shared GPT bench config frame: bf16 compute, selective remat,
    chunked CE, flash fused/512 (auto ladder when 512 does not divide a
    shrink-knob seq).  ``env`` is the BENCH_<env>_* knob prefix; cases
    layer their memory levers on top of the returned dict."""
    batch = int(os.environ.get(f"BENCH_{env}_BATCH", batch)) * n_dev
    seq = int(os.environ.get(f"BENCH_{env}_SEQ", seq))
    return {
        "Global": {
            "global_batch_size": batch,
            "micro_batch_size": batch // n_dev,
            "seed": 1024,
            "prng_impl": "rbg",
        },
        "Engine": {
            "max_steps": steps,
            "eval_freq": 0,
            "logging_freq": 10**9,
            "mix_precision": {"enable": True, "dtype": "bfloat16"},
            "save_load": {"save_steps": 0},
        },
        "Model": {
            "module": "GPTModule",
            # BENCH_<env>_* shrink knobs exist for CI smoke only
            "vocab_size": int(os.environ.get(f"BENCH_{env}_VOCAB", 50304)),
            "hidden_size": int(os.environ.get(f"BENCH_{env}_HIDDEN", hidden)),
            "num_layers": int(os.environ.get(f"BENCH_{env}_LAYERS", layers)),
            "num_attention_heads": 16,
            "max_position_embeddings": seq,
            "hidden_dropout_prob": 0.1,
            "attention_probs_dropout_prob": 0.1,
            "attn_impl": "flash",
            "use_recompute": True,
            "recompute_granularity":
                os.environ.get(f"BENCH_{env}_REMAT", "selective"),
            "use_fused_ln": True,
            "use_chunked_ce": True,
            # fused/512 measured end-to-end on-chip 18:57Z: 1.3B 14,024
            # tok/s at b8 vs 13,480 with split/256 (results_extra.jsonl)
            "flash_block": int(os.environ.get(
                f"BENCH_{env}_FLASH_BLOCK", 512 if seq % 512 == 0 else 0)),
            "flash_bwd": os.environ.get(f"BENCH_{env}_FLASH_BWD", "fused"),
        },
        "Distributed": {},
        "Optimizer": {
            "name": "FusedAdamW",
            "weight_decay": 0.01,
            "beta1": 0.9,
            "beta2": 0.95,
            "lr": {"name": "Constant", "learning_rate": 1e-4},
            "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
        },
    }, batch, seq


def _gpt_cfg(n_dev: int, steps: int):
    """GPT-1.3B (reference pretrain_gpt_1.3B_dp8.yaml model shape: hidden
    2048, 24 layers, 16 heads) on one chip: the memory levers that fit
    1.3B params + moments + activations in 16 GB HBM layered on the
    shared frame."""
    # b8 is the measured sweet spot (18:57Z on-chip: b8 14,024 tok/s /
    # 58.1% MFU vs b4 13,445; b12 OOMs; b8+full-remat 13,511)
    raw, batch, seq = _gpt_base_cfg(
        "1P3B", n_dev, steps, batch=8, seq=1024, hidden=2048, layers=24)
    # bf16 grads (main_grad off) halve the 4.1G of fp32 grad
    # accumulators — measured necessary to fit AdamW-complete 1.3B on one
    # 15.75G chip (03:18Z window: b2+full-remat+offload still OOM'd by
    # 853M with fp32 grads)
    raw["Engine"]["mix_precision"]["main_grad"] = (
        os.environ.get("BENCH_1P3B_MAIN_GRAD", "0") == "1")
    # fp32 masters (5.2G) + bf16 mu (2.6G) + fp32 nu (5.2G) alone are
    # 13G of the chip's 15.75G HBM; grads + activations push the step
    # past 21G (measured OOM).  Host offload of the moments does NOT
    # save the day either: the monolithic device_put stages every
    # stacked nu leaf on-device at once (measured 03:24Z window: 4.1G
    # of copy-start temps, still 1.19G over).  What fits is the
    # reference's OTHER knob: multi_precision=False — bf16 params, no
    # fp32 masters, moments in bf16 — ~10.4G peak including grads.
    raw["Distributed"] = {
        "sharding": {
            "sharding_offload":
                os.environ.get("BENCH_1P3B_OFFLOAD", "0") == "1",
        },
    }
    raw["Optimizer"]["multi_precision"] = (
        os.environ.get("BENCH_1P3B_MULTI_PRECISION", "0") == "1")
    # bf16 first moment halves the largest optimizer buffer
    # (optims/optimizer.py:46 moment_dtype -> optax mu_dtype)
    raw["Optimizer"]["moment_dtype"] = "bfloat16"
    return raw, batch, seq


def _vit_cfg(n_dev: int, steps: int, large: bool):
    """ViT-B/16 224 pretrain / ViT-L/16 384 finetune shapes (reference
    configs/vis/vit/ViT_{base,large}_patch16_*.yaml)."""
    if large:
        image, hidden, layers, heads = 384, 1024, 24, 16
        batch = int(os.environ.get("BENCH_VITL_BATCH", 32)) * n_dev
    else:
        image, hidden, layers, heads = 224, 768, 12, 12
        batch = int(os.environ.get("BENCH_VITB_BATCH", 128)) * n_dev
    layers = int(os.environ.get("BENCH_VIT_LAYERS", layers))  # CI shrink knob
    return {
        "Global": {
            "global_batch_size": batch,
            "micro_batch_size": batch // n_dev,
            "seed": 1024,
            "prng_impl": "rbg",
        },
        "Engine": {
            "max_steps": steps,
            "eval_freq": 0,
            "logging_freq": 10**9,
            "mix_precision": {"enable": True, "dtype": "bfloat16"},
            "save_load": {"save_steps": 0},
        },
        "Model": {
            "module": "ViTModule",
            "image_size": image,
            "patch_size": 16,
            "num_classes": 1000,
            "hidden_size": hidden,
            "num_layers": layers,
            "num_attention_heads": heads,
            "hidden_dropout_prob": 0.1,
            # without remat the 12-layer scan stashes every block activation
            # (443M apiece at b128) and the step OOMs; one extra forward is
            # far cheaper than spilling (measured: OOM -> fits)
            "use_recompute": os.environ.get("BENCH_VIT_REMAT", "1") == "1",
        },
        "Distributed": {},
        "Optimizer": {
            "name": "AdamW",
            "weight_decay": 0.3,
            "lr": {"name": "Constant", "learning_rate": 3e-4},
            "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
        },
    }, batch, image


def _gpt4k_cfg(n_dev: int, steps: int):
    """GPT-345M at seq 4096 (4x the headline): long-context single-chip
    evidence — flash fused/512 at 4096 rows, selective remat, chunked CE
    (the fp32 logits buffer at 4096x50304 would be 3.3 GB at b4).  The
    reference documents seq-1024 configs only, so the row reports an
    absolute rate (vs_baseline null) with the headline config cited."""
    return _gpt_base_cfg(
        "4K", n_dev, steps, batch=4, seq=4096, hidden=1024, layers=24)


CASES = {
    "gpt1p3b": {"baseline": 11500.0, "unit": "tokens/s/chip"},
    "gpt_seq4096": {
        "baseline": None, "unit": "tokens/s/chip",
        "note": "no published reference number at seq 4096 (reference GPT "
                "docs are seq-1024); shape = headline 345M at 4x sequence",
    },
    "vit_b16": {"baseline": 459.0, "unit": "images/s/chip"},
    "vit_l16": {"baseline": 32.4, "unit": "images/s/chip"},
    # the reference publishes NO throughput number for these two families
    # (projects/ernie/, projects/imagen/ ship configs + scripts only), so
    # the rows report absolute per-chip rates with vs_baseline null and a
    # citation of the config whose shape they reproduce
    "ernie_base": {
        "baseline": None, "unit": "tokens/s/chip",
        "note": "no published reference number; shape = "
                "pretrain_ernie_base_345M_single_card.yaml",
    },
    "imagen_base64": {
        "baseline": None, "unit": "images/s/chip",
        "note": "no published reference number; shape = "
                "imagen_397M_text2im_64x64.yaml unet1 (text embeds "
                "precomputed, encoder frozen as in only_train_unet_number=1)",
    },
}


def _ernie_cfg(n_dev: int, steps: int):
    """ERNIE-345M MLM+NSP pretrain shape (reference
    ppfleetx/configs/nlp/ernie/pretrain_ernie_base_345M_single_card.yaml:
    vocab 40000, hidden 1024, 24 layers, 16 heads, seq 512)."""
    batch = int(os.environ.get("BENCH_ERNIE_BATCH", 32)) * n_dev
    seq = int(os.environ.get("BENCH_ERNIE_SEQ", 512))
    return {
        "Global": {
            "global_batch_size": batch,
            "micro_batch_size": batch // n_dev,
            "seed": 1024,
            "prng_impl": "rbg",
        },
        "Engine": {
            "max_steps": steps,
            "eval_freq": 0,
            "logging_freq": 10**9,
            "mix_precision": {"enable": True, "dtype": "bfloat16"},
            "save_load": {"save_steps": 0},
        },
        "Model": {
            "module": "ErnieModule",
            "vocab_size": 40000,
            "hidden_size": int(os.environ.get("BENCH_ERNIE_HIDDEN", 1024)),
            "num_layers": int(os.environ.get("BENCH_ERNIE_LAYERS", 24)),
            "num_attention_heads": 16,
            "ffn_hidden_size": 4096,
            "max_position_embeddings": seq,
            "type_vocab_size": 4,
            "binary_head": True,
            "attn_impl": "flash",
            "use_chunked_ce": True,
        },
        "Distributed": {},
        "Optimizer": {
            "name": "FusedAdamW",
            "weight_decay": 0.01,
            "beta1": 0.9,
            "beta2": 0.999,
            "lr": {"name": "Constant", "learning_rate": 1e-4},
            "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
        },
    }, batch, seq


def _imagen_cfg(n_dev: int, steps: int):
    """Imagen base-64 text2im unet (reference
    ppfleetx/configs/multimodal/imagen/imagen_397M_text2im_64x64.yaml:
    dim 512, mults 1/2/3/4, 3 resblocks, text_embed_dim 1024, loader
    batch 16).  Text embeds are fed precomputed: the reference trains
    unet 1 only with the T5 encoder frozen, so encoder FLOPs are not part
    of the trained-throughput comparison either way."""
    batch = int(os.environ.get("BENCH_IMAGEN_BATCH", 16)) * n_dev
    dim = int(os.environ.get("BENCH_IMAGEN_DIM", 512))
    return {
        "Global": {
            "global_batch_size": batch,
            "micro_batch_size": batch // n_dev,
            "seed": 1024,
            "prng_impl": "rbg",
        },
        "Engine": {
            "max_steps": steps,
            "eval_freq": 0,
            "logging_freq": 10**9,
            "mix_precision": {"enable": True, "dtype": "bfloat16"},
            "save_load": {"save_steps": 0},
        },
        "Model": {
            "module": "ImagenModule",
            "unets": [{
                "dim": dim,
                "dim_mults": [1, 2, 3, 4],
                "num_resnet_blocks": 3,
                "layer_attns": [False, True, True, True],
                "layer_cross_attns": [False, True, True, True],
                "attn_heads": 8,
            }],
            "image_sizes": [64],
            "text_embed_dim": 1024,
            "timesteps": 1000,
            "noise_schedules": ["cosine"],
            "cond_drop_prob": 0.1,
            "unet_number": 1,
        },
        "Distributed": {},
        "Optimizer": {
            "name": "FusedAdamW",
            "weight_decay": 0.01,
            "lr": {"name": "Constant", "learning_rate": 1e-4},
            "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
        },
    }, batch, 64


def run_case(name: str, steps: int) -> dict:
    import jax
    import numpy as np

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    n_dev = jax.device_count()
    if name == "gpt1p3b":
        raw, batch, seq = _gpt_cfg(n_dev, steps)
    elif name == "gpt_seq4096":
        raw, batch, seq = _gpt4k_cfg(n_dev, steps)
    elif name == "ernie_base":
        raw, batch, seq = _ernie_cfg(n_dev, steps)
    elif name == "imagen_base64":
        raw, batch, seq = _imagen_cfg(n_dev, steps)
    else:
        raw, batch, seq = _vit_cfg(n_dev, steps, large=name == "vit_l16")

    cfg = process_configs(AttrDict.from_nested(raw), num_devices=n_dev)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    rng = np.random.default_rng(0)
    if name in ("gpt1p3b", "gpt_seq4096"):
        vocab = int(cfg.Model.vocab_size)
        host_batch = {
            "tokens": rng.integers(0, vocab, (batch, seq)).astype(np.int64),
            "labels": rng.integers(0, vocab, (batch, seq)).astype(np.int64),
            "loss_mask": np.ones((batch, seq), np.float32),
            "position_ids": np.tile(np.arange(seq), (batch, 1)),
        }
        per_step = batch * seq  # tokens
    elif name == "ernie_base":
        vocab = int(cfg.Model.vocab_size)
        # ~15% masked positions, -1 everywhere else (ernie/model.py label
        # contract: -1 = unmasked, ignored by the CE)
        labels = np.full((batch, seq), -1, np.int64)
        mask = rng.random((batch, seq)) < 0.15
        labels[mask] = rng.integers(0, vocab, mask.sum())
        host_batch = {
            "input_ids": rng.integers(0, vocab, (batch, seq)).astype(np.int64),
            "masked_lm_labels": labels,
            "next_sentence_label": rng.integers(0, 2, (batch,)).astype(np.int64),
        }
        per_step = batch * seq  # tokens
    elif name == "imagen_base64":
        text_len = 128  # reference text_max_len
        emb_dim = int(cfg.Model.text_embed_dim)
        host_batch = {
            "images": rng.uniform(0, 1, (batch, seq, seq, 3)).astype(np.float32),
            "text_embeds": rng.normal(0, 1, (batch, text_len, emb_dim)).astype(np.float32),
            "text_mask": np.ones((batch, text_len), np.int32),
        }
        per_step = batch  # images
    else:
        host_batch = {
            "images": rng.normal(0, 1, (batch, seq, seq, 3)).astype(np.float32),
            "labels": rng.integers(0, 1000, (batch,)).astype(np.int64),
        }
        per_step = batch  # images

    with mesh:
        engine = Engine(cfg, module, mesh)
        dev_batch = engine._put_batch(host_batch)
        for _ in range(3):
            engine.state, m = engine.train_step(engine.state, dev_batch)
        float(m["loss"])  # drain the warmup chain (see bench.py)
        t0 = time.time()
        for _ in range(steps):
            engine.state, m = engine.train_step(engine.state, dev_batch)
        final_loss = float(m["loss"])
        dt = time.time() - t0

    meta = CASES[name]
    if not np.isfinite(final_loss):
        return {"metric": f"{name}_throughput_per_chip", "value": 0.0,
                "unit": f"{meta['unit']} (non-finite loss)",
                "vs_baseline": 0.0 if meta["baseline"] else None,
                "platform": jax.default_backend()}
    rate = per_step * steps / dt / n_dev
    row = {
        "metric": f"{name}_throughput_per_chip",
        "value": round(rate, 1),
        "unit": meta["unit"],
        "vs_baseline": (round(rate / meta["baseline"], 3)
                        if meta["baseline"] else None),
        # CPU smoke rows must never read as chip evidence
        "platform": jax.default_backend(),
    }
    if meta.get("note"):
        row["note"] = meta["note"]
    if name in ("gpt1p3b", "gpt_seq4096"):
        from bench import model_flops_per_token

        mc = cfg.Model
        flops_tok = model_flops_per_token(
            mc.hidden_size, mc.num_layers, mc.vocab_size, seq
        )
        peak = float(os.environ.get("BENCH_PEAK_TFLOPS", 197)) * 1e12
        row["mfu"] = round(rate * flops_tok / peak, 4)
    return row


OUT_PATH = os.path.join(ROOT, "benchmarks", "results_extra.jsonl")


def _zero_vsb(name: str):
    """Honest-zero rows keep the success-path vs_baseline convention:
    0.0 ratio where a baseline exists, null where none is published."""
    return 0.0 if CASES[name]["baseline"] else None


def _emit(row: dict) -> None:
    line = json.dumps(row)
    print(line, flush=True)
    with open(OUT_PATH, "a") as f:
        f.write(line + "\n")


def _parse_cases(cases_arg: str) -> list:
    out = []
    for name in cases_arg.split(","):
        name = name.strip()
        if name not in CASES:
            print(f"unknown case {name!r}; have {sorted(CASES)}", file=sys.stderr)
            continue
        out.append(name)
    return out


def _parent(argv) -> int:
    """Same always-emit contract as bench.py (shared harness): the child
    runs the cases, the pure-Python parent stays signal-responsive and
    writes an honest 0.0 row for every case the child did not finish."""
    from bench import run_child_with_honest_fallback

    ap = argparse.ArgumentParser()
    ap.add_argument("--cases", default="gpt1p3b,vit_b16,vit_l16")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args(argv)
    cases = _parse_cases(args.cases)
    if not cases:
        # fail fast: spawning a child with no cases would probe the TPU
        # for minutes and exit 0 with zero rows
        print(f"no valid cases in {args.cases!r}; have {sorted(CASES)}",
              file=sys.stderr)
        return 2

    def emit_missing(seen, reason):
        for name in cases:
            metric = f"{name}_throughput_per_chip"
            if metric not in seen:
                _emit({"metric": metric, "value": 0.0,
                       "unit": f"{CASES[name]['unit']} ({reason})",
                       "vs_baseline": _zero_vsb(name)})

    return run_child_with_honest_fallback(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--cases", ",".join(cases), "--steps", str(args.steps)],
        float(os.environ.get("BENCH_EXTRA_DEADLINE_S", 1500)),
        emit_missing,
    )


def _child(argv) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cases", default="gpt1p3b,vit_b16,vit_l16")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args(argv)

    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()

    # same hang guard + bounded re-poll window as bench.py
    from bench import wait_for_backend

    platform = os.environ.get("PFX_PLATFORM", "").lower()
    if platform in ("", "tpu", "axon") and not wait_for_backend():
        for name in _parse_cases(args.cases):
            _emit({"metric": f"{name}_throughput_per_chip", "value": 0.0,
                   "unit": f"{CASES[name]['unit']} (tpu backend unreachable)",
                   "vs_baseline": _zero_vsb(name)})
        return

    for name in _parse_cases(args.cases):
        try:
            row = run_case(name, args.steps)
        except Exception as e:  # noqa: BLE001 — e.g. RESOURCE_EXHAUSTED on a
            # memory-tight case must not abort the remaining cases
            traceback.print_exc(file=sys.stderr)
            import jax

            row = {"metric": f"{name}_throughput_per_chip", "value": 0.0,
                   "unit": f"{CASES[name]['unit']} ({type(e).__name__})",
                   "vs_baseline": _zero_vsb(name),
                   "platform": jax.default_backend()}
        _emit(row)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--child" in argv:
        argv.remove("--child")
        _child(argv)
        return
    sys.exit(_parent(argv))


if __name__ == "__main__":
    main()
