"""TIPC-style benchmark harness.

Re-design of the reference benchmark layer (benchmarks/test_tipc/:
<model>/<graph-mode>/<parallel-mode>/<Nnodes-Ccards>/<case>.sh calling
benchmark_common/run_benchmark.sh, which shrinks the model to 4 layers/4
heads, runs tools/train.py under the launcher with a timeout, and regex-
parses logs for `ips:` tokens/s + `loss:` — SURVEY §4).

Here a case is a JSON file (benchmarks/cases/*.json):

  {"config": "<yaml>", "devices": 8, "platform": "cpu"|null,
   "overrides": ["Model.num_layers=4", ...], "timeout_s": 600}

Run:  python benchmarks/run_benchmark.py [case ...]  (default: all cases)
Output: one JSON line per case {case, ips, ips_per_device, last_loss, ok}
plus benchmarks/results.jsonl.  Loss keys double as the convergence
regression signal, exactly like the reference's convergence_key.
"""

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IPS_RE = re.compile(r"ips: ([\d,]+) tokens/s \(([\d,]+)/device\)")
# train-step lines only — 'eval loss:' must not pollute the convergence key
LOSS_RE = re.compile(r"step \d+/\d+ loss: ([\d.]+)")


def _provision(name: str, spec: dict, writer, writer_kwargs: dict,
               marker_file: str, returns_prefix: bool):
    """Cache-keyed synthetic corpus generation shared by all dataset
    families: regenerate when the case spec changes, not on mere
    existence.  Returns the value to point input_dir at (the corpus
    prefix or its directory, per the dataset's convention)."""
    data_dir = os.path.join("/tmp", "pfx_bench_data", name)  # noqa — dir, not a metric
    prefix = os.path.join(data_dir, "corpus")
    spec_path = os.path.join(data_dir, "spec.json")
    spec_str = json.dumps(spec, sort_keys=True)
    stale = True
    if os.path.exists(spec_path):
        with open(spec_path) as f:
            stale = f.read() != spec_str
    if stale or not os.path.exists(os.path.join(data_dir, marker_file)):
        os.makedirs(data_dir, exist_ok=True)
        writer(prefix, **writer_kwargs)
        with open(spec_path, "w") as f:
            f.write(spec_str)
    return prefix if returns_prefix else data_dir


def _ensure_synthetic_data(case: dict, name: str) -> list:
    """Generate a tiny corpus for the case (reference run_benchmark.sh
    points cases at pre-staged data; we self-provision).  Every knob in
    the case spec is forwarded to the writer — an unknown knob fails
    loudly rather than silently regenerating identical data."""
    sys.path.insert(0, ROOT)  # before the writer imports below
    espec = case.get("synthetic_ernie_data")
    if espec:
        from paddlefleetx_tpu.data.ernie_dataset import (
            write_synthetic_sentence_corpus,
        )

        target = _provision(
            name, espec, write_synthetic_sentence_corpus, dict(espec),
            marker_file="corpus_ids.npy", returns_prefix=True,
        )
    else:
        spec = case.get("synthetic_gpt_data")
        if not spec:
            return []
        from paddlefleetx_tpu.data.gpt_dataset import write_synthetic_corpus

        target = _provision(
            name, spec, write_synthetic_corpus, dict(spec),
            marker_file="corpus_ids.npy", returns_prefix=False,
        )
    return [
        f"Data.Train.dataset.input_dir={target}",
        f"Data.Eval.dataset.input_dir={target}",
    ]


def run_case(path: str) -> dict:
    with open(path) as f:
        case = json.load(f)
    name = os.path.splitext(os.path.basename(path))[0]
    cmd = [sys.executable, os.path.join(ROOT, "tools", "train.py"), "-c",
           os.path.join(ROOT, case["config"])]
    for o in case.get("overrides", []) + _ensure_synthetic_data(case, name):
        cmd += ["-o", o]
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if case.get("platform") == "cpu":
        # PFX_PLATFORM is honored in-process by tools/* (the axon
        # sitecustomize overrides a bare JAX_PLATFORMS env var)
        env["PFX_PLATFORM"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={case.get('devices', 8)}"
        )
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True,
            timeout=case.get("timeout_s", 900),
        )
        log = proc.stdout + proc.stderr
        ok = proc.returncode == 0
    except subprocess.TimeoutExpired as e:
        log = (e.stdout or "") + (e.stderr or "")
        ok = False
    ips = [float(m.group(1).replace(",", "")) for m in IPS_RE.finditer(log)]
    ips_dev = [float(m.group(2).replace(",", "")) for m in IPS_RE.finditer(log)]
    losses = [float(m.group(1)) for m in LOSS_RE.finditer(log)]
    result = {
        "case": name,
        "ok": ok and bool(ips),
        # steady-state: last window (first includes compile)
        "ips": ips[-1] if ips else None,
        "ips_per_device": ips_dev[-1] if ips_dev else None,
        "first_loss": losses[0] if losses else None,
        "last_loss": losses[-1] if losses else None,
        "wall_s": round(time.time() - t0, 1),
    }
    if not result["ok"]:
        result["log_tail"] = log[-2000:]
    return result


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("cases", nargs="*", help="case json paths (default: all)")
    args = ap.parse_args(argv)
    cases = args.cases or sorted(
        glob.glob(os.path.join(ROOT, "benchmarks", "cases", "*.json"))
    )
    results = []
    for path in cases:
        r = run_case(path)
        results.append(r)
        print(json.dumps(r))
    out = os.path.join(ROOT, "benchmarks", "results.jsonl")
    with open(out, "a") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
    bad = [r["case"] for r in results if not r["ok"]]
    if bad:
        print(f"FAILED cases: {bad}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
