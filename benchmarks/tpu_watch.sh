#!/usr/bin/env bash
# Tunnel watcher: probe the axon TPU backend every PROBE_EVERY_S seconds
# and run the still-missing chip measurements the moment it answers.
#
# The axon tunnel has dropped mid-round in rounds 2, 3, and 4 (uptime
# windows of ~20 min between multi-hour outages), so chip-gated work
# cannot assume a live backend at any particular moment.  This script is
# the standing order: leave it running detached, and each recovery window
# gets spent on the highest-value missing measurement instead of on
# noticing the recovery.
#
# A task whose output shows an honest-zero row (tunnel died mid-task)
# is rotated to the back of the queue for ONE retry instead of being
# consumed — round 4 lost eight gpt1p3b attempts to exactly that.
#
# Usage: bash benchmarks/tpu_watch.sh [task ...]
#   task: gpt1p3b | tune1p3b | profile | headline | fusedbwd | sweep2 | longseq |
#         kernels | decode | extra
#   (default: kernels headline)
set -u
cd "$(dirname "$0")/.."
PROBE_EVERY_S=${PROBE_EVERY_S:-120}
TASKS=("$@")
if [ $# -eq 0 ]; then TASKS=(kernels headline); fi
for t in "${TASKS[@]}"; do
  case "$t" in gpt1p3b|tune1p3b|profile|headline|fusedbwd|sweep2|longseq|kernels|decode|extra) ;; *)
    # a typo must not burn a scarce tunnel-up window on a no-op
    echo "unknown task '$t' (have: gpt1p3b tune1p3b profile headline fusedbwd sweep2 longseq kernels decode extra)" >&2; exit 2 ;;
  esac
done
LOG=benchmarks/tpu_watch.log

probe() {
  # jax.devices() HANGS (not errors) when the tunnel is down, so the
  # probe must be a killable child with a hard deadline
  timeout 60 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

run_task() {
  case "$1" in
    gpt1p3b)
      # b8 + selective remat + multi_precision=False (bf16 params/moments,
      # bench_extra defaults): the measured-best 1.3B single-chip layout —
      # 14,024 tok/s, 58.1% MFU with the fused flash backward (18:57Z
      # window; b12 OOMs, full-remat 13,511).  Offloaded fp32-master
      # layouts never fit (the monolithic device_put stages all nu leaves
      # at once; measured 1.19G over even with bf16 grads).
      BENCH_1P3B_BATCH=8 BENCH_EXTRA_DEADLINE_S=900 \
        timeout 1000 python benchmarks/bench_extra.py --cases gpt1p3b --steps 8
      ;;
    tune1p3b)
      # push 1.3B past 14,024 (the fused/512 b8 default): asymmetric K
      # block and the smaller q tile are the unprobed points at h=2048
      for combo in "PFX_FLASH_BLOCK_K=1024" \
                   "BENCH_1P3B_FLASH_BLOCK=256"; do
        echo "== 1.3B sweep: $combo =="
        env $combo BENCH_1P3B_BATCH=8 BENCH_EXTRA_DEADLINE_S=700 \
          timeout 800 python benchmarks/bench_extra.py --cases gpt1p3b --steps 8
      done
      ;;
    extra)
      # ERNIE + Imagen chip rows (VERDICT r4 #4): every BASELINE.json
      # family gets a measured number
      BENCH_EXTRA_DEADLINE_S=1200 timeout 1300 \
        python benchmarks/bench_extra.py --cases ernie_base,imagen_base64 --steps 8
      ;;
    longseq)
      # 345M at seq 4096: long-context single-chip evidence (flash
      # fused/512 at 4096 rows + chunked CE)
      BENCH_EXTRA_DEADLINE_S=900 timeout 1000 \
        python benchmarks/bench_extra.py --cases gpt_seq4096 --steps 8
      ;;
    profile)
      timeout 900 python benchmarks/profile_bench.py \
        --log_dir benchmarks/chip_day/profile_watch || echo "profile rc=$?"
      ;;
    headline)
      BENCH_DEADLINE_S=600 timeout 700 python bench.py
      ;;
    fusedbwd)
      # A/B the fused single-kernel flash backward vs the split default
      PFX_FLASH_BWD=fused BENCH_DEADLINE_S=600 timeout 700 python bench.py
      ;;
    decode)
      # inference-side evidence: decode grid (greedy + top-p, b8/b32,
      # 128/256) and the bucketed serving row
      BENCH_DECODE_DEADLINE_S=1200 timeout 1300 python benchmarks/bench_decode.py \
        || echo "decode rc=$?"
      ;;
    kernels)
      # ~20s/datapoint kernel microbench: answers bf16-dot delivery,
      # fused-vs-split, and block optimum before the full re-measures
      timeout 600 python benchmarks/kernel_bench.py
      ;;
    sweep2)
      # knob sweep on TOP of the fused/512 defaults (the 18:43Z window
      # made them the bench baseline): does the batch/unroll optimum
      # shift now that the flash pair is ~30% faster?
      # bigger batches need chunked CE (the fp32 logits buffer is
      # batch*1024*50304*4B — 6.6G at b32; bench.py:255 'try with bigger
      # BENCH_BATCH once enabled')
      for combo in "BENCH_BATCH=24 BENCH_CHUNKED_CE=1" \
                   "BENCH_BATCH=32 BENCH_CHUNKED_CE=1" \
                   "BENCH_SCAN_UNROLL=2 BENCH_BATCH=8" \
                   "BENCH_FLASH_BLOCK=256" \
                   "PFX_FLASH_BLOCK_K=1024"; do
        echo "== headline sweep: $combo =="
        env $combo BENCH_DEADLINE_S=400 timeout 500 python bench.py
      done
      ;;
  esac
}

echo "== tpu_watch start $(date -u +%FT%TZ) tasks: ${TASKS[*]} ==" >>"$LOG"
LAST_BEAT=$SECONDS
while [ ${#TASKS[@]} -gt 0 ]; do
  if probe; then
    # reset the still-down clock: a long task window must not make the
    # first failed probe after it look like an hour-old outage
    LAST_BEAT=$SECONDS
    task="${TASKS[0]}"
    base="${task%\!}"
    echo "== tunnel UP $(date -u +%FT%TZ); running $base ==" >>"$LOG"
    # stream into LOG as the task runs (a mid-task kill must not lose the
    # partial output — that partial log IS the scarce-window evidence)
    # while tee keeps a copy for the requeue check; fixed name, no leaks
    out=benchmarks/.tpu_watch_last.out
    run_task "$base" 2>&1 | tee "$out" >>"$LOG"
    TASKS=("${TASKS[@]:1}")
    if grep -q '"value": 0.0\|unreachable' "$out" && [ "$task" = "$base" ]; then
      # honest-zero output = the window closed mid-task; give it one
      # retry at the back of the queue (the '!' marks spent retry)
      echo "== $base hit honest-zero; requeued for one retry ==" >>"$LOG"
      TASKS=("${TASKS[@]}" "$base!")
    fi
  else
    # hourly still-down heartbeat (wall-clock based): the outage-duration
    # claims in BENCH_NOTE.md lean on the watcher having actually probed
    # the whole time — make that auditable
    if [ $((SECONDS - LAST_BEAT)) -ge 3600 ]; then
      echo "== tunnel still down $(date -u +%FT%TZ) (queue: ${TASKS[*]}) ==" >>"$LOG"
      LAST_BEAT=$SECONDS
    fi
    sleep "$PROBE_EVERY_S"
  fi
done
echo "== tpu_watch done $(date -u +%FT%TZ) ==" >>"$LOG"
