"""Flash-attention kernel microbench: fwd and fwd+bwd wall time at the
headline bench shape, across backward schedule x block size combos.

Much cheaper per data point than a full bench.py run (~20 s vs ~3 min),
so a short tunnel window can answer the kernel questions (does the
bf16-dot change deliver? fused vs split? block optimum?) before the
end-to-end re-measures.  One JSON row per combo to stdout and
benchmarks/kernel_results.jsonl.

  python benchmarks/kernel_bench.py [--bh 256] [--seq 1024] [--d 64]
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--bh", type=int, default=256)  # b16 x h16
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args(argv)

    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()
    from bench import wait_for_backend

    platform = os.environ.get("PFX_PLATFORM", "").lower()
    if platform in ("", "tpu", "axon") and not wait_for_backend():
        print("tpu backend unreachable", file=sys.stderr)
        sys.exit(1)

    import jax
    import jax.numpy as jnp

    b, n = 16, args.bh // 16
    shape = (b, args.seq, n, args.d)
    dt = jnp.dtype(args.dtype)
    kq, kk, kv, kg = jax.random.split(jax.random.key(0), 4)
    q = jax.random.normal(kq, shape, jnp.float32).astype(dt)
    k = jax.random.normal(kk, shape, jnp.float32).astype(dt)
    v = jax.random.normal(kv, shape, jnp.float32).astype(dt)
    ct = jax.random.normal(kg, shape, jnp.float32).astype(dt)

    # attention FLOPs at this shape (fwd): 2 matmuls x 2*b*n*s^2*d, causal
    # halves the useful work but the kernels still run the masked tiles'
    # dots, so report dense FLOPs for the occupancy view
    flops_fwd = 2 * 2 * b * n * args.seq * args.seq * args.d

    from bench import host_fence

    def timed(fn, *xs):
        host_fence(fn(*xs))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            host_fence(fn(*xs))
        return (time.perf_counter() - t0) / args.iters

    rows = []
    # (block_q, block_k): symmetric points plus asymmetric K/V blocks — a
    # bigger K block amortizes HBM streaming without growing the q tile
    combos = list(dict.fromkeys(
        [(256, 256), (512, 512), (512, args.seq), (256, args.seq)]))
    from bench import knob_env

    for bwd_mode in ("split", "fused"):
        for block, block_k in combos:
            if args.seq % block or args.seq % block_k:
                continue
            # knob_env restores the pre-combo values (pop if unset) even on
            # error: the last combo's knobs must not leak out of main() and
            # poison an in-process caller that traces flash attention later
            with knob_env({"PFX_FLASH_BWD": bwd_mode,
                           "PFX_FLASH_BLOCK": block,
                           "PFX_FLASH_BLOCK_K": block_k}):
                from paddlefleetx_tpu.ops.flash_attention import flash_attention

                fwd = jax.jit(lambda a, b_, c: flash_attention(a, b_, c))

                def loss(a, b_, c):
                    return jnp.sum(
                        flash_attention(a, b_, c).astype(jnp.float32)
                        * ct.astype(jnp.float32)
                    )

                grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                try:
                    t_fwd = timed(fwd, q, k, v)
                    t_all = timed(grad, q, k, v)
                except Exception as e:  # noqa: BLE001 - report the combo, keep sweeping
                    rows.append({"bwd": bwd_mode, "block": block,
                                 "block_k": block_k,
                                 "error": str(e)[:200],
                                 "platform": jax.default_backend()})
                    print(json.dumps(rows[-1]))
                    continue
                row = {
                    "bwd": bwd_mode, "block": block, "block_k": block_k,
                    "dtype": args.dtype,
                    "fwd_ms": round(t_fwd * 1e3, 2),
                    "fwd_bwd_ms": round(t_all * 1e3, 2),
                    "fwd_tflops": round(flops_fwd / t_fwd / 1e12, 1),
                    # CPU-interpret smoke rows must never read as chip evidence
                    "platform": jax.default_backend(),
                }
                rows.append(row)
                print(json.dumps(row))

    with open(os.path.join(ROOT, "benchmarks", "kernel_results.jsonl"), "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
