"""GPT-6.7B fit evidence without multi-chip hardware (VERDICT r4 #5).

Compiles the 6.7B train step at REAL dims (hidden 4096, 32 layers, seq
1024, vocab 50304) over virtual CPU meshes via Engine(abstract_init=True)
— nothing is allocated; XLA's compiled-executable memory analysis gives
the per-device HBM budget, and the SPMD-clean compile proves the layout
partitions without involuntary rematerialization.

Layouts:
  sharding16   the reference's published recipe (fp16+sharding16+recompute
               on 2x8 V100-32G, projects/gpt/docs/hybrid_parallel.md:53,
               pretrain_gpt_6.7B_sharding16.yaml) as bf16 ZeRO-2 over a
               16-device fsdp mesh
  mp2pp4       the TPU-idiomatic v5p-8 layout: dp1 x mp2 x pp4, full
               recompute, grad accumulation 16 (global batch 128)

Budgets compared: v5p (95.7 GB/chip), v5e (16 GB/chip), V100-32G.

Writes benchmarks/fit_6p7b.json and prints one summary line per layout.

  python benchmarks/fit_6p7b.py [--layouts sharding16,mp2pp4]
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

GIB = 1024**3
HBM_BUDGETS = {"v5p": 95.7 * GIB, "v5e": 16.0 * GIB, "V100-32G": 32.0 * GIB}

LAYOUTS = {
    "sharding16": {
        "devices": 16,
        "overrides": [
            # the yaml's own recipe: ZeRO-2 over 16 devices, recompute on;
            # fp16+scaler on V100 becomes bf16 on TPU (configs/gpt/base)
            "Global.local_batch_size=8",
            "Global.micro_batch_size=8",
        ],
    },
    "mp2pp4": {
        "devices": 8,
        "overrides": [
            "Distributed.mp_degree=2",
            "Distributed.pp_degree=4",
            "Distributed.sharding.sharding_degree=1",
            "Distributed.sharding.sharding_stage=0",
            "Global.local_batch_size=128",
            "Global.micro_batch_size=8",
        ],
    },
    # the measured 1.3B-fit precision recipe (bf16 params + moments +
    # grads, no fp32 masters — bench_extra gpt1p3b) applied to 6.7B:
    # the reference's stage-2 memory story shards its fp32 masters inside
    # the optimizer, this engine's equivalent lever is multi_precision=False
    "sharding16_bf16": {
        "devices": 16,
        "overrides": [
            "Global.local_batch_size=8",
            "Global.micro_batch_size=8",
            "Optimizer.multi_precision=False",
            "Optimizer.moment_dtype=bfloat16",
            "Engine.mix_precision.main_grad=False",
        ],
    },
    "mp2pp4_bf16": {
        "devices": 8,
        "overrides": [
            "Distributed.mp_degree=2",
            "Distributed.pp_degree=4",
            "Distributed.sharding.sharding_degree=1",
            "Distributed.sharding.sharding_stage=0",
            "Global.local_batch_size=128",
            "Global.micro_batch_size=8",
            "Optimizer.multi_precision=False",
            "Optimizer.moment_dtype=bfloat16",
            "Engine.mix_precision.main_grad=False",
        ],
    },
    # ZeRO-3 (params sharded too): the TPU-idiomatic FSDP spelling of the
    # same 16-device budget — under bf16-params the stage-2 layout pays a
    # replicated fp32 optimizer-update temp (params stay whole per
    # device), which stage 3 shards away
    "zero3_16_bf16": {
        "devices": 16,
        "overrides": [
            "Distributed.sharding.sharding_stage=3",
            "Global.local_batch_size=8",
            "Global.micro_batch_size=8",
            "Optimizer.multi_precision=False",
            "Optimizer.moment_dtype=bfloat16",
            "Engine.mix_precision.main_grad=False",
        ],
    },
}


def _force_cpu(n_devices: int) -> None:
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        )
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n_devices}"
        )
    import jax

    # same rationale as __graft_entry__._provision_devices: the image's
    # sitecustomize force-registers the axon TPU platform whose tunnel
    # init can hang; this is BY DEFINITION a virtual-mesh validation
    jax.config.update("jax_platforms", "cpu")


def run_layout(name: str) -> dict:
    import numpy as np

    import jax

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import get_config

    spec = LAYOUTS[name]
    n_dev = spec["devices"]
    cfg = get_config(
        os.path.join(ROOT, "configs/gpt/pretrain_gpt_6.7B_sharding16.yaml"),
        overrides=spec["overrides"],
        num_devices=n_dev,
    )
    mesh = init_dist_env(cfg, devices=jax.devices()[:n_dev])
    module = build_module(cfg)
    seq = int(cfg.Model.max_position_embeddings)
    batch = int(cfg.Global.global_batch_size)
    with mesh:
        engine = Engine(cfg, module, mesh, abstract_init=True)
        stats = engine.memory_report({
            "tokens": ((batch, seq), np.int32),
            "labels": ((batch, seq), np.int32),
            "loss_mask": ((batch, seq), np.float32),
            "position_ids": ((batch, seq), np.int32),
        })
    n_params = sum(x.size for x in jax.tree.leaves(engine.state.params))
    peak = stats["peak_bytes_per_device_est"]
    row = {
        "layout": name,
        "devices": n_dev,
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "model": {
            "params_m": round(n_params / 1e6, 1),
            "hidden": int(cfg.Model.hidden_size),
            "layers": int(cfg.Model.num_layers),
            "seq": seq,
            "global_batch": batch,
            "accumulate_steps": int(engine.accumulate_steps),
        },
        "per_device_bytes": stats,
        "fits": {
            hw: bool(peak <= budget) for hw, budget in HBM_BUDGETS.items()
        },
        "peak_gib_per_device": round(peak / GIB, 2),
    }
    return row


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--layouts",
        default="sharding16,mp2pp4,sharding16_bf16,mp2pp4_bf16,zero3_16_bf16",
    )
    args = ap.parse_args(argv)
    names = [n.strip() for n in args.layouts.split(",") if n.strip()]
    bad = [n for n in names if n not in LAYOUTS]
    if bad:
        print(f"unknown layouts {bad}; have {sorted(LAYOUTS)}", file=sys.stderr)
        return 2

    _force_cpu(max(LAYOUTS[n]["devices"] for n in names))

    rows = []
    for name in names:
        row = run_layout(name)
        rows.append(row)
        print(json.dumps({
            "layout": row["layout"],
            "peak_gib_per_device": row["peak_gib_per_device"],
            "fits": row["fits"],
        }))

    out = os.path.join(ROOT, "benchmarks", "fit_6p7b.json")
    with open(out, "w") as f:
        json.dump({"rows": rows}, f, indent=1)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
