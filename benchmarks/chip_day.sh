#!/usr/bin/env bash
# Full measurement suite for the moment the axon TPU tunnel comes up.
#
# The round-3 verdict's three chip-gated items in one command: the headline
# bench (always-emit contract), the MFU-push knob sweep, the extra
# north-star cases (GPT-1.3B / ViT-B / ViT-L), and the profiler op table.
# Every piece carries its own deadline and emits honest rows on failure,
# so a tunnel that drops mid-suite still leaves a usable record.
#
# Usage: bash benchmarks/chip_day.sh        (run when a probe succeeds)
# The TPU watcher can invoke it automatically on tunnel recovery.
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/chip_day
TS=$(date -u +%Y%m%dT%H%M%S)
LOG=benchmarks/chip_day/run_${TS}.log
{
  echo "== chip_day $TS =="
  echo "== 1/5 kernel_bench (flash fwd/bwd, split x fused x blocks) =="
  timeout 600 python benchmarks/kernel_bench.py || echo "kernels rc=$?"
  echo "== 2/5 bench.py (headline, default knobs) =="
  BENCH_DEADLINE_S=600 python bench.py
  echo "== 3/5 sweep_bench (all combos) =="
  python benchmarks/sweep_bench.py --combos default --steps 10
  echo "== 4/5 bench_extra (1.3B / ViT-B / ViT-L) =="
  BENCH_EXTRA_DEADLINE_S=1800 python benchmarks/bench_extra.py
  echo "== 5/5 profile_bench (op table -> benchmarks/chip_day/profile_$TS) =="
  timeout 1200 python benchmarks/profile_bench.py \
    --log_dir "benchmarks/chip_day/profile_${TS}" || echo "profile rc=$?"
  echo "== chip_day done =="
} 2>&1 | tee "$LOG"
