#!/usr/bin/env bash
# Full measurement suite for the moment the axon TPU tunnel comes up.
#
# One command for a LONG window (the 2-min-probe watcher handles short
# ones): kernel microbench, headline, knob sweep, every bench_extra case
# (GPT-1.3B / ViT / ERNIE / Imagen / seq-4096), decode grid + serving,
# and the profiler op table.
# Every piece carries its own deadline and emits honest rows on failure,
# so a tunnel that drops mid-suite still leaves a usable record.
#
# Usage: bash benchmarks/chip_day.sh        (run when a probe succeeds)
# The TPU watcher can invoke it automatically on tunnel recovery.
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/chip_day
TS=$(date -u +%Y%m%dT%H%M%S)
LOG=benchmarks/chip_day/run_${TS}.log
{
  echo "== chip_day $TS =="
  echo "== 1/6 kernel_bench (flash fwd/bwd, split x fused x blocks) =="
  timeout 600 python benchmarks/kernel_bench.py || echo "kernels rc=$?"
  echo "== 2/6 bench.py (headline, default knobs) =="
  BENCH_DEADLINE_S=600 python bench.py
  echo "== 3/6 sweep_bench (all combos) =="
  python benchmarks/sweep_bench.py --combos default --steps 10
  echo "== 4/6 bench_extra (1.3B / ViT-B / ViT-L / ERNIE / Imagen / seq4096) =="
  BENCH_EXTRA_DEADLINE_S=2400 python benchmarks/bench_extra.py \
    --cases gpt1p3b,vit_b16,vit_l16,ernie_base,imagen_base64,gpt_seq4096
  echo "== 5/6 bench_decode (b8/b32 x greedy/top-p + bucketed serving) =="
  BENCH_DECODE_DEADLINE_S=1200 timeout 1300 python benchmarks/bench_decode.py \
    || echo "decode rc=$?"
  echo "== 6/6 profile_bench (op table -> benchmarks/chip_day/profile_$TS) =="
  timeout 1200 python benchmarks/profile_bench.py \
    --log_dir "benchmarks/chip_day/profile_${TS}" || echo "profile rc=$?"
  echo "== chip_day done =="
} 2>&1 | tee "$LOG"
