"""Bench knob sweep: run bench.py across tuning-knob combinations on the
real chip and append one JSON row per combo to benchmarks/sweep_results.jsonl.

The round-3 verdict's MFU push (docs/performance_tuning.md) needs measured
evidence for which lever moves the 345M headline: chunked CE (streams the
vocab so the fp32 logits buffer never materializes — enables bigger batch),
remat granularity, batch size, dropout impl.  This driver makes the whole
sweep one command the moment the axon tunnel is up:

  python benchmarks/sweep_bench.py [--combos default|quick] [--steps N]

Each combo runs bench.py as a subprocess (inheriting its signal-safe
always-emit contract) with a per-run deadline, so one wedged run cannot eat
the window.
"""

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "benchmarks", "sweep_results.jsonl")

# name -> env overrides on top of bench.py defaults (batch16, seq1024,
# selective remat, fused ln, rbg dropout, chunked CE off)
COMBOS = {
    "baseline_b16": {},
    "chunked_ce_b16": {"BENCH_CHUNKED_CE": "1"},
    "chunked_ce_b24": {"BENCH_CHUNKED_CE": "1", "BENCH_BATCH": "24"},
    "chunked_ce_b32": {"BENCH_CHUNKED_CE": "1", "BENCH_BATCH": "32"},
    "no_remat_b8": {"BENCH_RECOMPUTE": "0", "BENCH_BATCH": "8"},
    "no_remat_chunked_b12": {
        "BENCH_RECOMPUTE": "0", "BENCH_CHUNKED_CE": "1", "BENCH_BATCH": "12",
    },
    "full_remat_b32": {"BENCH_REMAT": "full", "BENCH_BATCH": "32"},
    "full_remat_chunked_b48": {
        "BENCH_REMAT": "full", "BENCH_CHUNKED_CE": "1", "BENCH_BATCH": "48",
    },
    "no_dropout_b16": {"BENCH_DROPOUT": "0.0"},
}
QUICK = ["baseline_b16", "chunked_ce_b16", "chunked_ce_b32"]


def run_combo(name: str, env_over: dict, steps: int, deadline_s: float) -> dict:
    env = dict(os.environ)
    env.update(env_over)
    env["BENCH_STEPS"] = str(steps)
    env["BENCH_DEADLINE_S"] = str(deadline_s)
    # one short probe: the caller already confirmed the tunnel is up
    env.setdefault("BENCH_PROBE_WINDOW_S", "120")
    t0 = time.time()
    row = {"combo": name, "env": env_over}
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py")],
            env=env, capture_output=True, text=True,
            timeout=deadline_s + 120,
        )
    except subprocess.TimeoutExpired as te:
        # a child wedged in native code past its own deadline machinery:
        # record the honest row and keep sweeping — one wedged run must
        # not eat the tunnel-up window
        row.update({"wall_s": round(time.time() - t0, 1),
                    "metric": "gpt345m_pretrain_throughput_per_chip",
                    "value": 0.0,
                    "unit": "tokens/s/chip (combo wedged past hard timeout)",
                    "vs_baseline": 0.0})
        if te.stderr:
            stderr = te.stderr
            if isinstance(stderr, bytes):
                stderr = stderr.decode("utf-8", "replace")
            row["stderr_tail"] = stderr[-800:]
        return row
    row["wall_s"] = round(time.time() - t0, 1)
    for line in out.stdout.splitlines():
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            row.update(parsed)
    if "value" not in row:
        row.update({"metric": "gpt345m_pretrain_throughput_per_chip",
                    "value": 0.0, "unit": f"no JSON (rc={out.returncode})",
                    "vs_baseline": 0.0})
    if row.get("value") == 0.0 and out.stderr:
        # a dead combo's cause (e.g. the OOM allocator report) must survive
        # into the sweep record — round 4's no-remat rows died with nothing
        # but an rc
        row["stderr_tail"] = out.stderr[-800:]
    return row


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--combos", default="default", help="default|quick|name,name,...")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--per-run-deadline", type=float, default=420.0)
    args = ap.parse_args(argv)

    if args.combos == "default":
        names = list(COMBOS)
    elif args.combos == "quick":
        names = QUICK
    else:
        names = [n.strip() for n in args.combos.split(",") if n.strip()]
        unknown = [n for n in names if n not in COMBOS]
        if unknown:
            # a typo must not turn the sweep into a silent no-op during
            # the narrow tunnel-up window
            ap.error(f"unknown combos {unknown}; have {sorted(COMBOS)}")

    best = None
    for name in names:
        row = run_combo(name, COMBOS[name], args.steps, args.per_run_deadline)
        print(json.dumps(row), flush=True)
        with open(OUT, "a") as f:
            f.write(json.dumps(row) + "\n")
        if row.get("value", 0.0) and (best is None or row["value"] > best["value"]):
            best = row
    if best:
        print(f"# best: {best['combo']} {best['value']} {best.get('unit', '')}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
