"""Capture a jax.profiler trace at the bench.py shape and print the top
time sinks (the MFU-push workflow: VERDICT r2 item 3).

Runs the same GPT-345M config as bench.py (same env knobs), traces a
window of steady-state steps, then emits the ProfilerHook summary views
(summary_ops.txt ranked by self time + hlo_stats.json + memory summary)
into --log_dir and prints the top table to stdout.

  python benchmarks/profile_bench.py [--log_dir ./profiler_log] [--steps 8]
"""

import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--log_dir", default="./profiler_log")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args(argv)

    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()

    from bench import wait_for_backend

    platform = os.environ.get("PFX_PLATFORM", "").lower()
    if platform in ("", "tpu", "axon") and not wait_for_backend():
        print("tpu backend unreachable", file=sys.stderr)
        sys.exit(1)

    import jax
    import numpy as np

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs
    from paddlefleetx_tpu.utils.profiler import ProfilerHook


    n_dev = jax.device_count()
    batch = int(os.environ.get("BENCH_BATCH", 16)) * n_dev
    seq = int(os.environ.get("BENCH_SEQ", 1024))

    cfg = AttrDict.from_nested(
        {
            "Global": {
                "global_batch_size": batch,
                "micro_batch_size": batch // n_dev,
                "seed": 1024,
                "prng_impl": os.environ.get("BENCH_PRNG", "rbg"),
            },
            "Engine": {
                "max_steps": args.steps + 4,
                "eval_freq": 0,
                "logging_freq": 10**9,
                "mix_precision": {"enable": True, "dtype": "bfloat16"},
                "save_load": {"save_steps": 0},
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 50304,
                "hidden_size": int(os.environ.get("BENCH_HIDDEN", 1024)),
                "num_layers": int(os.environ.get("BENCH_LAYERS", 24)),
                "num_attention_heads": 16,
                "max_position_embeddings": seq,
                "hidden_dropout_prob": float(os.environ.get("BENCH_DROPOUT", 0.1)),
                "attention_probs_dropout_prob": float(os.environ.get("BENCH_DROPOUT", 0.1)),
                "attn_impl": os.environ.get("BENCH_ATTN", "flash"),
                "use_recompute": os.environ.get("BENCH_RECOMPUTE", "1") == "1",
                "recompute_granularity": os.environ.get("BENCH_REMAT", "selective"),
                "use_fused_ln": os.environ.get("BENCH_FUSED_LN", "1") == "1",
                "use_chunked_ce": os.environ.get("BENCH_CHUNKED_CE", "0") == "1",
                "scan_unroll": int(os.environ.get("BENCH_SCAN_UNROLL", 1)),
            },
            "Distributed": {},
            "Optimizer": {
                "name": "FusedAdamW",
                "weight_decay": 0.01,
                "beta1": 0.9,
                "beta2": 0.95,
                "lr": {"name": "Constant", "learning_rate": 1e-4},
                "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
            },
        }
    )
    cfg = process_configs(cfg, num_devices=n_dev)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    rng = np.random.default_rng(0)
    host_batch = {
        "tokens": rng.integers(0, 50304, (batch, seq)).astype(np.int64),
        "labels": rng.integers(0, 50304, (batch, seq)).astype(np.int64),
        "loss_mask": np.ones((batch, seq), np.float32),
        "position_ids": np.tile(np.arange(seq), (batch, 1)),
    }

    hook = ProfilerHook(
        {
            "enable": True,
            # warmup 3 compile+steady steps before the window
            "scheduler": [4, 4 + args.steps],
            "log_dir": args.log_dir,
            "summary_top": args.top,
        }
    )
    with mesh:
        engine = Engine(cfg, module, mesh)
        dev_batch = engine._put_batch(host_batch)
        for step in range(1, 5 + args.steps):
            engine.state, m = engine.train_step(engine.state, dev_batch)
            float(m["loss"])  # keep each step synchronous inside the trace
            hook.step(step)
    hook.close()
    print(open(os.path.join(os.path.abspath(args.log_dir), "summary_ops.txt")).read())


if __name__ == "__main__":
    main()
