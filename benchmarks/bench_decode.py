"""Decode/serving throughput bench: KV-cache generation on GPT-345M.

The training side has deep throughput evidence (headline, sweep, 1.3B,
ViT); this measures the INFERENCE side of the stack at realistic shapes:

  decode cases   batch {8, 32} x prompt 128 x dec_len 256, greedy AND
                 top-p sampling (the `ops/sampling.py` top-k-prefilter
                 nucleus sampler that replaces the reference's CUDA
                 topp_sampling kernel, ppfleetx/ops/topp_sampling.cu:377);
                 `*_legacy` variants re-trace with PFX_DECODE_ATTN=dense +
                 PFX_DECODE_SCAN=1 (pre-overhaul attend-over-the-whole-
                 cache scan) so every window emits an A/B row pair
  serving case   `core.serving.GenerationServer` bucketed-batch traffic
                 (mixed request sizes riding the power-of-two batch
                 buckets), i.e. the deploy path the reference serves via
                 its static-graph predictor (single_model.py:1190-1320)

Comparison point: the reference ships the fused sampler and a measured
generation path but publishes NO machine-readable decode tokens/s, so
every row reports absolute new-tokens/s/chip with vs_baseline null —
evidence artifacts, not ratios.

Contract: same parent/child split as bench.py — the parent never imports
jax, stays SIGTERM-responsive, and emits an honest value:0.0 row for any
case the child did not finish.  Rows append to
benchmarks/results_decode.jsonl.

  python benchmarks/bench_decode.py [--cases b8_greedy,b8_topp,...]
      [--prompt 128] [--dec 256] [--iters 3]
"""

import argparse
import json
import os
import sys
import time
import traceback

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# PFX_DECODE_RESULTS: contract tests / smoke runs point this at a tmp
# file so CPU rows don't accumulate in the tracked evidence artifact
OUT_PATH = os.environ.get(
    "PFX_DECODE_RESULTS", os.path.join(ROOT, "benchmarks", "results_decode.jsonl")
)

# BENCH_DEC_DTYPE: bf16 is the honest chip bench dtype (near-tie argmax
# flips between schedulers are counted in greedy_divergent_rows, not
# hidden); the CPU contract smoke forces float32, where greedy
# continuous-vs-coalesce divergence must be exactly ZERO
DTYPE = os.environ.get("BENCH_DEC_DTYPE", "bfloat16")

# case -> (batch, decode_strategy, legacy).  top_p 0.9 matches the
# reference's default nucleus setting (projects/gpt/docs generation
# configs).  ``*_legacy`` cases re-run the same shape with
# PFX_DECODE_ATTN=dense + PFX_DECODE_SCAN=1 (the attend-over-the-whole-
# cache scan path from before the decode overhaul), so every window
# produces an A/B row pair without code changes.
CASES = {
    "b8_greedy": (8, "greedy_search", False),
    "b8_greedy_legacy": (8, "greedy_search", True),
    "b8_topp": (8, "sampling", False),
    "b8_topp_legacy": (8, "sampling", True),
    "b32_greedy": (32, "greedy_search", False),
    "b32_greedy_legacy": (32, "greedy_search", True),
    "b32_topp": (32, "sampling", False),
    "b32_topp_legacy": (32, "sampling", True),
    # speculative + quantized A/B rows: each case runs its OWN baseline
    # on the same prompts and reports both sides in one row (value =
    # the feature side; baseline_tokens_per_s alongside).  The spec case
    # uses a REPETITIVE prompt — the self-draft lookup's best case, the
    # regime the acceptance contract pins (accept_rate >= 0.5).
    "b8_greedy_spec4": (8, "greedy_search", False),
    "b8_greedy_kvint8": (8, "greedy_search", False),
    "serving": (None, None, False),  # GenerationServer bucketed-batch traffic
    # staggered-arrival A/B: the SAME fixed-seed Poisson-ish request
    # trace through the continuous-batching scheduler vs the PR 3
    # coalescer — emits TWO rows (continuous + coalesce) reporting
    # delivered tokens/s and p99 TTFT, the head-of-line-blocking evidence
    "staggered": (None, None, False),
    # prefix-heavy staggered A/B: N requests sharing one long system
    # prefix replayed against the continuous scheduler with the
    # shared-prefix KV cache ON vs OFF — emits TWO rows (cached +
    # nocache) reporting TTFT percentiles, prefill tokens COMPUTED, and
    # the hit rate (docs/serving.md "Prefix cache")
    "prefix": (None, None, False),
    # dispatch-ahead A/B: the SAME greedy batch through two continuous
    # schedulers differing only in ``dispatch_ahead`` — emits TWO rows
    # (ahead + sync) reporting delivered tokens/s and ``host_gap_ms``,
    # the per-device-step host gap the overlap exists to hide
    # (docs/decode_path.md "Dispatch-ahead decode")
    "overlap": (None, None, False),
    # host-RAM spill tier A/B: the SAME prefix-heavy staggered trace
    # against a prefix budget too small to keep both prefix families
    # resident, with the spill tier ON vs OFF — emits TWO rows (on +
    # off) reporting readmits, prefill tokens COMPUTED (strictly fewer
    # with spill ON when anything readmitted), and honest greedy
    # divergence (docs/serving.md "KV lifecycle")
    "spill": (None, None, False),
    # two-tenant isolation A/B: a flood tenant bursts at t=0 while a
    # trickle tenant arrives staggered into the backlog, replayed
    # through a slot-starved continuous scheduler with weighted-fair
    # DRR ON vs single-class FCFS — emits TWO rows (fair + fcfs)
    # reporting per-tenant TTFT percentiles, the isolation evidence
    # (docs/serving.md "Multi-tenant isolation")
    "tenant": (None, None, False),
}

# env spellings of the two decode paths (read at trace time).  BOTH are
# pinned explicitly around each case — a baseline row must measure the
# overhauled path even if the caller's shell has PFX_DECODE_ATTN=dense
# left over from an A/B session, or the evidence artifact silently
# mislabels (the exact failure the loud-knob convention exists to stop).
_LEGACY_ENV = {"PFX_DECODE_ATTN": "dense", "PFX_DECODE_SCAN": "1"}
_OVERHAUL_ENV = {"PFX_DECODE_ATTN": "blocked", "PFX_DECODE_SCAN": "0"}


def _emit(row: dict) -> None:
    line = json.dumps(row)
    print(line, flush=True)
    with open(OUT_PATH, "a") as f:
        f.write(line + "\n")


def _metrics_for(name: str) -> list:
    """Metric names a case emits (staggered emits its A/B pair)."""
    if name == "serving":
        return ["gpt345m_serving_bucketed"]
    if name == "staggered":
        return ["gpt345m_decode_staggered_continuous",
                "gpt345m_decode_staggered_coalesce"]
    if name == "prefix":
        return ["gpt345m_decode_prefix_cached",
                "gpt345m_decode_prefix_nocache"]
    if name == "overlap":
        return ["gpt345m_decode_overlap_ahead",
                "gpt345m_decode_overlap_sync"]
    if name == "spill":
        return ["gpt345m_decode_spill_on",
                "gpt345m_decode_spill_off"]
    if name == "tenant":
        return ["gpt345m_decode_tenant_fair",
                "gpt345m_decode_tenant_fcfs"]
    return [f"gpt345m_decode_{name}"]


def _metric(name: str) -> str:
    return _metrics_for(name)[0]


def _parse_cases(cases_arg: str) -> list:
    out = []
    for name in cases_arg.split(","):
        name = name.strip()
        if name not in CASES:
            print(f"unknown case {name!r}; have {sorted(CASES)}", file=sys.stderr)
            continue
        out.append(name)
    return out


def _mfu_fields(cfg, per_chip_tokens_per_s: float) -> dict:
    """Hardware-normalized fields for a decode/serving row: the repo-wide
    analytic estimator on its forward-only basis (2·N per token — decode
    runs no backward) against the per-device-kind peak
    (docs/observability.md).  Same estimator as bench.py and the engine's
    step records, so BENCH_*.json trajectories compare on one definition."""
    from paddlefleetx_tpu.utils import telemetry

    flops_tok = telemetry.model_flops_per_token(cfg, backward=False)
    peak = telemetry.peak_flops()
    out = {"tokens_per_sec": round(per_chip_tokens_per_s, 1)}
    if flops_tok and peak:
        out["mfu"] = round(per_chip_tokens_per_s * flops_tok / peak, 6)
    return out


def _gpt_cfg(args):
    from paddlefleetx_tpu.models.gpt.config import GPTConfig

    return GPTConfig(
        vocab_size=50304, hidden_size=args.hidden, num_layers=args.layers,
        num_attention_heads=16,
        max_position_embeddings=args.prompt + args.dec,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        dtype=DTYPE,
    )


def run_decode_case(name: str, args, params_cache: dict) -> dict:
    import jax

    from paddlefleetx_tpu.models.gpt import model as gpt
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate

    batch, strategy, legacy = CASES[name]
    cfg = _gpt_cfg(args)
    gen = GenerationConfig(
        decode_strategy=strategy, max_dec_len=args.dec,
        top_p=0.9 if strategy == "sampling" else 1.0,
        temperature=1.0,
    )
    if "params" not in params_cache:
        params_cache["params"] = gpt.init(cfg, jax.random.key(0))
    params = params_cache["params"]
    prompts = jax.random.randint(
        jax.random.key(1), (batch, args.prompt), 0, cfg.vocab_size
    )
    key = jax.random.key(2)

    from bench import host_fence, knob_env

    with knob_env(_LEGACY_ENV if legacy else _OVERHAUL_ENV):
        fn = jax.jit(lambda p, ids, k: generate(p, ids, cfg, gen, key=k))
        # one-element host fetch per iteration (bench.host_fence): the axon
        # runtime's block_until_ready has been observed returning while
        # device work is still pending — the 2026-07-31 19:00Z rows showing
        # 19M-160M "tok/s" were pure dispatch cost.
        host_fence(fn(params, prompts, key))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            host_fence(fn(params, prompts, key))
        dt = (time.perf_counter() - t0) / args.iters

    return {
        "metric": _metric(name), "value": round(batch * args.dec / dt, 1),
        "unit": "new tokens/s/chip", "vs_baseline": None,
        "batch": batch, "prompt_len": args.prompt, "dec_len": args.dec,
        "strategy": strategy,
        "decode_path": "legacy(dense+scan)" if legacy else "overhauled",
        "per_token_ms": round(dt / args.dec * 1e3, 3),
        **_mfu_fields(cfg, batch * args.dec / dt),
        "platform": jax.default_backend(),
    }


def _delivered(rows, eos_token_id: int) -> int:
    """Delivered tokens (cut at EOS) — both A/B sides of a greedy pair
    deliver the same count when token-identical, and the honest count
    when not."""
    total = 0
    for row in rows.tolist():
        if eos_token_id in row:
            row = row[: row.index(eos_token_id)]
        total += len(row)
    return total


def run_spec_case(name: str, args, params_cache: dict) -> dict:
    """Speculative-vs-baseline A/B on the SAME repetitive prompts: one
    row whose ``value`` is the speculative tokens/s, carrying the
    baseline rate, the measured acceptance rate, and the count of rows
    whose greedy output diverged (must be 0 — greedy speculation is
    token-identical by construction; bf16 near-ties are counted, not
    hidden)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddlefleetx_tpu.models.gpt import model as gpt
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate
    from paddlefleetx_tpu.ops.speculative import SpecConfig

    batch, strategy, _ = CASES[name]
    k = int(name.rsplit("spec", 1)[1])
    # a floor on the decode window: acceptance is a STEADY-STATE metric —
    # the first iteration's drafts derive from the prompt before the
    # model's own output loop establishes, so a handful of decode steps
    # under-reports the rate every longer window sustains (the row
    # reports the dec_len it actually ran)
    dec = max(int(args.dec), 24)
    import dataclasses

    cfg = dataclasses.replace(
        _gpt_cfg(args), max_position_embeddings=args.prompt + dec
    )
    gen = GenerationConfig(decode_strategy=strategy, max_dec_len=dec)
    # the extended context keys its own position table: params are cached
    # per context length (the plain cases keep sharing theirs)
    pkey = ("params", cfg.max_position_embeddings)
    if pkey not in params_cache:
        params_cache[pkey] = gpt.init(cfg, jax.random.key(0))
    params = params_cache[pkey]
    # repetitive prompt: a short token cycle fills the window, so the
    # n-gram lookup's needle always has an earlier occurrence
    cycle = np.array([11, 23, 7, 41], np.int32)
    prompt_row = np.tile(cycle, -(-args.prompt // len(cycle)))[: args.prompt]
    prompts = jnp.asarray(np.tile(prompt_row, (batch, 1)))
    key = jax.random.key(2)
    spec = SpecConfig(draft_k=k)

    from bench import host_fence, knob_env

    with knob_env(_OVERHAUL_ENV):
        base_fn = jax.jit(lambda p, ids, kk: generate(p, ids, cfg, gen, key=kk))
        spec_fn = jax.jit(lambda p, ids, kk: generate(
            p, ids, cfg, gen, key=kk, spec=spec, return_spec_stats=True))
        base_out = base_fn(params, prompts, key)
        host_fence(base_out)  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            host_fence(base_fn(params, prompts, key))
        dt_base = (time.perf_counter() - t0) / args.iters
        spec_out, (prop, acc) = spec_fn(params, prompts, key)
        host_fence(spec_out)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            host_fence(spec_fn(params, prompts, key)[0])
        dt_spec = (time.perf_counter() - t0) / args.iters

    base_rows = np.asarray(base_out)
    spec_rows = np.asarray(spec_out)
    divergent = int((base_rows != spec_rows).any(axis=1).sum())
    delivered = _delivered(spec_rows, gen.eos_token_id)
    prop, acc = int(prop), int(acc)
    toks = delivered / dt_spec
    return {
        "metric": _metric(name), "value": round(toks, 1),
        "unit": "new tokens/s/chip (speculative)", "vs_baseline": None,
        "batch": batch, "prompt_len": args.prompt, "dec_len": dec,
        "strategy": strategy, "decode_path": "overhauled",
        "draft_k": k, "drafter": "ngram",
        "baseline_tokens_per_s": round(delivered / dt_base, 1),
        "speedup": round(dt_base / dt_spec, 3),
        "accept_rate": round(acc / prop, 4) if prop else 0.0,
        "spec_proposed": prop, "spec_accepted": acc,
        "greedy_divergent_rows": divergent,
        **_mfu_fields(cfg, toks),
        "platform": jax.default_backend(),
    }


def run_kvint8_case(name: str, args, params_cache: dict) -> dict:
    """int8-KV-vs-native A/B on the same prompts: ``value`` is the int8
    tokens/s (the HBM-bytes win is chip evidence — CPU rows pay the
    dequant multiplies without the bandwidth relief), with the native
    rate and honest divergence count alongside."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddlefleetx_tpu.models.gpt import model as gpt
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate

    batch, strategy, _ = CASES[name]
    cfg = _gpt_cfg(args)
    gen = GenerationConfig(decode_strategy=strategy, max_dec_len=args.dec)
    if "params" not in params_cache:
        params_cache["params"] = gpt.init(cfg, jax.random.key(0))
    params = params_cache["params"]
    prompts = jax.random.randint(
        jax.random.key(1), (batch, args.prompt), 0, cfg.vocab_size
    )
    key = jax.random.key(2)

    from bench import host_fence, knob_env

    outs, rates = {}, {}
    for kv in ("bf16", "int8"):
        with knob_env({**_OVERHAUL_ENV, "PFX_KV_DTYPE": kv}):
            fn = jax.jit(lambda p, ids, kk: generate(p, ids, cfg, gen, key=kk))
            out = fn(params, prompts, key)
            host_fence(out)  # compile + warm
            t0 = time.perf_counter()
            for _ in range(args.iters):
                host_fence(fn(params, prompts, key))
            dt = (time.perf_counter() - t0) / args.iters
            outs[kv] = np.asarray(out)
            rates[kv] = _delivered(outs[kv], gen.eos_token_id) / dt

    divergent = int((outs["bf16"] != outs["int8"]).any(axis=1).sum())
    return {
        "metric": _metric(name), "value": round(rates["int8"], 1),
        "unit": "new tokens/s/chip (int8 KV cache)", "vs_baseline": None,
        "batch": batch, "prompt_len": args.prompt, "dec_len": args.dec,
        "strategy": strategy, "decode_path": "overhauled",
        "kv_dtype": "int8",
        "baseline_tokens_per_s": round(rates["bf16"], 1),
        "divergent_rows": divergent,
        **_mfu_fields(cfg, rates["int8"]),
        "platform": jax.default_backend(),
    }


def run_serving_case(args) -> dict:
    """Bucketed-batch serving throughput: mixed request sizes through
    GenerationServer, measuring delivered new-tokens/s including the
    bucket-padding + host round-trip overhead the raw decode rows skip."""
    import jax
    import numpy as np

    server = _serving_server(args)  # sampling(top_p=0.9), the shared cfg

    rng = np.random.default_rng(0)
    # mixed client batch sizes -> power-of-two buckets 8 and 32; two
    # distinct request shapes exercise the bucket cache, repeats reuse it
    sizes = [8, 32, 8, 32]
    reqs = [
        [rng.integers(1, 50304, args.prompt).tolist() for _ in range(n)]
        for n in sizes
    ]
    from bench import knob_env

    with knob_env(_OVERHAUL_ENV):  # row is labeled "overhauled": pin it
        for req in reqs[:2]:  # compile both buckets outside the timed window
            server.generate_ids(req)
        t0 = time.perf_counter()
        delivered = 0
        for req in reqs:
            outs = server.generate_ids(req)
            delivered += sum(len(o) for o in outs)
        dt = time.perf_counter() - t0
    # the decode loop is bounded at batch*dec_len new tokens per request
    # (the while_loop can exit earlier once every row emits EOS, but with
    # random weights EOS is a ~1/vocab draw, so the bound is what runs);
    # report computed tokens/s as the throughput value and delivered
    # tokens/s alongside; normalized per chip like bench_extra (the dp
    # mesh spreads the batch)
    n_dev = jax.device_count()
    computed = sum(sizes) * args.dec
    return {
        "metric": _metric("serving"), "value": round(computed / dt / n_dev, 1),
        "unit": "new tokens/s/chip (bucketed serving)", "vs_baseline": None,
        "request_sizes": sizes, "prompt_len": args.prompt, "dec_len": args.dec,
        "delivered_tokens_per_s": round(delivered / dt / n_dev, 1),
        "strategy": "sampling(top_p=0.9)",
        "decode_path": "overhauled",
        "jit_traces": server.stats.get("traces"),
        **_mfu_fields(server.module.config, computed / dt / n_dev),
        "platform": jax.default_backend(),
    }


def _serving_server(args, *, greedy: bool = False):
    """One tiny-or-real GenerationServer for the serving/staggered cases."""
    import jax

    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    raw = {
        "Global": {"global_batch_size": 8, "seed": 7},
        "Engine": {"mix_precision": {"enable": False},
                   "save_load": {"save_steps": 0}},
        "Model": {
            "module": "GPTModule",
            "vocab_size": 50304, "hidden_size": args.hidden,
            "num_layers": args.layers, "num_attention_heads": 16,
            "max_position_embeddings": args.prompt + args.dec,
            "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
            "dtype": DTYPE,
        },
        "Distributed": {},
        "Optimizer": {"name": "FusedAdamW",
                      "lr": {"name": "Constant", "learning_rate": 1e-4}},
        "Generation": {
            "max_dec_len": args.dec,
            "decode_strategy": "greedy_search" if greedy else "sampling",
            "top_p": 0.9, "pad_to_multiple": args.prompt,
            "eos_token_id": 50256, "pad_token_id": 0,
        },
    }
    cfg = process_configs(AttrDict.from_nested(raw),
                          num_devices=jax.device_count())
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    return GenerationServer(cfg, mesh, module)


def _staggered_trace(n: int, mean_gap_s: float):
    """Fixed-seed Poisson-ish arrival offsets (exponential inter-arrival
    gaps, cumulative) — deterministic across runs, no wall-clock
    randomness, per the bench-contract rules."""
    import numpy as np

    rng = np.random.default_rng(42)
    gaps = rng.exponential(mean_gap_s, size=n)
    gaps[0] = 0.0
    return np.cumsum(gaps)


def _drive_staggered(submit, offsets, prompts, max_new, tenants=None):
    """Replay one arrival trace against a scheduler ``submit`` callable;
    returns (per-request TTFT seconds, per-request output rows, wall
    seconds).  TTFT here is submit->resolved: the serving definition for
    a non-streaming decode (tools/serve.py span semantics).  ``tenants``
    (optional, per-request labels) is forwarded as the ``tenant=``
    keyword — the multi-tenant case's fair side; ``None`` keeps the
    single-class submit shape every other case uses."""
    import threading

    n = len(prompts)
    ttft = [None] * n
    outs = [None] * n
    errs = [None] * n
    t0 = time.perf_counter()

    def worker(i):
        time.sleep(max(0.0, offsets[i] - (time.perf_counter() - t0)))
        t_sub = time.perf_counter()
        try:
            if tenants is None:
                fut = submit([prompts[i]], max_new)
            else:
                fut = submit([prompts[i]], max_new, tenant=tenants[i])
            rows = fut.result(timeout=600)
            ttft[i] = time.perf_counter() - t_sub
            outs[i] = rows[0]
        except Exception as e:  # noqa: BLE001 — recorded, parent stays honest
            errs[i] = e

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    bad = [e for e in errs if e is not None]
    if bad:
        raise RuntimeError(f"{len(bad)}/{n} staggered requests failed: {bad[0]}")
    return ttft, outs, wall


def run_staggered_case(args) -> list:
    """Continuous-vs-coalesce under the SAME staggered arrival trace.

    N single-prompt greedy requests arrive at fixed-seed Poisson-ish
    offsets scaled to ~25% of a single warm decode: most arrivals land
    while an earlier decode is mid-flight — exactly the head-of-line
    case iteration-level scheduling exists for.  The coalescer can only
    batch requests that are WAITING together, so late arrivals eat whole
    decode windows; the continuous scheduler admits them at the next
    step boundary.  Both paths deliver token-identical greedy output
    (asserted: the A/B is fair or the row is invalid)."""
    import jax
    import numpy as np

    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )
    from paddlefleetx_tpu.core.request_queue import RequestQueue

    from bench import knob_env

    n_req = int(os.environ.get("BENCH_STAGGER_N", 6))
    gap_frac = float(os.environ.get("BENCH_STAGGER_GAP", 0.5))
    server = _serving_server(args, greedy=True)
    rng = np.random.default_rng(1)
    prompts = [
        rng.integers(1, 50304, args.prompt).tolist() for _ in range(n_req)
    ]

    rows = []
    with knob_env(_OVERHAUL_ENV):
        # calibrate: one warm single-request decode bounds the gap scale
        server.generate_ids([prompts[0]], max_dec_len=args.dec)
        t0 = time.perf_counter()
        ref = [server.generate_ids([p], max_dec_len=args.dec)[0]
               for p in prompts]
        t_one = (time.perf_counter() - t0) / n_req
        offsets = _staggered_trace(n_req, mean_gap_s=gap_frac * t_one)

        # -- continuous: iteration-level admission --------------------
        engine = PagedDecodeEngine(server, max_batch=max(8, n_req))
        sched = ContinuousScheduler(engine, max_depth=2 * n_req)
        sched.warmup([args.prompt])
        sched.start()
        ttft_cb, outs_cb, wall_cb = _drive_staggered(
            sched.submit, offsets, prompts, args.dec
        )
        sched.shutdown(timeout=60)
        # fairness: both paths must DELIVER the same token counts or the
        # tokens/s A/B is invalid.  Exact token identity is the f32 test
        # contract (tests/test_continuous_batching.py); the bench model
        # runs bf16 where random-init logits carry near-ties that flip
        # argmax between float-equivalent summation orders — count the
        # divergent rows honestly instead of failing the row
        if [len(o) for o in outs_cb] != [len(r) for r in ref]:
            raise RuntimeError(
                "continuous staggered DELIVERED COUNTS diverged from the "
                "sequential reference — the tokens/s A/B would be unfair"
            )
        divergent = sum(1 for a, b in zip(outs_cb, ref) if a != b)
        toks_cb = sum(len(o) for o in outs_cb)

        # -- coalesce: the PR 3 queue over the same server -------------
        # warm every power-of-two batch bucket a coalesced burst can land
        # on (exactly what tools/serve.py does at boot) so the A/B
        # measures scheduling, not a mid-traffic compile
        b = 1
        while b <= 8:
            server.generate_ids([prompts[0]] * b, max_dec_len=args.dec)
            b *= 2
        queue = RequestQueue(
            lambda ps, mx: server.generate_ids(ps, max_dec_len=mx),
            max_depth=2 * n_req, max_coalesce=8,
        )
        queue.start()
        ttft_co, outs_co, wall_co = _drive_staggered(
            lambda ps, mx: queue.submit(
                ps, mx, coalesce_key=(args.prompt, args.dec)
            ),
            offsets, prompts, args.dec,
        )
        queue.shutdown(timeout=60)
        toks_co = sum(len(o) for o in outs_co)

    n_dev = jax.device_count()

    def row(metric, ttft, toks, wall, extra):
        r = {
            "metric": metric, "value": round(toks / wall / n_dev, 1),
            "unit": "delivered new tokens/s/chip (staggered arrivals)",
            "vs_baseline": None,
            "arrivals": n_req, "prompt_len": args.prompt,
            "dec_len": args.dec,
            "mean_gap_s": round(float(gap_frac * t_one), 4),
            "single_decode_s": round(float(t_one), 4),
            "p50_ttft_s": round(float(np.quantile(ttft, 0.5)), 4),
            "p99_ttft_s": round(float(np.quantile(ttft, 0.99)), 4),
            "strategy": "greedy_search",
            "decode_path": "overhauled",
            **_mfu_fields(server.module.config, toks / wall / n_dev),
            "platform": jax.default_backend(),
        }
        r.update(extra)
        return r

    rows.append(row(
        "gpt345m_decode_staggered_continuous", ttft_cb, toks_cb, wall_cb,
        {"scheduler": "continuous", "jit_traces": engine.stats["traces"],
         "steps": engine.stats["steps"],
         "greedy_divergent_rows": divergent},
    ))
    rows.append(row(
        "gpt345m_decode_staggered_coalesce", ttft_co, toks_co, wall_co,
        {"scheduler": "coalesce"},
    ))
    return rows


def run_tenant_case(args) -> list:
    """Weighted-fair DRR vs single-class FCFS under the SAME two-tenant
    arrival trace (docs/serving.md "Multi-tenant isolation").

    A flood tenant bursts every request at t=0 into a deliberately
    slot-starved continuous engine (max_batch=2: the backlog is the
    point); a trickle tenant's requests land staggered INSIDE that
    backlog window.  The fair side labels submissions and weights the
    trickle tenant 8:1, so DRR hands it the next free slot ahead of the
    flood's queue; the FCFS side replays the identical trace through
    the same scheduler with every request in one class, so the trickle
    waits behind the whole burst.  Per-tenant TTFT percentiles are the
    row payload — the contract pins fair trickle-p99 <= fcfs
    trickle-p99 and exact greedy token identity at the f32 smoke dtype
    (both sides decode the same rows on the same engine; bf16 chip rows
    count near-tie argmax flips honestly instead)."""
    import jax
    import numpy as np

    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )
    from paddlefleetx_tpu.core.tenancy import TenantConfig

    from bench import knob_env

    n_flood = int(os.environ.get("BENCH_TENANT_FLOOD", 6))
    n_trickle = int(os.environ.get("BENCH_TENANT_TRICKLE", 3))
    n_req = n_flood + n_trickle
    server = _serving_server(args, greedy=True)
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(1, 50304, args.prompt).tolist() for _ in range(n_req)
    ]
    tenants = ["flood"] * n_flood + ["trickle"] * n_trickle

    with knob_env(_OVERHAUL_ENV):
        # calibrate: one warm single-request decode bounds the gap scale
        server.generate_ids([prompts[0]], max_dec_len=args.dec)
        t0 = time.perf_counter()
        ref = [server.generate_ids([p], max_dec_len=args.dec)[0]
               for p in prompts]
        t_one = (time.perf_counter() - t0) / n_req
        # flood burst at t=0; trickle arrivals start a quarter-decode in
        # and stagger from there — all inside the ~(n_flood/2)*t_one
        # backlog the burst creates on a 2-slot engine
        gap = 0.5 * t_one
        trickle_off = 0.25 * t_one + _staggered_trace(n_trickle, gap)
        offsets = np.concatenate([np.zeros(n_flood), trickle_off])

        def side(tenant_cfg, labels):
            engine = PagedDecodeEngine(server, max_batch=2)
            sched = ContinuousScheduler(
                engine, max_depth=2 * n_req, tenant_config=tenant_cfg
            )
            sched.warmup([args.prompt])
            sched.start()
            ttft, outs, wall = _drive_staggered(
                sched.submit, offsets, prompts, args.dec, tenants=labels
            )
            sched.shutdown(timeout=120)
            if [len(o) for o in outs] != [len(r) for r in ref]:
                raise RuntimeError(
                    "tenant-case DELIVERED COUNTS diverged from the "
                    "sequential reference — the TTFT A/B would be unfair"
                )
            divergent = sum(1 for a, b in zip(outs, ref) if a != b)
            return ttft, sum(len(o) for o in outs), wall, divergent

        fair_cfg = TenantConfig.from_obj(
            {"tenants": {"flood": {"weight": 1}, "trickle": {"weight": 8}}},
            where="bench tenant case",
        )
        fair = side(fair_cfg, tenants)
        # FCFS control: same trace, same engine shape, one class — a
        # single tenant queue degenerates to exactly the old FCFS pull
        fcfs = side(None, None)

    n_dev = jax.device_count()

    def row(metric, scheduler, res, extra):
        ttft, toks, wall, divergent = res
        flood_t = ttft[:n_flood]
        trickle_t = ttft[n_flood:]
        r = {
            "metric": metric, "value": round(toks / wall / n_dev, 1),
            "unit": "delivered new tokens/s/chip (two-tenant trace)",
            "vs_baseline": None,
            "arrivals": n_req, "flood_arrivals": n_flood,
            "trickle_arrivals": n_trickle,
            "prompt_len": args.prompt, "dec_len": args.dec,
            "mean_gap_s": round(float(gap), 4),
            "single_decode_s": round(float(t_one), 4),
            "scheduler": scheduler,
            "p50_ttft_s": round(float(np.quantile(ttft, 0.5)), 4),
            "p99_ttft_s": round(float(np.quantile(ttft, 0.99)), 4),
            "flood_p50_ttft_s": round(float(np.quantile(flood_t, 0.5)), 4),
            "flood_p99_ttft_s": round(float(np.quantile(flood_t, 0.99)), 4),
            "trickle_p50_ttft_s": round(float(np.quantile(trickle_t, 0.5)), 4),
            "trickle_p99_ttft_s": round(float(np.quantile(trickle_t, 0.99)), 4),
            "greedy_divergent_rows": divergent,
            "strategy": "greedy_search",
            "decode_path": "overhauled",
            **_mfu_fields(server.module.config, toks / wall / n_dev),
            "platform": jax.default_backend(),
        }
        r.update(extra)
        return r

    return [
        row("gpt345m_decode_tenant_fair", "fair-drr", fair,
            {"weights": {"flood": 1, "trickle": 8}}),
        row("gpt345m_decode_tenant_fcfs", "fcfs", fcfs, {}),
    ]


def run_prefix_case(args) -> list:
    """Shared-prefix cache ON vs OFF under the SAME prefix-heavy
    staggered trace.

    N greedy requests share one long system prefix (75% of the prompt,
    distinct tails) and arrive at fixed-seed staggered offsets.  Both
    sides run the continuous scheduler on identical engines except
    ``prefix_cache_blocks``; a PRIMER request carrying the bare prefix
    runs before each timed window (cache-off too — same warm-up work)
    so the cached side models the steady state where the system prefix
    is resident.  The cached row reports the hit rate and the prompt
    tokens actually COMPUTED — strictly fewer than cache-off whenever
    anything hit — plus TTFT percentiles; output token-identity across
    the two sides is counted honestly (divergent_rows must be 0 at the
    f32 contract dtype)."""
    import jax
    import numpy as np

    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )

    from bench import knob_env

    n_req = int(os.environ.get("BENCH_PREFIX_N", 6))
    gap_frac = float(os.environ.get("BENCH_STAGGER_GAP", 0.5))
    server = _serving_server(args, greedy=True)
    rng = np.random.default_rng(3)
    shared_len = max((args.prompt * 3 // 4), 2)
    shared = rng.integers(1, 50304, shared_len).tolist()
    prompts = [
        shared + rng.integers(1, 50304, args.prompt - shared_len).tolist()
        if args.prompt > shared_len else list(shared)
        for _ in range(n_req)
    ]

    with knob_env(_OVERHAUL_ENV):
        # calibrate the arrival gaps off one warm single decode
        server.generate_ids([prompts[0]], max_dec_len=args.dec)
        t0 = time.perf_counter()
        server.generate_ids([prompts[0]], max_dec_len=args.dec)
        t_one = time.perf_counter() - t0
        offsets = _staggered_trace(n_req, mean_gap_s=gap_frac * t_one)

        sides = {}
        for label, budget in (("nocache", 0), ("cached", 4096)):
            engine = PagedDecodeEngine(
                server, max_batch=max(8, n_req),
                prefix_cache_blocks=budget,
            )
            sched = ContinuousScheduler(engine, max_depth=2 * n_req)
            sched.warmup([args.prompt])
            sched.start()
            # primers, both OUTSIDE the timed window and identical on
            # both sides: the bare system prefix (on the cached side
            # this publishes its blocks) and one full prompt (on the
            # cached side its suffix compiles the chunk family, so the
            # timed window measures scheduling — not a first-hit
            # mid-traffic compile)
            sched.submit([shared], args.dec).result(timeout=600)
            sched.submit([prompts[0]], args.dec).result(timeout=600)
            # baselines AFTER the primers: the row reports the timed
            # window only (cumulative stats would count the second
            # primer's hit and push hit_rate past 1.0)
            tok0 = int(engine.stats["prefill_tokens"])
            pfx = engine.cache.prefix.stats
            h0, ht0 = int(pfx["hits"]), int(pfx["hit_tokens"])
            ttft, outs, wall = _drive_staggered(
                sched.submit, offsets, prompts, args.dec
            )
            sched.shutdown(timeout=60)
            sides[label] = {
                "ttft": ttft, "outs": outs, "wall": wall,
                "prefill_tokens": int(engine.stats["prefill_tokens"]) - tok0,
                "hits": int(pfx["hits"]) - h0,
                "hit_tokens": int(pfx["hit_tokens"]) - ht0,
                "traces": int(engine.stats["traces"]),
            }

    a, b = sides["cached"], sides["nocache"]
    if [len(o) for o in a["outs"]] != [len(o) for o in b["outs"]]:
        raise RuntimeError(
            "prefix-cache DELIVERED COUNTS diverged from cache-off — the "
            "TTFT/prefill A/B would be unfair"
        )
    divergent = sum(1 for x, y in zip(a["outs"], b["outs"]) if x != y)
    n_dev = jax.device_count()
    rows = []
    for label, side in (("cached", a), ("nocache", b)):
        toks = sum(len(o) for o in side["outs"])
        rows.append({
            "metric": f"gpt345m_decode_prefix_{label}",
            "value": round(toks / side["wall"] / n_dev, 1),
            "unit": "delivered new tokens/s/chip (prefix-heavy staggered)",
            "vs_baseline": None,
            "arrivals": n_req, "prompt_len": args.prompt,
            "dec_len": args.dec,
            "shared_prefix_len": shared_len,
            "mean_gap_s": round(float(gap_frac * t_one), 4),
            "single_decode_s": round(float(t_one), 4),
            "p50_ttft_s": round(float(np.quantile(side["ttft"], 0.5)), 4),
            "p99_ttft_s": round(float(np.quantile(side["ttft"], 0.99)), 4),
            "prefill_tokens": side["prefill_tokens"],
            "prefix_hits": side["hits"],
            "prefix_hit_tokens": side["hit_tokens"],
            "hit_rate": round(side["hits"] / n_req, 4),
            "greedy_divergent_rows": divergent,
            "jit_traces": side["traces"],
            "strategy": "greedy_search",
            "decode_path": "overhauled",
            "scheduler": "continuous",
            **_mfu_fields(server.module.config, toks / side["wall"] / n_dev),
            "platform": jax.default_backend(),
        })
    return rows


def run_overlap_case(args) -> list:
    """Dispatch-ahead ON vs OFF under the SAME greedy batch.

    One batched submission of N prompts through two continuous
    schedulers on identical engines, differing only in
    ``dispatch_ahead``.  Each side reports delivered tokens/s plus
    ``host_gap_ms`` — mean host time per device step spent with NO step
    in flight (the engine's ``host_gap_s``/``steps`` accounting).  The
    synchronous side pays the full commit-processing + scheduler-scan
    gap on EVERY step; the overlapped side only pays it on admission
    boundaries (chained dispatches land while the previous step is
    still in flight, gap zero by construction), so its ``host_gap_ms``
    must come out strictly lower — the contract test pins that.
    Greedy output token-identity across the sides is counted
    (``greedy_divergent_rows`` must be 0 at the f32 contract dtype)."""
    import jax
    import numpy as np

    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )

    from bench import knob_env

    n_req = int(os.environ.get("BENCH_OVERLAP_N", 8))
    server = _serving_server(args, greedy=True)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 50304, args.prompt).tolist()
               for _ in range(n_req)]

    with knob_env(_OVERHAUL_ENV):
        sides = {}
        for label, ahead in (("sync", False), ("ahead", True)):
            engine = PagedDecodeEngine(server, max_batch=n_req)
            sched = ContinuousScheduler(engine, max_depth=2 * n_req,
                                        dispatch_ahead=ahead)
            sched.warmup([args.prompt])
            sched.start()
            # primer OUTSIDE the timed window: compiles the decode
            # chunk family so the window measures stepping, not traces
            sched.submit([prompts[0]], args.dec).result(timeout=600)
            g0 = float(engine.stats["host_gap_s"])
            n0 = int(engine.stats["gap_steps"])
            s0 = int(engine.stats["steps"])
            tl0 = sched.time_ledger()
            t0 = time.perf_counter()
            outs = sched.submit(prompts, args.dec).result(timeout=600)
            wall = time.perf_counter() - t0
            # goodput off the scheduler's own time ledger, deltas over
            # the timed window only (the primer/warmup laps are out).
            # The numerator is DEVICE-COVERED wall: non-idle scheduler
            # time minus host_gap_s (host time the device sat starved
            # waiting for its next dispatch).  Attributed-bucket sums
            # (device_decode+readback) cannot discriminate the overlap
            # win — both sides book the device wait under readback —
            # but the gap is zero by construction when chained
            # dispatches land in flight, so covered/non-idle is the
            # honest "was the device fed" fraction.
            tl1 = sched.time_ledger()
            led = {k: tl1["buckets"][k] - tl0["buckets"][k]
                   for k in tl1["buckets"]}
            led_wall = max(tl1["wall_s"] - tl0["wall_s"], 1e-9)
            gap = float(engine.stats["host_gap_s"]) - g0
            non_idle = max(led_wall - led["idle"], 1e-9)
            covered = max(non_idle - gap, 0.0)
            sides[label] = {
                "outs": outs, "wall": wall,
                "host_gap_s": gap,
                "gap_steps": int(engine.stats["gap_steps"]) - n0,
                "steps": max(1, int(engine.stats["steps"]) - s0),
                "traces": int(engine.stats["traces"]),
                "goodput_frac": covered / non_idle,
                "device_util": covered / led_wall,
            }
            sched.shutdown(timeout=60)

    a, b = sides["ahead"], sides["sync"]
    divergent = sum(1 for x, y in zip(a["outs"], b["outs"]) if x != y)
    n_dev = jax.device_count()
    rows = []
    for label, side in (("ahead", a), ("sync", b)):
        toks = sum(len(o) for o in side["outs"])
        rows.append({
            "metric": f"gpt345m_decode_overlap_{label}",
            "value": round(toks / side["wall"] / n_dev, 1),
            "unit": "delivered new tokens/s/chip (dispatch-ahead A/B)",
            "vs_baseline": None,
            "dispatch_ahead": label == "ahead",
            "host_gap_ms": round(
                side["host_gap_s"] * 1000.0 / side["steps"], 4),
            # goodput ledger view of the same window: device-covered
            # fraction of non-idle scheduler wall (goodput_frac) and of
            # TOTAL wall (device_util) — dispatch-ahead must win the
            # former strictly (contract-pinned; its host gap is zero by
            # construction while sync pays it every step)
            "goodput_frac": round(side["goodput_frac"], 4),
            "device_util": round(side["device_util"], 4),
            "gap_steps": side["gap_steps"],
            "device_steps": side["steps"],
            "batch": n_req, "prompt_len": args.prompt,
            "dec_len": args.dec,
            "greedy_divergent_rows": divergent,
            "jit_traces": side["traces"],
            "strategy": "greedy_search",
            "decode_path": "overhauled",
            "scheduler": "continuous",
            **_mfu_fields(server.module.config,
                          toks / side["wall"] / n_dev),
            "platform": jax.default_backend(),
        })
    return rows


def run_spill_case(args) -> list:
    """Host-RAM spill tier ON vs OFF under the SAME prefix-heavy
    staggered trace with an arena prefix budget too small for the
    traffic.

    Two prefix families (A and B, one full KV block each) alternate at
    fixed-seed staggered offsets against a prefix budget of ONE block:
    every publication of one family evicts the other, so with the spill
    tier OFF each arrival recomputes its full prompt, while with the
    tier ON (``prefix_spill_bytes``) the evicted prefix demotes to host
    RAM and the next arrival of its family READMITS it instead.  Both
    sides run identical engines except ``prefix_spill_bytes`` and the
    same primers (bare prefixes publish the blocks, one full prompt per
    family compiles the post-hit suffix family outside the timed
    window).  The ON row reports readmits and the prompt tokens
    actually COMPUTED — strictly fewer than OFF whenever anything
    readmitted — and greedy output token-identity across the sides is
    counted honestly (``greedy_divergent_rows`` must be 0 at the f32
    contract dtype: a readmitted block is the bit-exact KV that was
    evicted)."""
    import jax
    import numpy as np

    from paddlefleetx_tpu.core.continuous_batching import (
        ContinuousScheduler,
        PagedDecodeEngine,
    )

    from bench import knob_env

    n_req = int(os.environ.get("BENCH_SPILL_N", 6))
    gap_frac = float(os.environ.get("BENCH_STAGGER_GAP", 0.5))
    block = 8  # small block so a tiny --prompt still carries full blocks
    server = _serving_server(args, greedy=True)
    rng = np.random.default_rng(13)
    shared_len = block
    tail_len = max(args.prompt - shared_len, block)
    fams = ("A", "B")
    pref = {f: rng.integers(1, 50304, shared_len).tolist() for f in fams}
    # primer tails are DISTINCT from the timed prompts: the timed
    # window must exercise prefix readmission, not whole-prompt reuse
    primer = {f: pref[f] + rng.integers(1, 50304, tail_len).tolist()
              for f in fams}
    prompts = [
        pref[fams[i % 2]] + rng.integers(1, 50304, tail_len).tolist()
        for i in range(n_req)
    ]

    with knob_env(_OVERHAUL_ENV):
        # calibrate the arrival gaps off one warm single decode
        server.generate_ids([prompts[0]], max_dec_len=args.dec)
        t0 = time.perf_counter()
        server.generate_ids([prompts[0]], max_dec_len=args.dec)
        t_one = time.perf_counter() - t0
        offsets = _staggered_trace(n_req, mean_gap_s=gap_frac * t_one)

        sides = {}
        for label, spill_bytes in (("off", 0), ("on", 64 << 20)):
            engine = PagedDecodeEngine(
                server, max_batch=max(8, n_req), block=block,
                # ONE block of prefix budget: publishing either family
                # evicts the other — the churn the spill tier survives
                prefix_cache_blocks=1,
                prefix_spill_bytes=spill_bytes,
            )
            sched = ContinuousScheduler(engine, max_depth=2 * n_req)
            sched.warmup([shared_len + tail_len])
            sched.start()
            # primers, identical on both sides and OUTSIDE the timed
            # window: bare prefixes publish each family's block; full
            # prompts compile the post-hit suffix prefill family.  After
            # family B's primers, family A's block is evicted — spilled
            # on the ON side, gone on the OFF side
            for f in fams:
                sched.submit([list(pref[f])], args.dec).result(timeout=600)
                sched.submit([list(primer[f])], args.dec).result(timeout=600)
            tok0 = int(engine.stats["prefill_tokens"])
            pfx = engine.cache.prefix.stats
            h0, ht0 = int(pfx["hits"]), int(pfx["hit_tokens"])
            sp = engine.cache.spill.stats
            sp0, rd0, dc0 = (int(sp["spills"]), int(sp["readmits"]),
                             int(sp["discards"]))
            ttft, outs, wall = _drive_staggered(
                sched.submit, offsets, prompts, args.dec
            )
            sched.shutdown(timeout=60)
            sides[label] = {
                "ttft": ttft, "outs": outs, "wall": wall,
                "prefill_tokens": int(engine.stats["prefill_tokens"]) - tok0,
                "hits": int(pfx["hits"]) - h0,
                "hit_tokens": int(pfx["hit_tokens"]) - ht0,
                "spills": int(sp["spills"]) - sp0,
                "readmits": int(sp["readmits"]) - rd0,
                "spill_discards": int(sp["discards"]) - dc0,
                "traces": int(engine.stats["traces"]),
            }

    a, b = sides["on"], sides["off"]
    if [len(o) for o in a["outs"]] != [len(o) for o in b["outs"]]:
        raise RuntimeError(
            "spill-tier DELIVERED COUNTS diverged from spill-off — the "
            "prefill/readmit A/B would be unfair"
        )
    divergent = sum(1 for x, y in zip(a["outs"], b["outs"]) if x != y)
    n_dev = jax.device_count()
    rows = []
    for label, side, budget in (("on", a, 64 << 20), ("off", b, 0)):
        toks = sum(len(o) for o in side["outs"])
        rows.append({
            "metric": f"gpt345m_decode_spill_{label}",
            "value": round(toks / side["wall"] / n_dev, 1),
            "unit": "delivered new tokens/s/chip "
                    "(prefix-heavy staggered, spill A/B)",
            "vs_baseline": None,
            "arrivals": n_req, "prompt_len": shared_len + tail_len,
            "dec_len": args.dec,
            "shared_prefix_len": shared_len,
            "kv_block": block,
            "prefix_budget_blocks": 1,
            "spill_budget_bytes": budget,
            "mean_gap_s": round(float(gap_frac * t_one), 4),
            "p50_ttft_s": round(float(np.quantile(side["ttft"], 0.5)), 4),
            "p99_ttft_s": round(float(np.quantile(side["ttft"], 0.99)), 4),
            "prefill_tokens": side["prefill_tokens"],
            "prefix_hits": side["hits"],
            "prefix_hit_tokens": side["hit_tokens"],
            "spills": side["spills"],
            "readmits": side["readmits"],
            "spill_discards": side["spill_discards"],
            "readmit_hit_rate": round(side["readmits"] / n_req, 4),
            "greedy_divergent_rows": divergent,
            "jit_traces": side["traces"],
            "strategy": "greedy_search",
            "decode_path": "overhauled",
            "scheduler": "continuous",
            **_mfu_fields(server.module.config, toks / side["wall"] / n_dev),
            "platform": jax.default_backend(),
        })
    return rows


def _parent(argv) -> int:
    from bench import run_child_with_honest_fallback

    ap = _argparser()
    args = ap.parse_args(argv)
    cases = _parse_cases(args.cases)
    if not cases:
        print(f"no valid cases in {args.cases!r}; have {sorted(CASES)}",
              file=sys.stderr)
        return 2

    def emit_missing(seen, reason):
        for name in cases:
            for metric in _metrics_for(name):
                if metric not in seen:
                    _emit({"metric": metric, "value": 0.0,
                           "unit": f"new tokens/s/chip ({reason})",
                           "vs_baseline": None})

    return run_child_with_honest_fallback(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--cases", ",".join(cases), "--prompt", str(args.prompt),
         "--dec", str(args.dec), "--iters", str(args.iters),
         "--hidden", str(args.hidden), "--layers", str(args.layers)],
        float(os.environ.get("BENCH_DECODE_DEADLINE_S", 1200)),
        emit_missing,
    )


def _child(argv) -> None:
    args = _argparser().parse_args(argv)

    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()
    from bench import wait_for_backend

    platform = os.environ.get("PFX_PLATFORM", "").lower()
    cases = _parse_cases(args.cases)
    if platform in ("", "tpu", "axon") and not wait_for_backend():
        for name in cases:
            for metric in _metrics_for(name):
                _emit({"metric": metric, "value": 0.0,
                       "unit": "new tokens/s/chip (tpu backend unreachable)",
                       "vs_baseline": None})
        return

    params_cache: dict = {}
    for name in cases:
        try:
            if name == "serving":
                rows = [run_serving_case(args)]
            elif name == "staggered":
                rows = run_staggered_case(args)
            elif name == "prefix":
                rows = run_prefix_case(args)
            elif name == "overlap":
                rows = run_overlap_case(args)
            elif name == "spill":
                rows = run_spill_case(args)
            elif name == "tenant":
                rows = run_tenant_case(args)
            elif "_spec" in name:
                rows = [run_spec_case(name, args, params_cache)]
            elif name.endswith("_kvint8"):
                rows = [run_kvint8_case(name, args, params_cache)]
            else:
                rows = [run_decode_case(name, args, params_cache)]
        except Exception as e:  # noqa: BLE001 — an OOM on b32 must not
            # abort the remaining cases
            traceback.print_exc(file=sys.stderr)
            rows = [{"metric": metric, "value": 0.0,
                     "unit": f"new tokens/s/chip ({type(e).__name__})",
                     "vs_baseline": None}
                    for metric in _metrics_for(name)]
        for row in rows:
            _emit(row)


def _argparser():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--cases",
        default="b8_greedy,b8_greedy_legacy,b8_topp,b8_topp_legacy,"
                "b32_greedy,b32_greedy_legacy,b32_topp,b32_topp_legacy,"
                "b8_greedy_spec4,b8_greedy_kvint8,serving,staggered,prefix,"
                "overlap,spill,tenant",
    )
    ap.add_argument("--prompt", type=int, default=128)
    ap.add_argument("--dec", type=int, default=256)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--hidden", type=int, default=int(os.environ.get("BENCH_DEC_HIDDEN", 1024)))
    ap.add_argument("--layers", type=int, default=int(os.environ.get("BENCH_DEC_LAYERS", 24)))
    return ap


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--child" in argv:
        argv.remove("--child")
        _child(argv)
        return
    sys.exit(_parent(argv))


if __name__ == "__main__":
    main()
