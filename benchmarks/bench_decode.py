"""Decode-throughput bench: greedy KV-cache generation on GPT-345M.

The training-side throughput record is deep (headline, sweep, 1.3B,
ViT); this measures the INFERENCE side of the stack — the static
lax.scan decode loop with a donated KV cache that also backs serving
(`core/serving.py`).  No reference machine-readable baseline exists for
decode, so the row reports absolute tokens/s (vs_baseline null) — an
evidence artifact, not a comparison.

One JSON row to stdout and benchmarks/results_decode.jsonl:
  {"metric": "gpt345m_greedy_decode", "value": tok/s, ...}

  python benchmarks/bench_decode.py [--batch 8] [--prompt 128] [--dec 128]
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt", type=int, default=128)
    ap.add_argument("--dec", type=int, default=128)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--hidden", type=int, default=int(os.environ.get("BENCH_DEC_HIDDEN", 1024)))
    ap.add_argument("--layers", type=int, default=int(os.environ.get("BENCH_DEC_LAYERS", 24)))
    args = ap.parse_args(argv)

    from paddlefleetx_tpu.utils.device import apply_platform_env

    apply_platform_env()
    from bench import wait_for_backend

    platform = os.environ.get("PFX_PLATFORM", "").lower()
    row = {"metric": "gpt345m_greedy_decode", "value": 0.0,
           "unit": "new tokens/s/chip", "vs_baseline": None}
    if platform in ("", "tpu", "axon") and not wait_for_backend():
        row["unit"] += " (tpu backend unreachable)"
        print(json.dumps(row))
        sys.exit(0)

    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.models.gpt import model as gpt
    from paddlefleetx_tpu.models.gpt.config import GPTConfig
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate

    cfg = GPTConfig(
        vocab_size=50304, hidden_size=args.hidden, num_layers=args.layers,
        num_attention_heads=16,
        max_position_embeddings=args.prompt + args.dec,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        dtype="bfloat16",
    )
    gen = GenerationConfig(decode_strategy="greedy_search", max_dec_len=args.dec)
    params = gpt.init(cfg, jax.random.key(0))
    prompts = jax.random.randint(
        jax.random.key(1), (args.batch, args.prompt), 0, cfg.vocab_size
    )

    fn = jax.jit(lambda p, ids: generate(p, ids, cfg, gen))
    try:
        jax.block_until_ready(fn(params, prompts))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(params, prompts)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / args.iters
    except Exception as e:  # noqa: BLE001 - a crash must still emit the row
        row["unit"] += f" ({type(e).__name__})"
        print(json.dumps(row))
        sys.exit(0)

    row["value"] = round(args.batch * args.dec / dt, 1)
    row["batch"] = args.batch
    row["prompt_len"] = args.prompt
    row["dec_len"] = args.dec
    row["per_token_ms"] = round(dt / args.dec * 1e3, 2)
    print(json.dumps(row))
    with open(os.path.join(ROOT, "benchmarks", "results_decode.jsonl"), "a") as f:
        f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
