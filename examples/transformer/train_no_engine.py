"""Train a GPT model WITHOUT the Engine: the public API below it.

Counterpart of the reference's examples layer
(examples/transformer/utils/components.py:32-191), which demonstrates
assembling dataset/sampler/loader/lr/optimizer/model by hand instead of
through the Engine.  Here the same tour is the TPU-native one: every piece
is a plain function you can compose inside your own jitted step —

    config      utils.config.get_config (+ -o overrides)
    mesh        parallel.env.init_dist_env -> jax.sharding.Mesh
    data        data.build_dataset / DistributedBatchSampler / DataLoader
    model       models.gpt.model (init / loss_fn + ShardingCtx)
    optimizer   optims.build_optimizer -> optax GradientTransformation
    step        YOUR code: jax.jit(value_and_grad + optax update)

Run (virtual 8-device CPU mesh):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 PFX_PLATFORM=cpu \
    python examples/transformer/train_no_engine.py
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init

import jax
import jax.numpy as jnp
import optax

from paddlefleetx_tpu.data.batch_sampler import (
    DataLoader,
    DistributedBatchSampler,
    collate_stack,
)
from paddlefleetx_tpu.data.gpt_dataset import GPTDataset, write_synthetic_corpus
from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.optims.optimizer import build_optimizer
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding
from paddlefleetx_tpu.utils.config import AttrDict


def main():
    # --- mesh: dp over however many devices exist --------------------------
    devices = jax.devices()
    mesh = build_mesh(MeshConfig(dp_degree=len(devices)), devices)
    rules = make_rules(mesh=mesh)
    ctx = gpt.ShardingCtx(mesh, rules)

    # --- data: synthetic corpus -> dataset -> sampler -> loader ------------
    data_dir = "/tmp/pfx_example_data"
    os.makedirs(data_dir, exist_ok=True)
    prefix = write_synthetic_corpus(
        os.path.join(data_dir, "corpus"), vocab_size=128, num_docs=32
    )
    batch_size, seq_len, steps = 8, 32, 10
    dataset = GPTDataset(
        data_prefix=prefix, max_seq_len=seq_len,
        num_samples=batch_size * steps, split=[1, 0, 0],
    )
    sampler = DistributedBatchSampler(
        dataset_len=len(dataset), batch_size=batch_size, shuffle=True, seed=0
    )
    loader = DataLoader(dataset, sampler, collate_stack)

    # --- model + sharded params -------------------------------------------
    cfg = GPTConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_attention_heads=8,
        max_position_embeddings=seq_len, dtype="float32",
    )
    params = gpt.init(cfg, jax.random.key(0))
    shardings = tree_logical_to_sharding(gpt.gpt_logical_axes(cfg), mesh, rules)
    params = jax.device_put(params, shardings)

    # --- optimizer from the same config vocabulary the Engine uses ---------
    tx, schedule = build_optimizer(
        AttrDict.from_nested(
            {
                "name": "FusedAdamW",
                "weight_decay": 0.01,
                "lr": {"name": "Constant", "learning_rate": 3e-3},
                "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
            }
        )
    )
    opt_state = jax.jit(tx.init)(params)

    # --- YOUR train step: the Engine writes this for you; without it, it is
    # four lines of jax -----------------------------------------------------
    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: gpt.loss_fn(p, batch, cfg, ctx=ctx, train=True)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    with mesh:
        it = iter(loader)
        for i in range(steps):
            host_batch = next(it)
            batch = jax.tree.map(jnp.asarray, host_batch)
            params, opt_state, loss = step(params, opt_state, batch)
            print(f"step {i + 1}/{steps} loss {float(loss):.5f}")

    print("no-engine training loop done")


if __name__ == "__main__":
    main()
