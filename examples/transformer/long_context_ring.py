"""Long-context training with ring attention + the zigzag balanced layout.

The long-context recipe end to end, via the Engine (the reference has no
context-parallel path — SURVEY §5.7; this is the TPU-native answer):

  - ``Distributed.sep_degree``: the sequence stays sharded over the `sep`
    mesh axis; K/V shards rotate the ring (`parallel/ring_attention.py`),
    so per-device memory is O(s/P) and no device ever holds the full
    sequence.
  - ``Distributed.sep_zigzag``: sequences are fed in the zigzag block
    order so causal masking wastes the same work on every ring device
    (contiguous shards leave the first device almost fully masked).
  - ``Model.ring_chunk_k``: bounds each ring step's score buffer to
    [s_local, chunk_k] via an inner rematerialized scan — the
    flash-attention memory trade in plain XLA.

Run (virtual 8-device CPU mesh; on TPU drop the env vars):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 PFX_PLATFORM=cpu \
    python examples/transformer/long_context_ring.py [--seq 4096] [--steps 2]
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    args = ap.parse_args(argv)

    import numpy as np

    from paddlefleetx_tpu.core.engine import Engine
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs
    from paddlefleetx_tpu.utils.log import logger

    import jax

    n_dev = jax.device_count()
    sep = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    dp = n_dev // sep
    batch = dp

    cfg = AttrDict.from_nested(
        {
            "Global": {"global_batch_size": batch, "micro_batch_size": 1, "seed": 7},
            "Engine": {
                "max_steps": args.steps,
                "eval_freq": 0,
                "logging_freq": 1,
                "mix_precision": {"enable": False},
                "save_load": {"save_steps": 0},
            },
            "Model": {
                "module": "GPTModule",
                "vocab_size": 256,
                "hidden_size": args.hidden,
                "num_layers": args.layers,
                "num_attention_heads": 8,
                "max_position_embeddings": args.seq,
                "hidden_dropout_prob": 0.0,
                "attention_probs_dropout_prob": 0.0,
                "attn_impl": "ring",
                "ring_chunk_k": 512,
                "use_recompute": True,
                "recompute_granularity": "full",
                "dtype": "float32",
            },
            "Distributed": {"dp_degree": dp, "sep_degree": sep, "sep_zigzag": True},
            "Optimizer": {
                "name": "FusedAdamW",
                "weight_decay": 0.01,
                "lr": {"name": "Constant", "learning_rate": 1e-4},
                "grad_clip": {"name": "ClipGradByGlobalNorm", "clip_norm": 1.0},
            },
        }
    )
    cfg = process_configs(cfg, num_devices=n_dev)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    rng = np.random.default_rng(0)
    s = args.seq

    def loader():
        while True:
            toks = rng.integers(0, 256, (batch, s)).astype(np.int64)
            yield {
                "tokens": toks,
                "labels": np.roll(toks, -1, 1),
                "loss_mask": np.ones((batch, s), np.float32),
                "position_ids": np.tile(np.arange(s), (batch, 1)),
            }

    with mesh:
        engine = Engine(cfg, module, mesh)
        state = engine.fit(loader())
    logger.info(
        f"long-context ring+zigzag: seq {s} over sep={sep} "
        f"(s_local {s // sep}), {args.steps} steps done; final step "
        f"{int(state.step)}"
    )


if __name__ == "__main__":
    main()
