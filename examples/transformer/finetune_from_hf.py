"""Warm-start finetuning from an imported HF GPT-2 checkpoint, no Engine.

The public-API tour for the migration path (docs/migration_from_paddlefleetx.md):

  1. tools/convert_hf_gpt2.py writes a params-only checkpoint
  2. restore_params loads it (any mesh; shardings applied by device_put)
  3. a hand-rolled optax loop finetunes
  4. generate() samples from the tuned weights

Run (CPU mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 PFX_PLATFORM=cpu \
  python examples/transformer/finetune_from_hf.py --ckpt <converted_dir>

Without --ckpt a tiny random GPT-2 is converted in-process (needs torch +
transformers, both in the base image) so the example is self-contained.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt", default=None, help="converted params-only dir")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args(argv)

    from paddlefleetx_tpu.models.gpt import model as gpt
    from paddlefleetx_tpu.models.gpt.config import GPTConfig
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate

    if args.ckpt:
        import yaml

        from paddlefleetx_tpu.utils.checkpoint import restore_params

        params = restore_params(args.ckpt)
        model_yaml = yaml.safe_load(open(os.path.join(args.ckpt, "model.yaml")))
        cfg = GPTConfig.from_config({**model_yaml["Model"], "dtype": "float32"})
    else:  # self-contained: convert a tiny random HF GPT-2 in-process
        import torch
        from transformers import GPT2Config, GPT2LMHeadModel

        from paddlefleetx_tpu.models.gpt.convert import (
            convert_hf_gpt2_state_dict,
            hf_gpt2_config,
        )

        torch.manual_seed(0)
        hf = GPT2LMHeadModel(
            GPT2Config(vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=4)
        )
        cfg = hf_gpt2_config(hf.config, dtype="float32",
                             hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
        params = convert_hf_gpt2_state_dict(hf.state_dict(), cfg)

    # toy task: continue an arithmetic-ish token pattern
    rng = np.random.default_rng(0)
    seq = 32
    base = rng.integers(2, cfg.vocab_size - 2, cfg.vocab_size)

    def make_batch(n=8):
        starts = rng.integers(0, cfg.vocab_size, n)
        rows = np.stack([base[(s + np.arange(seq + 1)) % cfg.vocab_size] for s in starts])
        return {
            "tokens": jnp.asarray(rows[:, :-1]),
            "labels": jnp.asarray(rows[:, 1:]),
            "loss_mask": jnp.ones((n, seq), jnp.float32),
        }

    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: gpt.loss_fn(p, batch, cfg, train=False)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, make_batch())
        print(f"step {i + 1}: loss {float(loss):.4f}")

    gen = GenerationConfig(max_dec_len=8, decode_strategy="greedy_search",
                           eos_token_id=-1, pad_token_id=0)
    prompt = jnp.asarray([base[:4]])
    out = generate(params, prompt, cfg, gen)
    print("prompt:", prompt[0].tolist())
    print("continuation:", np.asarray(out)[0].tolist())
    print("pattern next:", base[4:12].tolist())


if __name__ == "__main__":
    main()
