"""Generate from a GPT model WITHOUT the Engine/InferenceEngine.

Mesh-serving tour of the generation API (reference
examples/transformer/... no-engine layer): build a TP mesh, shard params,
call ``generate`` with a ShardingCtx — the KV cache stays heads-sharded
over the model axis and GSPMD inserts the serving collectives.

Run (virtual 8-device CPU mesh):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 PFX_PLATFORM=cpu \
    python examples/transformer/generate_no_engine.py
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.gpt.generation import GenerationConfig, generate
from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh
from paddlefleetx_tpu.parallel.sharding import make_rules, tree_logical_to_sharding


def main():
    devices = jax.devices()
    mp = 2 if len(devices) % 2 == 0 else 1
    mesh = build_mesh(
        MeshConfig(dp_degree=len(devices) // mp, mp_degree=mp), devices
    )
    rules = make_rules(mesh=mesh)
    ctx = gpt.ShardingCtx(mesh, rules)

    cfg = GPTConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_attention_heads=8,
        max_position_embeddings=64, dtype="float32",
    )
    params = jax.device_put(
        gpt.init(cfg, jax.random.key(0)),
        tree_logical_to_sharding(gpt.gpt_logical_axes(cfg), mesh, rules),
    )

    gen = GenerationConfig(
        max_dec_len=16, decode_strategy="beam_search", num_beams=4,
        eos_token_id=127,
    )
    # one prompt per dp group (batch must divide the data axis), jitted so
    # GSPMD plans the whole decode loop once
    dp = mesh.shape["data"]
    prompt = jnp.tile(jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]]), (dp, 1))
    with mesh:
        out = jax.jit(lambda p, x: generate(p, x, cfg, gen, ctx=ctx))(params, prompt)
    print("prompt:", prompt[0].tolist())
    print("beam-searched continuation:", out[0].tolist())


if __name__ == "__main__":
    main()
