"""Import a HuggingFace ViT checkpoint into the native format.

Same contract as tools/convert_hf_gpt2.py: params-only orbax checkpoint +
model.yaml.  Logits parity with transformers is covered by
tests/test_hf_convert.py.

Usage:
  python tools/convert_hf_vit.py --model /path/to/hf_vit -o out/vit
      [--num-classes 1000]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True, help="HF model dir (local)")
    ap.add_argument("-o", "--out", required=True)
    ap.add_argument("--num-classes", type=int, default=0)
    args = ap.parse_args(argv)

    from paddlefleetx_tpu.models.vit.convert import (
        convert_hf_vit_state_dict,
        hf_vit_config,
    )

    if args.num_classes > 0:
        # head-bearing load: AutoModel would strip a trained classifier
        from transformers import ViTForImageClassification

        m, info = ViTForImageClassification.from_pretrained(
            args.model, num_labels=args.num_classes, output_loading_info=True
        )
        sd = m.state_dict()
        if any(k.startswith("classifier") for k in info.get("missing_keys", [])):
            # the checkpoint had no trained classifier: drop the randomly
            # initialized one so the converter emits its documented
            # zero-init linear-probe head instead of random garbage
            print("note: checkpoint has no trained classifier; emitting a zero head")
            sd = {k: v for k, v in sd.items() if not k.startswith("classifier")}
    else:
        from transformers import AutoModel

        m = AutoModel.from_pretrained(args.model)
        sd = m.state_dict()
    cfg = hf_vit_config(m.config, num_classes=args.num_classes)
    params = convert_hf_vit_state_dict(sd, cfg)

    from paddlefleetx_tpu.utils.checkpoint import save_params_checkpoint

    out = save_params_checkpoint(
        args.out,
        params,
        f"hf-vit:{args.model}",
        {
            "module": "ViTModule",
            "image_size": cfg.image_size,
            "patch_size": cfg.patch_size,
            "in_channels": cfg.in_channels,
            "num_classes": cfg.num_classes,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_attention_heads,
            "ffn_hidden_size": cfg.ffn_hidden_size,
            "gelu_approximate": cfg.gelu_approximate,
            "layer_norm_eps": cfg.layer_norm_eps,
        },
    )
    print(f"converted -> {out}")


if __name__ == "__main__":
    main()
