"""Evaluation entry point (reference tools/eval.py:34-54)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init

from paddlefleetx_tpu.core.engine import Engine
from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.data.builders import build_dataloader
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.utils.config import get_config, parse_args


def main(argv=None):
    args = parse_args(argv)
    cfg = get_config(args.config, overrides=args.override)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)
    with mesh:
        engine = Engine(cfg, module, mesh)
        ckpt_dir = cfg.Engine.save_load.get("ckpt_dir")
        if ckpt_dir:
            engine.load(ckpt_dir)
        loader = build_dataloader(cfg, "Eval")
        engine.evaluate(loader, iters=int(cfg.Engine.get("eval_iters", 10)))


if __name__ == "__main__":
    main()
