"""Inference entry point (reference tools/inference.py:37-59): load the
exported artifact (or build the module live), compile over the configured
mesh, run a batch, report latency."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init

import numpy as np

from paddlefleetx_tpu.core.inference_engine import CompileConfig, InferenceEngine
from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.parallel.seed import get_seed_tracker
from paddlefleetx_tpu.utils.config import get_config, parse_args
from paddlefleetx_tpu.utils.log import logger


def main(argv=None):
    args = parse_args(argv)
    cfg = get_config(args.config, overrides=args.override)
    mesh = init_dist_env(cfg)

    inf_cfg = cfg.get("Inference", {})
    compile_cfg = CompileConfig.from_config(inf_cfg)
    model_dir = inf_cfg.get("model_dir")

    if model_dir:
        engine = InferenceEngine.from_export(model_dir, compile_cfg=compile_cfg)
        seq = int(inf_cfg.get("max_seq_len", 128))
        tokens = np.zeros((int(inf_cfg.get("batch_size", 1)), seq), np.int32)
        out = engine.predict(tokens)
    else:
        # live-module path (no export artifact): TP-shard params over mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paddlefleetx_tpu.models.gpt import model as gpt
        from paddlefleetx_tpu.parallel.sharding import (
            make_rules,
            tree_logical_to_sharding,
        )

        if cfg.Model.get("module", "GPTModule") not in ("GPTModule", "GPTGenerationModule"):
            raise ValueError(
                "live-module inference currently serves the GPT forward; "
                f"got module={cfg.Model.get('module')} — export it first and "
                "set Inference.model_dir"
            )
        module = build_module(cfg)
        from paddlefleetx_tpu.utils.checkpoint import load_pretrained_params

        params = load_pretrained_params(cfg)
        if params is None:
            params = module.init_params(get_seed_tracker().params_key())
        rules = make_rules()
        shardings = tree_logical_to_sharding(module.logical_axes(), mesh, rules)
        mcfg = module.config
        seq = int(inf_cfg.get("max_seq_len", mcfg.max_position_embeddings))
        tokens = np.zeros((int(inf_cfg.get("batch_size", 1)), seq), np.int32)

        engine = InferenceEngine(
            lambda p, t: gpt.forward(p, t, mcfg, train=False),
            params,
            mesh=mesh,
            param_shardings=shardings,
            batch_spec=NamedSharding(mesh, P("data")),
            compile_cfg=compile_cfg,
        )
        out = engine.predict(tokens)

    stats = engine.benchmark(tokens, iters=int(inf_cfg.get("bench_iters", 5)))
    logger.info(
        f"inference ok: output {np.asarray(out).shape} "
        f"latency {stats['latency_ms']:.1f}ms qps {stats['qps']:.1f}"
    )


if __name__ == "__main__":
    main()
