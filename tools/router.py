"""Multi-host serving front door: route client traffic across N
`tools/serve.py` replicas (`core/router.py`), with queue-aware load
balancing, replica health management, rolling drains, and the
disaggregated prefill/decode topology.

Two topologies (docs/serving.md "Multi-host serving"):

  replicated     N monolith replicas; each POST /generate is forwarded
                 to the least-loaded serving replica (bounded retry on
                 connection-refused and provably-unsent sends only — a
                 partial exchange returns an honest 503, never a
                 replay).
  disaggregated  separate --prefill and --decode pools: the router runs
                 each prompt's prefill on a prefill replica and a decode
                 replica continues it — long prompts stop head-of-line-
                 blocking decode steps (greedy output token-identical to
                 the single-process continuous path; drilled).  Under
                 ``--handoff direct`` (default) the router issues a
                 placement ticket and the prefill replica POSTs the
                 KV-handoff payload STRAIGHT to the chosen decode
                 replica — payload bytes never transit the router;
                 ``--handoff proxy`` carries them through the router
                 (the drilled fallback).  Failover ladder: a prefill
                 replica lost mid-exchange is retried on another
                 (stateless); a decode replica lost after adoption
                 triggers ONE re-prefill fallback through a healthy
                 pair when the deadline allows, an honest 503 otherwise
                 — never a replay at a replica that saw the bytes.

The router owns front-door admission (bounded in-flight -> 429,
draining -> 503, deadline checked before every dispatch) and mirrors
the serve.py drain contract: SIGTERM stops admission, in-flight
requests finish, exit 0; a second signal force-quits.

Elastic control plane (``--supervise``, docs/serving.md "Elastic
control plane"): the router spawns its replicas itself as MANAGED
subprocesses (`core/controller.py`) — crash-restart with exponential
backoff, a flap budget that quarantines a crash-looping replica LOUDLY,
warm boot off the persistent compile cache — and runs the SLO-driven
scale controller: breach/depth/occupancy-driven fast scale-up, idle
scale-down through the authenticated remote-drain primitive, hysteresis
and min/max bounds, every decision in a bounded replayable log.

Usage:
  # replicated
  python tools/router.py --port 9000 \
      --replica http://127.0.0.1:8001 --replica http://127.0.0.1:8002
  # disaggregated
  python tools/router.py --port 9000 \
      --prefill http://127.0.0.1:8001 --decode http://127.0.0.1:8002
  # supervised + autoscaled (the elastic control plane)
  python tools/router.py --port 9000 --supervise \
      --replica-cmd "python tools/serve.py -c cfg.yaml --port {port} \
                     --replica-id {replica_id}" \
      --min-replicas 1 --max-replicas 4 --base-port 8101
  # supervised DISAGGREGATED pools (role-aware: prefill scales on
  # depth/TTFT burn, decode on arena occupancy/available_blocks)
  python tools/router.py --port 9000 --supervise \
      --prefill-cmd "python tools/serve.py -c cfg.yaml --role prefill \
                     --port {port} --replica-id {replica_id}" \
      --decode-cmd "python tools/serve.py -c cfg.yaml --role decode \
                    --port {port} --replica-id {replica_id}" \
      --min-prefill 1 --max-prefill 4 --min-decode 1 --max-decode 4
  # rolling deploy, one replica at a time (requires the router up):
  python tools/router.py drain --admin http://127.0.0.1:9000 [--replica-id r0]

Endpoints:
  POST /generate        route one request (token-id modes only in
                        disaggregated mode — the router has no tokenizer)
  GET  /healthz         router health + per-replica lifecycle states
  GET  /metrics         Prometheus exposition (pfx_router_* and friends)
  GET  /replicas        detailed per-replica view (identity, scores)
  POST /admin/drain     initiate drain-one-replica (body: {"replica": id})
  GET  /debug/traces    sampled routing timelines (Perfetto JSON)
  GET  /debug/controller  scale policy + decision log + supervised slots

/admin/* and /debug/* are gated by the fleet-shared ``PFX_ADMIN_TOKEN``
bearer token (unset = loopback-only, loudly); drains ride the same
token to each replica's ``POST /admin/drain``, so rolling deploys work
cross-host.
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def serve_router(args) -> int:
    import signal
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from paddlefleetx_tpu.core.controller import (
        ElasticController,
        ReplicaSupervisor,
        ScalePolicy,
    )
    from urllib.parse import parse_qs, urlsplit

    from paddlefleetx_tpu.core.request_queue import QueueClosed, QueueFull
    from paddlefleetx_tpu.core.router import (
        FleetJournal,
        FleetLog,
        NoReplicaAvailable,
        ReplicaUnavailable,
        RouterCore,
        TenantQuotaExceeded,
        _DownstreamError,
        _http_request,
        admin_headers,
        check_admin,
        read_fleet_journal,
        replay_fleet_state,
    )
    from paddlefleetx_tpu.utils.log import log_server_error
    from paddlefleetx_tpu.core.tenancy import (
        PRIORITY_HEADER,
        TENANT_HEADER,
        TenantConfig,
    )
    from paddlefleetx_tpu.utils.telemetry import (
        flight_dir,
        get_flight_recorder,
        get_registry,
    )
    from paddlefleetx_tpu.utils import tracing
    from paddlefleetx_tpu.utils.tracing import chrome_trace, get_trace_buffer

    replicas = [(u, "monolith") for u in args.replica]
    replicas += [(u, "prefill") for u in args.prefill]
    replicas += [(u, "decode") for u in args.decode]
    pool_supervise = bool(args.supervise and args.prefill_cmd)
    tenant_config = None
    if getattr(args, "tenants", ""):
        # a bad quota file must fail the boot, not silently admit all
        tenant_config = TenantConfig.from_file(args.tenants)
    core = RouterCore(
        replicas,
        max_inflight=args.max_inflight,
        retries=args.retries,
        poll_interval_s=args.poll_interval,
        eject_after=args.eject_after,
        serve_after=args.serve_after,
        allow_empty=args.supervise,
        handoff=args.handoff,
        tenant_config=tenant_config,
    )
    if pool_supervise:
        # the supervised pools register as they spawn; pin the topology
        # now so the first /generate routes disaggregated (add_replica
        # keeps it consistent from then on)
        core.disaggregated = True
    log_dir = args.replica_log_dir or os.path.join(flight_dir(), "replicas")
    shared_policy = dict(
        high_depth=args.scale_high_depth,
        low_depth=args.scale_low_depth,
        up_cooldown_s=args.scale_up_cooldown,
        down_cooldown_s=args.scale_down_cooldown,
        idle_s=args.scale_idle,
        interval_s=args.control_interval,
    )
    shared_sup = dict(
        compile_cache_dir=args.compile_cache_dir,
        log_dir=log_dir,
        backoff_base_s=args.restart_backoff,
        flap_budget=args.flap_budget,
        flap_window_s=args.flap_window,
    )
    controllers = []
    if pool_supervise:
        # role-aware pool supervision (docs/serving.md "Disaggregated
        # operations"): one supervisor + controller per pool, each on
        # its own port range and replica-id prefix, with pool-specific
        # scale signals — prefill watches queue depth + TTFT burn (its
        # replicas hold no decode arena), decode watches arena
        # occupancy + available_blocks (its queue drains at step
        # boundaries; the arena is what actually bounces adoptions)
        specs = (
            ("prefill", args.prefill_cmd, args.prefill_base_port,
             args.min_prefill, args.max_prefill, "p",
             # under the direct transport a prefill dispatch stays
             # in-flight through the whole prefill->decode relay, so
             # router-side in-flight would scale the prefill pool on
             # DECODE duration — count replica-reported queue depth only
             dict(use_occupancy=False,
                  count_in_flight=args.handoff != "direct")),
            ("decode", args.decode_cmd, args.decode_base_port,
             args.min_decode, args.max_decode, "d",
             dict(use_depth=False, low_blocks=args.decode_low_blocks)),
        )
        for role, cmd, base_port, mn, mx, prefix, signals in specs:
            supervisor = ReplicaSupervisor(
                cmd, base_port=base_port, max_replicas=mx, role=role,
                slot_prefix=prefix, **shared_sup,
            )
            controllers.append(ElasticController(
                core, supervisor,
                ScalePolicy(min_replicas=mn, max_replicas=mx,
                            **shared_policy, **signals),
                role=role,
            ))
    elif args.supervise:
        supervisor = ReplicaSupervisor(
            args.replica_cmd,
            base_port=args.base_port,
            max_replicas=args.max_replicas,
            **shared_sup,
        )
        controllers.append(ElasticController(
            core, supervisor,
            ScalePolicy(
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                **shared_policy,
            ),
        ))
    reg = get_registry()
    recorder = get_flight_recorder()
    recorder.install_excepthook()
    trace_buffer = get_trace_buffer()
    identity = {
        "replica_id": args.router_id or f"{args.host}:{args.port}",
        "role": "router",
        "scheduler": "disaggregated" if core.disaggregated else "replicated",
        "listen": f"{args.host}:{args.port}",
        "pid": os.getpid(),
    }
    tracing.set_process_identity(
        replica_id=identity["replica_id"], role="router",
    )
    # fleet observability artifact: one sample row per replica per poll
    # cadence + controller scale events — what tools/report.py --fleet
    # renders from the router's artifacts alone (crash-tolerant JSONL)
    core.fleet_log = FleetLog(
        os.path.join(flight_dir(), "fleet_metrics.jsonl")
    )
    # crash-consistent control-plane journal (docs/serving.md
    # "Control-plane recovery"): registry transitions, controller scale
    # decisions, supervisor slot facts, and tenant buckets all survive
    # THIS process — the recovery block below folds the previous
    # incarnation's journal back in before the listener opens
    journal_path = os.path.join(flight_dir(), "fleet_state.jsonl")
    journal = FleetJournal(journal_path)
    core.journal = journal
    for ctl in controllers:
        ctl.journal = journal
        ctl.supervisor.journal = journal
    flags = {"draining": False}
    default_deadline = float(args.deadline)
    max_deadline = float(args.max_deadline)
    # one fleet profile capture at a time: each replica already refuses
    # its own overlaps (409), but the router-level guard keeps a second
    # operator from profiling a DIFFERENT slice of the fleet while the
    # first capture is still distorting it
    profile_lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        timeout = 120

        def log_message(self, *a):
            pass

        def _send(self, code, body, ctype, headers=None):
            if code >= 500:
                # one structured line per 5xx (utils/log.log_server_error)
                # joinable against the trace timeline by trace_id
                outcome = None
                if ctype == "application/json":
                    try:
                        outcome = json.loads(body.decode()).get("error")
                    except (ValueError, UnicodeDecodeError):
                        pass
                log_server_error(
                    "router", code, self.path,
                    replica_id=identity["replica_id"],
                    tenant=self.headers.get(TENANT_HEADER),
                    trace_id=(headers or {}).get("X-Trace-Id"),
                    outcome=outcome,
                )
            try:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError, TimeoutError):
                reg.counter("pfx_http_client_gone_total").inc()
            else:
                reg.counter("pfx_http_responses_total", code=str(code)).inc()

        def _json(self, code, obj, headers=None):
            self._send(code, json.dumps(obj).encode(), "application/json",
                       headers)

        def _authorized(self, what: str) -> bool:
            """Gate /admin and /debug on the shared PFX_ADMIN_TOKEN
            (core/router.check_admin): token set -> bearer match; unset
            -> loopback-only, loudly.  Answers 401/403 on failure."""
            ok, code, msg = check_admin(
                self.headers, self.client_address, what=what
            )
            if not ok:
                self._json(code, {"error": msg})
            return ok

        def do_GET(self):
            if self.path == "/healthz":
                states = core.states()
                body = {
                    "ok": not flags["draining"],
                    "state": "draining" if flags["draining"] else "ok",
                    "identity": identity,
                    "mode": identity["scheduler"],
                    "in_flight": core.depth(),
                    "replicas": states,
                    "eligible": sum(
                        1 for v in core.replica_views() if v["eligible"]
                    ),
                }
                if len(controllers) == 1:
                    c = controllers[0]
                    body["controller"] = {
                        "target": c.target,
                        "quarantined": c.supervisor.quarantined_count(),
                        "decisions": len(c.decision_log),
                    }
                elif controllers:
                    body["controller"] = {"pools": {
                        c.role: {
                            "target": c.target,
                            "quarantined":
                                c.supervisor.quarantined_count(),
                            "decisions": len(c.decision_log),
                        }
                        for c in controllers
                    }}
                return self._json(200, body)
            if self.path == "/metrics":
                return self._send(
                    200, reg.render_prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            if self.path == "/replicas":
                # per-tenant occupancy ledgers ride the federation scrape
                # (pfx_tenant_*_seconds_total, labels already folded
                # through the replica's top-k cap) — billing-grade cost
                # attribution per replica without a second poll
                views = core.replica_views()
                for v in views:
                    occ = {}
                    for fam, field in (
                        ("pfx_tenant_slot_seconds_total", "slot_s"),
                        ("pfx_tenant_kv_block_seconds_total",
                         "kv_block_s"),
                    ):
                        for lab, val in core.federation.samples(
                            v["key"], fam
                        ):
                            ten = lab.get("tenant", "?")
                            occ.setdefault(
                                ten, {"slot_s": 0.0, "kv_block_s": 0.0}
                            )[field] = val
                    if occ:
                        v["tenant_occupancy"] = occ
                return self._json(200, {
                    "replicas": views,
                    "tenants": core.tenant_snapshot(),
                })
            if self.path.startswith("/debug/"):
                if not self._authorized("/debug"):
                    return
                if self.path == "/debug/traces":
                    return self._json(
                        200, chrome_trace(trace_buffer.traces())
                    )
                parts = urlsplit(self.path)
                if parts.path == "/debug/trace":
                    # ONE stitched timeline: the router's own routing
                    # events plus every hop's remote spans (each naming
                    # its process) on one wall-clock-anchored axis —
                    # the fleet "why is this request slow" entry point
                    tid = (parse_qs(parts.query).get("id") or [""])[0]
                    if not tid:
                        return self._json(
                            400, {"error": "need ?id=<trace_id>"})
                    tc = trace_buffer.get(tid)
                    if tc is None:
                        return self._json(404, {
                            "error": f"trace {tid!r} not in the sampled "
                                     f"window (cap {trace_buffer.cap}, "
                                     f"sample {trace_buffer.sample:g})"
                        })
                    return self._json(200, tc.timeline())
                if self.path == "/debug/controller":
                    if not controllers:
                        return self._json(404, {
                            "error": "no controller: run with --supervise"
                        })
                    if len(controllers) == 1:
                        return self._json(200, controllers[0].view())
                    return self._json(200, {"pools": {
                        c.role: c.view() for c in controllers
                    }})
                return self._json(404, {"error": "unknown debug path"})
            return self._json(404, {"error": "unknown path"})

        def do_POST(self):
            parts = urlsplit(self.path)
            if parts.path == "/admin/drain":
                return self._admin_drain()
            if parts.path == "/admin/register":
                return self._admin_register()
            if parts.path == "/admin/profile":
                return self._admin_profile()
            if parts.path != "/generate":
                return self._json(404, {"error": "unknown path"})
            return self._generate(parts)

        def _wants_stream(self, parts) -> bool:
            qs = parse_qs(parts.query or "")
            if (qs.get("stream") or [""])[0] not in ("", "0"):
                return True
            return "text/event-stream" in (self.headers.get("Accept") or "")

        def _admin_drain(self):
            if not self._authorized("/admin"):
                return
            n = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
            except json.JSONDecodeError as e:
                return self._json(400, {"error": f"bad JSON: {e}"})
            try:
                out = core.drain(req.get("replica"))
            except ValueError as e:
                return self._json(409, {"error": str(e)})
            return self._json(200, out)

        def _admin_profile(self):
            """POST /admin/profile — fan an on-demand jax.profiler
            capture out to selected live replicas (optional body
            filters: {"pool": "decode", "replica": "<key|id>"}) and
            aggregate ONE fleet summary: per-replica outcomes plus a
            merged top-op table (docs/observability.md "On-demand
            profiling").  Each replica enforces its own single-capture
            guard and duration cap; the router adds the fleet-level
            overlap guard (409) so two operators cannot profile
            different slices concurrently."""
            if not self._authorized("/admin"):
                return
            n = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
            except json.JSONDecodeError as e:
                return self._json(400, {"error": f"bad JSON: {e}"})
            seconds = req.get("seconds", 3.0)
            try:
                seconds = float(seconds)
            except (TypeError, ValueError):
                return self._json(
                    400, {"error": f"seconds must be a number, got "
                                   f"{seconds!r}"})
            top = int(req.get("top", 20))
            pool = req.get("pool")
            want = req.get("replica")
            targets = [
                v for v in core.replica_views()
                if v["url"] and v["healthy"]
                and v["state"] in ("serving", "draining")
                and (pool is None or v["role"] == pool)
                and (want is None or want in (v["key"], v["replica_id"]))
            ]
            if not targets:
                return self._json(404, {
                    "error": "no matching live replica to profile "
                             f"(pool={pool!r}, replica={want!r})"
                })
            if not profile_lock.acquire(blocking=False):
                return self._json(409, {
                    "error": "a fleet profile capture is already "
                             "active; retry after it finishes"
                })
            try:
                results = {}

                def _one(v):
                    payload = json.dumps(
                        {"seconds": seconds, "top": top}
                    ).encode()
                    try:
                        code, data, _, _ = _http_request(
                            v["url"], "POST", "/admin/profile",
                            body=payload,
                            headers={"Content-Type": "application/json",
                                     **admin_headers()},
                            # the replica sleeps `seconds` then parses
                            # the trace in pure Python while its decode
                            # threads keep the GIL busy — on a loaded
                            # host the parse, not the capture, is the
                            # long pole, so the headroom is generous
                            timeout=seconds + 180.0,
                        )
                        try:
                            out = json.loads(data.decode())
                        except ValueError:
                            out = {"error": data[:200].decode("replace")}
                        results[v["key"]] = {"status": code, **out}
                    except Exception as e:  # noqa: BLE001 — per-replica
                        results[v["key"]] = {"status": 0, "error": str(e)}

                threads = [
                    threading.Thread(target=_one, args=(v,), daemon=True)
                    for v in targets
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(seconds + 210.0)
                # merge the per-replica op tables into one fleet view:
                # same op name -> summed occurrences/durations
                merged = {}
                device_us = host_us = 0.0
                captured = 0
                for r in results.values():
                    if r.get("status") != 200:
                        continue
                    captured += 1
                    device_us += float(r.get("device_us", 0.0))
                    host_us += float(r.get("host_us", 0.0))
                    for op in r.get("top_ops", []):
                        m = merged.setdefault(op["op"], {
                            "op": op["op"],
                            "category": op.get("category", "?"),
                            "occurrences": 0,
                            "total_us": 0.0, "self_us": 0.0,
                        })
                        m["occurrences"] += int(op.get("occurrences", 0))
                        m["total_us"] += float(op.get("total_us", 0.0))
                        m["self_us"] += float(op.get("self_us", 0.0))
                top_ops = sorted(
                    merged.values(), key=lambda r: -r["self_us"]
                )[:top]
                total_self = sum(r["self_us"] for r in merged.values()) or 1.0
                for op in top_ops:
                    op["self_frac"] = round(op["self_us"] / total_self, 4)
                body = {
                    "requested": len(targets),
                    "captured": captured,
                    "seconds": seconds,
                    "device_us": round(device_us, 1),
                    "host_us": round(host_us, 1),
                    "top_ops": top_ops,
                    "replicas": results,
                }
                recorder.record({
                    "event": "fleet_profile_capture",
                    "requested": len(targets), "captured": captured,
                    "seconds": seconds,
                })
                # every replica failing is a gateway failure, honestly
                return self._json(200 if captured else 502, body)
            finally:
                profile_lock.release()

        def _admin_register(self):
            # replica self-registration heartbeat (tools/serve.py
            # --router-url): how a router restarted with a lost or
            # stale journal rediscovers its fleet, and how a drained
            # replica says goodbye without waiting out --eject-after
            if not self._authorized("/admin"):
                return
            n = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
            except json.JSONDecodeError as e:
                return self._json(400, {"error": f"bad JSON: {e}"})
            try:
                out = core.register_replica(req)
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            return self._json(200, out)

        def _tenant_headers(self):
            """The request's tenant/priority headers, VERBATIM, for
            forwarding on every downstream hop (including retry and
            re-prefill failover legs)."""
            fwd = {}
            for h in (TENANT_HEADER, PRIORITY_HEADER):
                v = self.headers.get(h)
                if v:
                    fwd[h] = v
            return fwd

        def _generate(self, parts=None):
            t0 = time.monotonic()
            tenant = self.headers.get(TENANT_HEADER)
            try:
                core.acquire(tenant)
            except TenantQuotaExceeded as e:
                # HONEST Retry-After: the tenant's own bucket refill
                # time (plus the machine-precise value in the body)
                retry = max(0.001, e.retry_after_s)
                return self._json(
                    429,
                    {"error": str(e), "tenant": e.tenant,
                     "reason": e.reason, "retry_after_s": retry},
                    headers={"Retry-After": f"{retry:.3f}"},
                )
            except QueueFull:
                return self._json(
                    429,
                    {"error": f"router at capacity "
                              f"({args.max_inflight} in flight)"},
                    headers={"Retry-After": "1"},
                )
            except QueueClosed:
                return self._json(
                    503, {"error": "router draining"},
                    headers={"Retry-After": "5"},
                )
            trace = trace_buffer.maybe_start("route", t0=t0)
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                try:
                    req = json.loads(body or b"{}")
                except json.JSONDecodeError as e:
                    return self._json(400, {"error": f"bad JSON: {e}"})
                try:
                    deadline_s = float(req.get("deadline_s",
                                               default_deadline))
                    if not (deadline_s > 0 and math.isfinite(deadline_s)):
                        raise ValueError(
                            "deadline_s must be a positive finite number"
                        )
                    deadline_s = min(deadline_s, max_deadline)
                except (ValueError, TypeError) as e:
                    return self._json(400, {"error": str(e)})
                if core.disaggregated:
                    return self._generate_disagg(req, deadline_s, trace)
                # prefix-affinity signal: the request's prompt ids (when
                # the body carries ids — text prompts would need the
                # replica's tokenizer) steer `pick` toward the replica
                # already holding the cached prefill.  Malformed ids are
                # ignored here: the replica answers the 400, affinity
                # just scores 0
                ids = req.get("prompt_ids") or next(
                    iter(req.get("prompts_ids") or []), None
                )
                try:
                    prefix_tokens = ([int(t) for t in ids]
                                     if isinstance(ids, list) and ids
                                     else None)
                except (TypeError, ValueError):
                    prefix_tokens = None
                streaming = parts is not None and self._wants_stream(parts)
                relay = {"started": False, "lost": False}

                def relay_sink(chunk: bytes) -> None:
                    # unbuffered proxy: forward each replica flush the
                    # moment it lands.  Must not raise back into the
                    # dispatch (the _http_request sink contract) — a
                    # gone client just drains the rest of the stream.
                    if relay["lost"]:
                        return
                    try:
                        if not relay["started"]:
                            relay["started"] = True
                            self.send_response(200)
                            self.send_header("Content-Type",
                                             "text/event-stream")
                            self.send_header("Cache-Control", "no-cache")
                            self.send_header("Connection", "close")
                            if trace is not None:
                                self.send_header("X-Trace-Id",
                                                 trace.trace_id)
                            self.end_headers()
                        self.wfile.write(chunk)
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError,
                            TimeoutError, OSError):
                        relay["lost"] = True
                        reg.counter("pfx_http_client_gone_total").inc()

                try:
                    status, data, ctype = core.dispatch(
                        "POST",
                        "/generate?stream=1" if streaming else "/generate",
                        body,
                        role="monolith", deadline_s=deadline_s,
                        # the fleet token rides along so a token-gated
                        # replica honors the trace-propagation headers
                        # (serve.py accepts them only from callers that
                        # pass the admin rule)
                        headers={"Content-Type": "application/json",
                                 **self._tenant_headers(),
                                 **admin_headers()},
                        trace=trace,
                        sink=relay_sink if streaming else None,
                        prefix_tokens=prefix_tokens,
                    )
                except NoReplicaAvailable as e:
                    return self._json(
                        503, {"error": str(e)},
                        headers={"Retry-After": "2"},
                    )
                except ReplicaUnavailable as e:
                    if relay["started"]:
                        # stream torn mid-relay: the status line is
                        # already on the close-delimited wire, so the
                        # truncated stream IS the client's error signal
                        return
                    return self._json(
                        503, {"error": str(e)},
                        headers={"Retry-After": "1"},
                    )
                if relay["started"]:
                    # the relay sink already wrote the whole response
                    reg.counter("pfx_http_responses_total",
                                code="200").inc()
                    return
                headers = (
                    {"Retry-After": "1"} if status in (429, 503) else None
                )
                return self._send(status, data, ctype, headers)
            except Exception as e:  # noqa: BLE001 — last-resort guard
                return self._json(500, {"error": str(e)})
            finally:
                if trace is not None:
                    trace.event("respond")
                    trace.finish()
                core.release(tenant)

        def _generate_disagg(self, req, deadline_s, trace):
            if "prompt_ids" in req:
                prompts, plural = [list(req["prompt_ids"])], False
            elif "prompts_ids" in req:
                prompts, plural = [list(p) for p in req["prompts_ids"]], True
            else:
                return self._json(400, {
                    "error": "disaggregated routing serves token-id "
                             "requests (prompt_ids / prompts_ids); the "
                             "router has no tokenizer"
                })
            if not prompts or any(not p for p in prompts):
                return self._json(400, {
                    "error": "prompts must be non-empty id lists"
                })
            mt = req.get("max_tokens")
            try:
                rows = core.generate_disaggregated(
                    prompts, None if mt is None else int(mt),
                    deadline_s, trace=trace,
                    extra_headers=self._tenant_headers(),
                )
            except _DownstreamError as e:
                try:
                    obj = json.loads(e.body)
                except json.JSONDecodeError:
                    obj = {"error": e.body.decode(errors="replace")}
                headers = (
                    {"Retry-After": "1"} if e.status in (429, 503) else None
                )
                return self._json(e.status, obj, headers)
            except NoReplicaAvailable as e:
                return self._json(503, {"error": str(e)},
                                  headers={"Retry-After": "2"})
            except ReplicaUnavailable as e:
                return self._json(503, {"error": str(e)},
                                  headers={"Retry-After": "1"})
            payload = ({"completions_ids": rows} if plural
                       else {"completion_ids": rows[0]})
            if trace is not None:
                payload["trace_id"] = trace.trace_id
            return self._json(200, payload)

    class Server(ThreadingHTTPServer):
        daemon_threads = False  # drain joins in-flight responses
        block_on_close = True

        def handle_error(self, request, client_address):
            exc = sys.exc_info()[1]
            if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                                TimeoutError)):
                reg.counter("pfx_http_client_gone_total").inc()
                return
            super().handle_error(request, client_address)

    httpd = Server((args.host, args.port), Handler)
    orig_handlers = {}

    def _on_signal(signum, frame):
        for sig, h in orig_handlers.items():
            signal.signal(sig, h)
        flags["draining"] = True
        recorder.record({"event": "drain_start", "signum": signum,
                         "in_flight": core.depth()})
        print(
            f"signal {signum}: router draining — admission closed, "
            f"{core.depth()} request(s) in flight "
            "(send again to force-quit)",
            flush=True,
        )

        def _drain():
            core.close()
            core.join()
            httpd.shutdown()

        threading.Thread(target=_drain, name="router-drain",
                         daemon=True).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        orig_handlers[sig] = signal.signal(sig, _on_signal)

    # ---- control-plane recovery (docs/serving.md "Control-plane
    # recovery"): fold the previous incarnation's journal back in BEFORE
    # anything spawns — tenant buckets restore with the death window's
    # worth of refill (no free burst allowance mid-429-storm), controller
    # clocks rebase so the restart can neither double-spawn nor
    # insta-rescale, and each supervisor reconciles its journaled slots
    # against what is actually running (adopt, reap, or respawn) --------
    def _journal_snapshot():
        """Full control-plane state for FleetJournal compaction — the
        same shape replay_fleet_state produces, so a compacted journal
        replays identically to the append log it replaced."""
        views = core.replica_views()
        by_url = {v["url"]: v for v in views}
        slots = {}
        ctl_state = {}
        for c in controllers:
            pool = c.role or "monolith"
            rows = {}
            for mv in c.supervisor.views():
                if not mv.get("desired") and mv.get("pid") is None:
                    continue  # empty slot: nothing to recover
                rv = by_url.get(mv["url"]) or {}
                rows[str(mv["slot"])] = {
                    "port": mv["port"], "url": mv["url"],
                    "rid": mv["replica_id"],
                    "cmd_hash": mv.get("cmd_hash"),
                    "pid": mv.get("pid"),
                    "boot_id": rv.get("boot_id"),
                    "phase": ("adopted" if mv.get("adopted")
                              else "spawned"),
                }
            slots[pool] = rows
            ctl_state[pool] = c.journal_state()
        return {
            "replicas": {
                v["key"]: {f: v.get(f) for f in (
                    "url", "role", "state", "replica_id", "pid",
                    "boot_id")}
                for v in views
            },
            "slots": slots,
            "controller": ctl_state,
            "tenants": core.tenant_journal_snapshot(),
        }

    journal_records, journal_note = read_fleet_journal(journal_path)
    if journal_note:
        print(f"recovery: {journal_note}", flush=True)
    replayed = (replay_fleet_state(journal_records)
                if journal_records else None)
    age_s = 0.0
    if replayed is not None and replayed.get("wall"):
        age_s = max(0.0, time.time() - float(replayed["wall"]))
    if replayed is not None:
        restored = core.restore_tenant_buckets(
            (replayed.get("tenants") or {}).get("buckets") or {},
            age_s=age_s,
        )
        print(
            f"recovery: replayed {replayed['records']} journal "
            f"record(s) (death window {age_s:.1f}s); restored "
            f"{restored} tenant bucket(s)", flush=True,
        )
        reg.counter("pfx_router_recoveries_total").inc()
    for ctl in controllers:
        pool = ctl.role or "monolith"
        facts = {}
        if replayed is not None:
            cs = (replayed.get("controller") or {}).get(pool)
            if cs:
                ctl.restore_clocks(
                    target=cs.get("target"), tick=cs.get("tick"),
                    up_age_s=cs.get("up_age_s"),
                    scale_age_s=cs.get("scale_age_s"),
                    extra_age_s=age_s,
                )
            facts = (replayed.get("slots") or {}).get(pool) or {}
        # probe EVERY slot, journaled or not: with facts the full
        # identity triple must match; without (journal lost), a live
        # process answering with the slot's own replica_id is adopted —
        # either way a surviving fleet is re-entered with zero respawns
        probe = {
            str(i): (facts.get(str(i)) or {})
            for i in range(ctl.supervisor.max_replicas)
        }
        adopted = ctl.supervisor.adopt(probe)
        if adopted:
            ctl._register(adopted)
            print(
                f"recovery: re-adopted {len(adopted)} live "
                f"replica(s) into the {pool} pool (zero respawns, "
                "no flap budget spent)", flush=True,
            )
    journal.set_snapshot_fn(_journal_snapshot)

    core.start()
    for ctl in controllers:
        # spawn min_replicas (registered with the core as they come up)
        # and start each control loop; the poller walks each replica
        # booting -> warm -> serving as it answers /healthz
        ctl.start()

    stop_scale_log = threading.Event()

    def _scale_event_log():
        # mirror controller scale decisions into the fleet log so the
        # offline fleet report can mark them on the curves — only NEW
        # non-hold rows are appended, tracked by each row's monotonic
        # `tick` (a LENGTH high-water mark would stall forever once the
        # bounded deque reaches maxlen and len() stops growing)
        seen = {id(c): 0 for c in controllers}
        while not stop_scale_log.wait(1.0):
            for ctl in controllers:
                last = seen[id(ctl)]
                # view() copies the log under the controller's own
                # lock — iterating the live deque would race tick()'s
                # append ("deque mutated during iteration" would kill
                # this thread and silently end scale-event mirroring)
                for row in ctl.view().get("decisions", []):
                    tick = int(row.get("tick", 0))
                    if tick <= last:
                        continue
                    seen[id(ctl)] = max(seen[id(ctl)], tick)
                    if row.get("action") not in (None, "hold"):
                        core.fleet_log.event({
                            "event": "scale",
                            "pool": ctl.role or "fleet",
                            "action": row.get("action"),
                            "reason": row.get("reason", ""),
                            "target": row.get("target"),
                        })

    if controllers:
        threading.Thread(target=_scale_event_log,
                         name="router-scale-log", daemon=True).start()
    mode = identity["scheduler"]
    supervising = ""
    if pool_supervise:
        supervising = (
            f"; supervising prefill {args.min_prefill}.."
            f"{args.max_prefill} from port {args.prefill_base_port}, "
            f"decode {args.min_decode}..{args.max_decode} from port "
            f"{args.decode_base_port}"
        )
    elif controllers:
        supervising = (
            f"; supervising {args.min_replicas}..{args.max_replicas} "
            f"replicas from port {args.base_port}"
        )
    print(
        f"router on {args.host}:{args.port} ({mode}; "
        f"{len(core.replicas)} replica(s), max in-flight "
        f"{args.max_inflight}, retries {args.retries}, "
        f"handoff {args.handoff}" + supervising + ")",
        flush=True,
    )
    def _force_quit(where):
        # os._exit skips every finally: take the managed children down
        # HARD so their ports free up for the next boot — orphans
        # running old code would crash-loop the replacement fleet into
        # quarantine while still answering /healthz
        print(f"force-quit on second interrupt ({where})", flush=True)
        recorder.record({"event": "force_quit"})
        recorder.dump(reason="force_quit")
        for ctl in controllers:
            ctl.supervisor.kill_all()
        os._exit(130)

    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        _force_quit("serving")
    finally:
        try:
            stop_scale_log.set()
            for ctl in controllers:
                # stop scaling first, then drain the children
                # gracefully: each managed replica gets SIGTERM,
                # answers its admitted work, exits 0 (the PR 3
                # contract) — the router never leaves orphans behind a
                # clean shutdown
                ctl.stop()
            for ctl in controllers:
                ctl.supervisor.stop_all()
            core.stop()
            httpd.server_close()
        except KeyboardInterrupt:
            # the second signal landed while the graceful teardown was
            # already underway (a fast drain finishes before a human's
            # second Ctrl-C): still honor the force-quit contract —
            # never a traceback, never an orphan
            _force_quit("teardown")
    if flags["draining"]:
        print("router drained cleanly: all admitted requests answered",
              flush=True)
    return 0


def cmd_drain(args) -> int:
    """The rolling-deploy primitive: ask a RUNNING router to drain one
    replica, then watch it walk draining -> gone (the replica answers
    its admitted work, exits 0, and its port goes refused).  Repeat per
    replica — redeploying between drains — for a full rolling deploy
    (runbook: docs/serving.md)."""
    import urllib.error
    import urllib.request

    from paddlefleetx_tpu.core.router import admin_headers

    admin = args.admin.rstrip("/")
    req = urllib.request.Request(
        f"{admin}/admin/drain",
        data=json.dumps(
            {"replica": args.replica_id or None}
        ).encode(),
        # the shared PFX_ADMIN_TOKEN rides along so the deploy tooling
        # works against a remote, token-gated router
        headers={"Content-Type": "application/json", **admin_headers()},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            out = json.load(r)
    except urllib.error.HTTPError as e:
        print(f"drain refused: {e.code} "
              f"{(e.read() or b'').decode(errors='replace')}",
              file=sys.stderr, flush=True)
        return 1
    except (urllib.error.URLError, OSError) as e:
        # router down / wrong --admin: a clean rc-1 message, never a
        # traceback from the deploy tooling
        print(f"cannot reach router at {admin}: {e}",
              file=sys.stderr, flush=True)
        return 1
    key = out["replica"]
    print(f"drain initiated: replica {key} (pid {out.get('pid')})",
          flush=True)
    last = None
    t_end = time.time() + args.timeout
    while time.time() < t_end:
        try:
            with urllib.request.urlopen(
                f"{admin}/replicas", timeout=10
            ) as r:
                views = json.load(r)["replicas"]
        except (urllib.error.URLError, OSError) as e:
            # transient: the router itself may be mid-restart; keep
            # polling until the timeout decides
            print(f"router poll failed ({e}); retrying", flush=True)
            time.sleep(1.0)
            continue
        view = next((v for v in views if v["key"] == key), None)
        if view is None:
            print(f"replica {key} disappeared from the router",
                  file=sys.stderr, flush=True)
            return 1
        if view["state"] != last:
            last = view["state"]
            print(f"replica {key}: {last}", flush=True)
        if view["state"] == "gone":
            print(f"replica {key} drained and exited", flush=True)
            return 0
        time.sleep(0.3)
    print(f"timeout: replica {key} still {last!r} after "
          f"{args.timeout:g}s", file=sys.stderr, flush=True)
    return 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("command", nargs="?", default="serve",
                    choices=("serve", "drain"),
                    help="serve (default): run the front door; drain: "
                    "ask a running router to drain one replica and wait "
                    "for it to exit (rolling deploy)")
    ap.add_argument("--port", type=int, default=0,
                    help="router listen port (serve mode)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (unauthenticated endpoint: "
                    "exposing beyond loopback is an operator decision)")
    ap.add_argument("--replica", action="append", default=[],
                    help="monolith replica base URL (repeatable)")
    ap.add_argument("--prefill", action="append", default=[],
                    help="prefill-pool replica base URL (repeatable; "
                    "requires --decode too)")
    ap.add_argument("--decode", action="append", default=[],
                    help="decode-pool replica base URL (repeatable)")
    ap.add_argument("--max-inflight", type=int, default=64,
                    help="router admission bound: requests in flight "
                    "beyond this get HTTP 429 (the front-door queue)")
    ap.add_argument("--retries", type=int, default=2,
                    help="max retries on ANOTHER replica after "
                    "connection-refused (partial responses never retry)")
    ap.add_argument("--handoff", choices=("direct", "proxy"),
                    default="direct",
                    help="disaggregated KV-handoff transport: 'direct' "
                    "(default) issues a placement ticket and the "
                    "prefill replica POSTs the payload straight to the "
                    "chosen decode replica — handoff bytes never "
                    "transit the router; 'proxy' carries the payload "
                    "through the router (the drilled fallback a failed "
                    "direct send degrades to)")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="default per-request routing deadline seconds")
    ap.add_argument("--max-deadline", type=float, default=600.0,
                    help="ceiling on client deadline_s")
    ap.add_argument("--poll-interval", type=float, default=0.5,
                    help="replica /healthz poll cadence seconds")
    ap.add_argument("--eject-after", type=int, default=3,
                    help="consecutive failed polls before a replica is "
                    "marked gone")
    ap.add_argument("--serve-after", type=int, default=1,
                    help="consecutive healthy polls before a warm "
                    "replica starts receiving traffic")
    ap.add_argument("--tenants", default="",
                    help="per-tenant quota/weight config JSON "
                    "(docs/serving.md 'Multi-tenant isolation'); "
                    "unset = one anonymous tenant, no limits")
    # ---- elastic control plane (--supervise; docs/serving.md) ----
    ap.add_argument("--supervise", action="store_true",
                    help="spawn + supervise the replicas as managed "
                    "subprocesses and run the SLO-driven scale "
                    "controller (crash-restart with backoff, flap-"
                    "budget quarantine, warm boot, breach-driven "
                    "scale-up, idle scale-down via remote drains)")
    ap.add_argument("--replica-cmd", default="",
                    help="supervise: replica command template with "
                    "{port} and {replica_id} placeholders, e.g. "
                    "'python tools/serve.py -c cfg.yaml --port {port} "
                    "--replica-id {replica_id}'")
    ap.add_argument("--min-replicas", type=int, default=1,
                    help="supervise: replica floor (boot + scale-down "
                    "bound)")
    ap.add_argument("--max-replicas", type=int, default=4,
                    help="supervise: replica ceiling (scale-up bound)")
    ap.add_argument("--base-port", type=int, default=8101,
                    help="supervise: slot i listens on base-port + i")
    ap.add_argument("--compile-cache-dir", default="",
                    help="supervise: persistent compile cache passed to "
                    "every spawned replica (--compile-cache-dir on "
                    "serve.py) — warm boot makes scale-up seconds, not "
                    "a cold trace")
    ap.add_argument("--replica-log-dir", default="",
                    help="supervise: per-replica stdout logs (default "
                    "<PFX_FLIGHT_DIR>/replicas)")
    ap.add_argument("--control-interval", type=float, default=1.0,
                    help="supervise: seconds between control-loop ticks")
    ap.add_argument("--scale-high-depth", type=float, default=4.0,
                    help="supervise: scale up when avg waiting depth "
                    "per serving replica exceeds this")
    ap.add_argument("--scale-low-depth", type=float, default=0.5,
                    help="supervise: fleet counts as idle below this "
                    "avg depth (hysteresis band with --scale-high-depth)")
    ap.add_argument("--scale-up-cooldown", type=float, default=5.0,
                    help="supervise: min seconds between scale-ups")
    ap.add_argument("--scale-down-cooldown", type=float, default=60.0,
                    help="supervise: min seconds after any scale action "
                    "before a scale-down")
    ap.add_argument("--scale-idle", type=float, default=30.0,
                    help="supervise: sustained idle seconds before a "
                    "scale-down")
    ap.add_argument("--flap-budget", type=int, default=5,
                    help="supervise: crash-restarts inside --flap-window "
                    "before a replica is quarantined LOUDLY")
    ap.add_argument("--flap-window", type=float, default=60.0,
                    help="supervise: flap-budget window seconds")
    ap.add_argument("--restart-backoff", type=float, default=0.5,
                    help="supervise: base seconds of the exponential "
                    "crash-restart backoff")
    # ---- disaggregated pool supervision (--supervise with pool cmds;
    # docs/serving.md "Disaggregated operations") ----
    ap.add_argument("--prefill-cmd", default="",
                    help="supervise the PREFILL pool: serve.py command "
                    "template with {port}/{replica_id} placeholders "
                    "(must include --role prefill); requires "
                    "--decode-cmd too")
    ap.add_argument("--decode-cmd", default="",
                    help="supervise the DECODE pool: serve.py command "
                    "template (must include --role decode)")
    ap.add_argument("--min-prefill", type=int, default=1,
                    help="prefill-pool replica floor")
    ap.add_argument("--max-prefill", type=int, default=4,
                    help="prefill-pool replica ceiling")
    ap.add_argument("--min-decode", type=int, default=1,
                    help="decode-pool replica floor")
    ap.add_argument("--max-decode", type=int, default=4,
                    help="decode-pool replica ceiling")
    ap.add_argument("--prefill-base-port", type=int, default=8201,
                    help="prefill slot i listens on this + i")
    ap.add_argument("--decode-base-port", type=int, default=8301,
                    help="decode slot i listens on this + i")
    ap.add_argument("--decode-low-blocks", type=int, default=0,
                    help="decode-pool scale-up watermark: any serving "
                    "decode replica reporting available_blocks at or "
                    "below this is arena pressure (0 = occupancy/"
                    "breach signals only)")
    ap.add_argument("--router-id", default="",
                    help="identity for this router's /healthz block")
    ap.add_argument("--admin", default="http://127.0.0.1:9000",
                    help="drain mode: the running router's base URL")
    ap.add_argument("--replica-id", default="",
                    help="drain mode: replica to drain (router key or "
                    "identity id; default: least-loaded serving replica)")
    ap.add_argument("--timeout", type=float, default=180.0,
                    help="drain mode: seconds to wait for the replica "
                    "to reach gone")
    args = ap.parse_args(argv)

    if args.command == "drain":
        return cmd_drain(args)
    if not args.port:
        ap.error("serve mode requires --port")
    if args.supervise:
        if bool(args.prefill_cmd) != bool(args.decode_cmd):
            ap.error("disaggregated pool supervision needs BOTH "
                     "--prefill-cmd and --decode-cmd")
        if args.prefill_cmd and args.replica_cmd:
            ap.error("--replica-cmd (monolith fleet) and --prefill-cmd/"
                     "--decode-cmd (pool fleet) are mutually exclusive")
        if not (args.replica_cmd or args.prefill_cmd):
            ap.error("--supervise requires --replica-cmd (monolith "
                     "fleet) or --prefill-cmd + --decode-cmd "
                     "(disaggregated pools), each a serve.py command "
                     "template with {port}")
        if args.replica or args.prefill or args.decode:
            ap.error("--supervise manages its own replicas; static "
                     "--replica/--prefill/--decode URLs are exclusive "
                     "with it")
        if args.prefill_cmd:
            # overlapping slot port ranges would surface as bind-failure
            # crash loops and a misleading flap-budget quarantine — make
            # the misconfiguration a config error instead
            pools = [
                ("prefill", args.prefill_base_port, args.max_prefill),
                ("decode", args.decode_base_port, args.max_decode),
            ]
            ranges = [(n, b, b + mx - 1) for n, b, mx in pools]
            (na, alo, ahi), (nb, blo, bhi) = ranges
            if alo <= bhi and blo <= ahi:
                ap.error(
                    f"slot port ranges overlap: {na} {alo}..{ahi} vs "
                    f"{nb} {blo}..{bhi} — replicas would fight for the "
                    "same port and crash-loop into quarantine; move "
                    f"--{nb}-base-port past the {na} pool's "
                    f"--max-{na} slots"
                )
            for name, lo, hi in ranges:
                if lo <= args.port <= hi:
                    ap.error(
                        f"--port {args.port} falls inside the {name} "
                        f"slot range {lo}..{hi}; the router and a "
                        f"{name} replica would fight for it")
    elif not (args.replica or args.prefill or args.decode):
        ap.error("need --replica URLs, --prefill and --decode URLs, "
                 "or --supervise with a replica command template")
    return serve_router(args)


if __name__ == "__main__":
    sys.exit(main())
