"""Tokenize + pack a jsonl corpus into the mmap training format.

Re-design of the reference preprocessing pipeline
(ppfleetx/data/data_tools/gpt/preprocess_data.py: jsonl {"text"} ->
tokenize (multiprocess) -> append eos per doc -> <prefix>_ids.npy (token
stream) + <prefix>_idx.npz (per-doc lengths), consumed by GPTDataset
(gpt_dataset.py:95-116 in the reference; data/gpt_dataset.py here).

Tokenizers: gpt (byte-level BPE; needs --vocab_file/--merges_file) or
t5 (unigram; needs --vocab_file json).

Usage:
  python tools/preprocess_data.py --input corpus.jsonl --output_prefix data/corpus \
      --tokenizer gpt --vocab_file vocab.json --merges_file merges.txt [--workers 8]
"""

import argparse
import json
import multiprocessing as mp
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

_TOK = None


def _init_worker(kind, vocab_file, merges_file):
    global _TOK
    if kind == "gpt":
        from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

        _TOK = GPTTokenizer(vocab_file, merges_file)
        _TOK._eos = _TOK.eos_token_id
    else:
        from paddlefleetx_tpu.data.tokenizers.t5_tokenizer import T5Tokenizer

        _TOK = T5Tokenizer.from_file(vocab_file)
        _TOK._eos = _TOK.eos_id


def _encode(line):
    line = line.strip()
    if not line:
        return None
    text = json.loads(line).get("text", "")
    if not text:
        return None
    ids = _TOK.encode(text)
    if not ids or ids[-1] != _TOK._eos:
        ids = list(ids) + [_TOK._eos]
    return ids


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True, help="jsonl with {'text': ...}")
    ap.add_argument("--output_prefix", required=True)
    ap.add_argument("--tokenizer", choices=["gpt", "t5"], default="gpt")
    ap.add_argument("--vocab_file", required=True)
    ap.add_argument("--merges_file", default=None)
    ap.add_argument("--workers", type=int, default=1)
    args = ap.parse_args(argv)

    init_args = (args.tokenizer, args.vocab_file, args.merges_file)

    # stream line -> tokens -> compact uint32 chunks (never hold the whole
    # corpus as Python lists: ~4 bytes/token peak instead of ~36)
    def doc_arrays():
        with open(args.input) as f:
            if args.workers > 1:
                with mp.Pool(args.workers, initializer=_init_worker, initargs=init_args) as pool:
                    for d in pool.imap(_encode, f, chunksize=64):
                        if d:
                            yield np.asarray(d, np.uint32)
            else:
                _init_worker(*init_args)
                for line in f:
                    d = _encode(line)
                    if d:
                        yield np.asarray(d, np.uint32)

    chunks, lens, max_id = [], [], 0
    for arr in doc_arrays():
        chunks.append(arr)
        lens.append(len(arr))
        max_id = max(max_id, int(arr.max()))
    if not chunks:
        print("no documents with text found — nothing written", file=sys.stderr)
        sys.exit(1)

    dtype = np.uint16 if max_id < 2**16 else np.uint32
    stream = np.concatenate(chunks).astype(dtype)
    lens = np.asarray(lens, np.int32)

    os.makedirs(os.path.dirname(os.path.abspath(args.output_prefix)) or ".", exist_ok=True)
    np.save(args.output_prefix + "_ids.npy", stream)
    np.savez(args.output_prefix + "_idx.npz", lens=lens)
    print(
        f"packed {len(lens)} docs, {stream.size} tokens ({dtype.__name__}) -> "
        f"{args.output_prefix}_ids.npy / _idx.npz"
    )


if __name__ == "__main__":
    main()
