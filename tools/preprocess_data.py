"""Tokenize + pack a jsonl corpus into the mmap training format.

Re-design of the reference preprocessing pipeline
(ppfleetx/data/data_tools/gpt/preprocess_data.py: jsonl {"text"} ->
tokenize (multiprocess) -> append eos per doc -> <prefix>_ids.npy (token
stream) + <prefix>_idx.npz (per-doc lengths), consumed by GPTDataset
(gpt_dataset.py:95-116 in the reference; data/gpt_dataset.py here).

Tokenizers: gpt (byte-level BPE; needs --vocab_file/--merges_file),
t5 (unigram; needs --vocab_file json), or ernie (wordpiece; needs
--vocab_file txt).

The ernie path splits each document into sentences (the reference's
--split_sentences mode, data_tools/ernie/preprocess/create_pretraining_data.py:
226-259: NLTK punkt / newline splitter; here a punctuation-rule splitter
covering Latin and CJK enders) and writes the sentence-indexed corpus
ErnieDataset consumes: <prefix>_ids.npy + <prefix>_idx.npz with
``sent_lens`` and ``doc_sent_counts``.

Usage:
  python tools/preprocess_data.py --input corpus.jsonl --output_prefix data/corpus \
      --tokenizer gpt --vocab_file vocab.json --merges_file merges.txt [--workers 8]
  python tools/preprocess_data.py --input corpus.jsonl --output_prefix data/ernie \
      --tokenizer ernie --vocab_file vocab.txt
"""

import argparse
import json
import multiprocessing as mp
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import re

import numpy as np

_TOK = None

# sentence enders: Latin .!? (not mid-number dots) and CJK 。！？；…
_SENT_END = re.compile(r"([.!?;]+[\s\"')\]]*\s+|[。！？；…]+[”’）》]*)")


def split_sentences(text: str):
    """Punctuation-rule sentence splitter (both scripts), newline-aware."""
    sents = []
    for block in text.splitlines():
        block = block.strip()
        if not block:
            continue
        # split() alternates text / captured ender: accumulate, flush after
        # each ender so it stays attached to its sentence
        cur = ""
        for i, piece in enumerate(_SENT_END.split(block)):
            cur += piece
            if i % 2:
                if cur.strip():
                    sents.append(cur.strip())
                cur = ""
        if cur.strip():
            sents.append(cur.strip())
    return sents


def _init_worker(kind, vocab_file, merges_file):
    global _TOK
    if kind == "gpt":
        from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

        _TOK = GPTTokenizer(vocab_file, merges_file)
        _TOK._eos = _TOK.eos_token_id
    elif kind == "ernie":
        from paddlefleetx_tpu.data.tokenizers.ernie_tokenizer import ErnieTokenizer

        _TOK = ErnieTokenizer.from_file(vocab_file)
    else:
        from paddlefleetx_tpu.data.tokenizers.t5_tokenizer import T5Tokenizer

        _TOK = T5Tokenizer.from_file(vocab_file)
        _TOK._eos = _TOK.eos_id


def _encode(line):
    line = line.strip()
    if not line:
        return None
    text = json.loads(line).get("text", "")
    if not text:
        return None
    ids = _TOK.encode(text)
    if not ids or ids[-1] != _TOK._eos:
        ids = list(ids) + [_TOK._eos]
    return ids


def _encode_ernie(line):
    """One document -> list of per-sentence id lists (no special tokens:
    ErnieDataset adds [CLS]/[SEP] when building sentence-pair samples)."""
    line = line.strip()
    if not line:
        return None
    text = json.loads(line).get("text", "")
    if not text:
        return None
    sents = []
    for sent in split_sentences(text):
        ids = _TOK.convert_tokens_to_ids(_TOK.tokenize(sent))
        if ids:
            sents.append(ids)
    return sents or None


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True, help="jsonl with {'text': ...}")
    ap.add_argument("--output_prefix", required=True)
    ap.add_argument("--tokenizer", choices=["gpt", "t5", "ernie"], default="gpt")
    ap.add_argument("--vocab_file", required=True)
    ap.add_argument("--merges_file", default=None)
    ap.add_argument("--workers", type=int, default=1)
    args = ap.parse_args(argv)

    init_args = (args.tokenizer, args.vocab_file, args.merges_file)

    if args.tokenizer == "ernie":
        return _main_ernie(args, init_args)

    # stream line -> tokens -> compact uint32 chunks (never hold the whole
    # corpus as Python lists: ~4 bytes/token peak instead of ~36)
    def doc_arrays():
        with open(args.input) as f:
            if args.workers > 1:
                with mp.Pool(args.workers, initializer=_init_worker, initargs=init_args) as pool:
                    for d in pool.imap(_encode, f, chunksize=64):
                        if d:
                            yield np.asarray(d, np.uint32)
            else:
                _init_worker(*init_args)
                for line in f:
                    d = _encode(line)
                    if d:
                        yield np.asarray(d, np.uint32)

    chunks, lens, max_id = [], [], 0
    for arr in doc_arrays():
        chunks.append(arr)
        lens.append(len(arr))
        max_id = max(max_id, int(arr.max()))
    if not chunks:
        print("no documents with text found — nothing written", file=sys.stderr)
        sys.exit(1)

    dtype = np.uint16 if max_id < 2**16 else np.uint32
    stream = np.concatenate(chunks).astype(dtype)
    lens = np.asarray(lens, np.int32)

    os.makedirs(os.path.dirname(os.path.abspath(args.output_prefix)) or ".", exist_ok=True)
    np.save(args.output_prefix + "_ids.npy", stream)
    np.savez(args.output_prefix + "_idx.npz", lens=lens)
    print(
        f"packed {len(lens)} docs, {stream.size} tokens ({dtype.__name__}) -> "
        f"{args.output_prefix}_ids.npy / _idx.npz"
    )


def _main_ernie(args, init_args):
    """Sentence-indexed corpus for ErnieDataset (reference
    create_pretraining_data.py --split_sentences output shape)."""

    def doc_sents():
        with open(args.input) as f:
            if args.workers > 1:
                with mp.Pool(
                    args.workers, initializer=_init_worker, initargs=init_args
                ) as pool:
                    yield from pool.imap(_encode_ernie, f, chunksize=64)
            else:
                _init_worker(*init_args)
                for line in f:
                    yield _encode_ernie(line)

    chunks, sent_lens, doc_sent_counts, max_id = [], [], [], 0
    for sents in doc_sents():
        if not sents:
            continue
        for ids in sents:
            arr = np.asarray(ids, np.uint32)
            chunks.append(arr)
            sent_lens.append(len(arr))
            max_id = max(max_id, int(arr.max()))
        doc_sent_counts.append(len(sents))
    if not chunks:
        print("no documents with text found — nothing written", file=sys.stderr)
        sys.exit(1)

    dtype = np.uint16 if max_id < 2**16 else np.uint32
    stream = np.concatenate(chunks).astype(dtype)

    os.makedirs(os.path.dirname(os.path.abspath(args.output_prefix)) or ".", exist_ok=True)
    np.save(args.output_prefix + "_ids.npy", stream)
    np.savez(
        args.output_prefix + "_idx.npz",
        sent_lens=np.asarray(sent_lens, np.int32),
        doc_sent_counts=np.asarray(doc_sent_counts, np.int32),
    )
    print(
        f"packed {len(doc_sent_counts)} docs / {len(sent_lens)} sentences, "
        f"{stream.size} tokens ({dtype.__name__}) -> "
        f"{args.output_prefix}_ids.npy / _idx.npz"
    )


if __name__ == "__main__":
    main()
