"""Training entry point (reference tools/train.py:44-73):
config -> dist init -> build module -> dataloaders -> engine.fit.

Crash-loop contract: relaunching the same command auto-resumes from the
newest restorable checkpoint (corrupt ones are quarantined and skipped —
docs/fault_tolerance.md).  A SIGTERM mid-run checkpoints and exits 0;
``--exit-after-save`` bounds the run to one checkpoint interval."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init

from paddlefleetx_tpu.core.engine import Engine
from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.data.builders import build_dataloader
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.utils.config import get_config, parse_args
from paddlefleetx_tpu.utils.log import advertise, logger


def main(argv=None):
    args = parse_args(argv)
    cfg = get_config(args.config, overrides=args.override)
    advertise()

    # crash postmortem: an uncaught exception dumps the flight recorder
    # ring (recent step records, data_skips, rollback/preempt events) to
    # flight_recorder.jsonl (PFX_FLIGHT_RECORDER) before the traceback —
    # no longer dependent on Engine.metrics_file being configured
    from paddlefleetx_tpu.utils.telemetry import get_flight_recorder

    get_flight_recorder().install_excepthook(
        path=os.path.join(
            cfg.Engine.save_load.get("output_dir", "./output"),
            "flight_recorder.jsonl",
        )
    )

    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    from paddlefleetx_tpu.utils.checkpoint import (
        latest_checkpoint,
        resume_with_fallback,
    )

    output_dir = cfg.Engine.save_load.get("output_dir", "./output")
    ckpt_dir = cfg.Engine.save_load.get("ckpt_dir")
    auto_resume = not ckpt_dir and bool(cfg.Engine.save_load.get("auto_resume"))
    if auto_resume:
        # crash-loop restart contract (reference _load_recovery,
        # eager_engine.py:244,816-825): newest restorable step_N dir wins.
        # This peek only decides whether pretrained warm-start applies, so
        # it must be side-effect free (quarantine=False); the real resolve
        # below quarantines as needed.
        resuming = latest_checkpoint(output_dir, quarantine=False) is not None
    else:
        resuming = bool(ckpt_dir)
    if resuming and cfg.Engine.save_load.get("pretrained_params"):
        # the resume load replaces params wholesale — skip the (possibly
        # multi-GB) warm-start restore on every crash-loop restart
        logger.info("pretrained_params skipped: resume checkpoint takes over")
        cfg.Engine.save_load.pretrained_params = None

    with mesh:
        engine = Engine(cfg, module, mesh)
        if getattr(args, "exit_after_save", False):
            engine.exit_after_save = True
        if ckpt_dir:
            engine.load(ckpt_dir)
        elif auto_resume:
            loaded = resume_with_fallback(engine, output_dir)
            if loaded is None and resuming:
                # the peek promised a resume (and may have skipped the
                # pretrained warm start on its word): silently training
                # from scratch would be the worst outcome — stop loudly
                raise RuntimeError(
                    f"auto_resume: checkpoints exist under {output_dir} "
                    "but none restored (see QUARANTINED logs); refusing "
                    "to silently restart from scratch — inspect/remove "
                    "the *.corrupt dirs, or disable auto_resume to "
                    "intentionally start over"
                )
        # loaders built after load so the sampler resumes the data order
        # from the checkpoint's consumed_samples
        train_loader = build_dataloader(
            cfg, "Train", consumed_samples=engine._consumed_samples
        )
        eval_loader = (
            build_dataloader(cfg, "Eval")
            if "Eval" in cfg.get("Data", {}) and int(cfg.Engine.get("eval_freq", 0) or 0)
            else None
        )
        engine.fit(train_loader, eval_loader)
        # data-pipeline health epilogue: skips spent and host-side wait are
        # the two numbers an operator checks after a flaky-storage run
        skips = int(getattr(train_loader, "skips", 0) or 0)
        if skips:
            logger.warning(
                f"run finished with {skips} corrupt sample(s) skipped "
                "(data_skip events in the metrics stream — inspect the "
                "shard before the next run)"
            )
        stats_fn = getattr(train_loader, "stats", None)
        if callable(stats_fn):
            wait = stats_fn().get("data_wait_s", 0)
            if wait:
                logger.info(f"host data pipeline: {wait}s total step wait")
        # observatory epilogue: the run's memory watermark + compile tally
        # and the one-liner that turns this run's artifacts into a report
        from paddlefleetx_tpu.utils.model_stats import get_compile_watcher
        from paddlefleetx_tpu.utils.tracing import export_chrome_trace

        if engine._fit_peak_bytes:
            logger.info(
                f"memory watermark: {engine._fit_peak_bytes / (1 << 20):.0f} "
                "MiB peak this fit (per-record detail under 'mem')"
            )
        compiles = get_compile_watcher().snapshot()
        if compiles:
            total = sum(c.get("elapsed_s", 0.0) for c in compiles)
            logger.info(
                f"compile events: {len(compiles)} ({total:.1f}s backend "
                "compile) — retrace attribution rides the flight ring"
            )
        trace_path = export_chrome_trace()
        report_cmd = f"python tools/report.py --run-dir {output_dir}"
        if cfg.Engine.get("metrics_file"):
            report_cmd += f" --metrics {cfg.Engine.metrics_file}"
        if trace_path:
            report_cmd += f" --trace {trace_path}"
        logger.info(f"run report: {report_cmd} -o report.html")
        if engine.preempted:
            # final checkpoint already written (preemption / exit_after_save
            # path); exit 0 so the orchestrator relaunches with auto_resume
            logger.info("clean early exit: final checkpoint saved; exiting 0")
            return
        if cfg.Engine.save_load.get("save_steps"):
            engine.save()


if __name__ == "__main__":
    main()
