"""Training entry point (reference tools/train.py:44-73):
config -> dist init -> build module -> dataloaders -> engine.fit."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init

from paddlefleetx_tpu.core.engine import Engine
from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.data.builders import build_dataloader
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.utils.config import get_config, parse_args
from paddlefleetx_tpu.utils.log import advertise, logger


def main(argv=None):
    args = parse_args(argv)
    cfg = get_config(args.config, overrides=args.override)
    advertise()

    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    ckpt_dir = cfg.Engine.save_load.get("ckpt_dir")
    if not ckpt_dir and cfg.Engine.save_load.get("auto_resume"):
        # crash-loop restart contract (reference _load_recovery,
        # eager_engine.py:244,816-825): newest complete step_N dir wins
        from paddlefleetx_tpu.utils.checkpoint import latest_checkpoint

        ckpt_dir = latest_checkpoint(cfg.Engine.save_load.get("output_dir", "./output"))
        if ckpt_dir:
            logger.info(f"auto_resume: found {ckpt_dir}")
    if ckpt_dir and cfg.Engine.save_load.get("pretrained_params"):
        # the resume load replaces params wholesale — skip the (possibly
        # multi-GB) warm-start restore on every crash-loop restart
        logger.info("pretrained_params skipped: resume checkpoint takes over")
        cfg.Engine.save_load.pretrained_params = None

    with mesh:
        engine = Engine(cfg, module, mesh)
        if ckpt_dir:
            engine.load(ckpt_dir)
        # loaders built after load so the sampler resumes the data order
        # from the checkpoint's consumed_samples
        train_loader = build_dataloader(
            cfg, "Train", consumed_samples=engine._consumed_samples
        )
        eval_loader = (
            build_dataloader(cfg, "Eval")
            if "Eval" in cfg.get("Data", {}) and int(cfg.Engine.get("eval_freq", 0) or 0)
            else None
        )
        engine.fit(train_loader, eval_loader)
        if cfg.Engine.save_load.get("save_steps"):
            engine.save()


if __name__ == "__main__":
    main()
