"""Import a HuggingFace GPT-2 checkpoint into the native format.

Reference parity: utils/download.py + per-model pretrained loaders let
reference users start from published weights; this tool does the same from
the ubiquitous HF format (torch runs CPU-only here).  Output layout:

  <out>/params/...        orbax params-only checkpoint
  <out>/meta.json         {"format": "params-only", "source": ...}
  <out>/model.yaml        the matching Model config block

Consume it with:
  Engine.save_load.pretrained_params: <out>     (train/finetune init)
  Engine.save_load.ckpt_dir: <out>              (serve/export/inference)

Usage:
  python tools/convert_hf_gpt2.py --model /path/to/hf_gpt2_dir -o out/gpt2
      [--pad-vocab-to 50304]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True, help="HF model dir (local)")
    ap.add_argument("-o", "--out", required=True)
    ap.add_argument("--pad-vocab-to", type=int, default=None)
    args = ap.parse_args(argv)

    from transformers import GPT2LMHeadModel

    from paddlefleetx_tpu.models.gpt.convert import (
        convert_hf_gpt2_state_dict,
        hf_gpt2_config,
    )

    m = GPT2LMHeadModel.from_pretrained(args.model)
    cfg = hf_gpt2_config(
        m.config,
        **({"vocab_size": args.pad_vocab_to} if args.pad_vocab_to else {}),
    )
    params = convert_hf_gpt2_state_dict(
        m.state_dict(), cfg, pad_vocab_to=args.pad_vocab_to
    )

    from paddlefleetx_tpu.utils.checkpoint import save_params_checkpoint

    out = save_params_checkpoint(
        args.out,
        params,
        f"hf-gpt2:{args.model}",
        {
            "module": "GPTModule",
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_attention_heads,
            "max_position_embeddings": cfg.max_position_embeddings,
        },
    )
    print(f"converted -> {out}")


if __name__ == "__main__":
    main()
