"""Offline run-report renderer: one self-contained HTML (or markdown)
page from a training run's artifacts — no server, no deps beyond stdlib,
no jax import, so it runs on CI artifacts and laptops alike.

Inputs (any subset; missing ones get a loud note in the report):

  - the engine's metrics JSONL (``Engine.metrics_file``) — step records
    + structured events (rollback / preempt_save / data_skip /
    eval_empty);
  - a flight-recorder dump (``<output_dir>/flight_recorder.jsonl`` or
    ``<PFX_FLIGHT_DIR>/flight_recorder.jsonl``) — for a CRASHED run this
    is usually the only artifact, and its ring carries the step records
    the metrics stream never flushed, plus compile events (retrace
    attribution) and the dump reason;
  - a Chrome-trace export (``<PFX_FLIGHT_DIR>/trace.json``).

Rendered: loss / lr / MFU / data-wait curves (rollback, preempt and
compile markers overlaid), the per-layer-group norm heatmap from the
observatory's ``model_stats`` records, a memory-watermark timeline, and
an annotated event table.  Usage::

    python tools/report.py --metrics m.jsonl --flight out/flight_recorder.jsonl \
        --trace artifacts/trace.json -o report.html
    python tools/report.py --run-dir out/ --format md -o report.md

``--run-dir`` scans for the conventional file names.  Exit is nonzero
only when NO input artifact could be read.

Fleet mode (``--fleet [fleet_metrics.jsonl]``, docs/observability.md
"Fleet metrics federation"): renders the FLEET view from the router's
own append-only artifact (`core/router.FleetLog` — per-replica samples
every poll cadence + controller scale events), with the same
crash-tolerance contract: per-replica TTFT/latency/occupancy/depth
curves with scale events as markers, the handoff byte/time breakdown by
transport, and a last-known per-replica state table.  With no path the
conventional locations are scanned (``--run-dir``, then
``$PFX_FLIGHT_DIR``/``./artifacts``)::

    python tools/report.py --fleet artifacts/fleet_metrics.jsonl -o fleet.html
"""

import argparse
import html
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

STEP_EVENT_KINDS = ("rollback", "preempt_save", "data_skip", "eval_empty")


# ---------------------------------------------------------------------------
# artifact loading
# ---------------------------------------------------------------------------


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                rows.append({"event": "unparseable", "raw": line[:200]})
    return rows


class RunData:
    """Everything the renderer needs, merged from whichever artifacts
    exist.  Step records from the metrics stream win over flight-ring
    copies of the same step (the stream is the durable writer); a
    crashed run with no metrics file still gets records from the ring."""

    def __init__(self) -> None:
        self.sources: List[str] = []
        self.notes: List[str] = []
        self.records: Dict[int, Dict[str, Any]] = {}
        self.events: List[Dict[str, Any]] = []
        self.compiles: List[Dict[str, Any]] = []
        self.flight_header: Optional[Dict[str, Any]] = None
        self.trace_summary: Optional[Dict[str, Any]] = None
        self.profile: Optional[Dict[str, Any]] = None

    def add_profile(self, path: str) -> None:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"profile summary {path} is not an object")
        self.profile = doc
        self.sources.append(f"profile: {path}")

    def _ingest_row(self, row: Dict[str, Any], prefer: bool) -> None:
        kind = row.get("event", "step" if "loss" in row else None)
        if kind == "step" and isinstance(row.get("step"), (int, float)):
            step = int(row["step"])
            if prefer or step not in self.records:
                self.records[step] = row
            elif "ts" in row:
                # a metrics-stream record won, but only the flight-ring
                # copy carries a wall-clock ts — backfill it so compile
                # events (ts-only) can be mapped onto the step axis
                self.records[step].setdefault("ts", row["ts"])
        elif kind == "compile":
            self.compiles.append(row)
        elif kind == "flight_recorder_dump":
            self.flight_header = row
        elif kind in STEP_EVENT_KINDS:
            self.events.append(row)
        elif kind in ("crash", "span", "unparseable"):
            self.events.append(row)

    def add_metrics(self, path: str) -> None:
        for row in load_jsonl(path):
            self._ingest_row(row, prefer=True)
        self.sources.append(f"metrics: {path}")

    def add_flight(self, path: str) -> None:
        seen = {
            (e.get("event"), e.get("step"), e.get("reason"))
            for e in self.events
        }
        for row in load_jsonl(path):
            kind = row.get("event", "step" if "loss" in row else None)
            if kind in STEP_EVENT_KINDS:
                key = (kind, row.get("step"), row.get("reason"))
                if key in seen:
                    continue  # already ingested from the metrics stream
            self._ingest_row(row, prefer=False)
        self.sources.append(f"flight: {path}")

    def add_trace(self, path: str) -> None:
        with open(path) as f:
            doc = json.load(f)
        # both Chrome-trace containers are valid: the object form
        # ({"traceEvents": [...]}) our exporter writes, and the bare
        # JSON-array form many Perfetto tools emit
        evs = doc if isinstance(doc, list) else doc.get("traceEvents", [])
        evs = [e for e in evs if isinstance(e, dict)]
        dur = sum(e.get("dur", 0) for e in evs if e.get("ph") == "X")
        self.trace_summary = {
            "path": path,
            "events": len(evs),
            "lanes": len({(e.get("pid"), e.get("tid")) for e in evs}),
            "span_seconds": round(dur / 1e6, 3),
        }
        self.sources.append(f"trace: {path}")

    # -- derived views --------------------------------------------------
    def steps(self) -> List[int]:
        return sorted(self.records)

    def series(self, key: str, sub: Optional[str] = None) -> List[Tuple[int, float]]:
        out = []
        for s in self.steps():
            rec = self.records[s]
            v = rec.get(key)
            if sub is not None and isinstance(v, dict):
                v = v.get(sub)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out.append((s, float(v)))
        return out

    def model_stats_rows(self) -> List[Dict[str, Any]]:
        return [
            self.records[s]["model_stats"] for s in self.steps()
            if isinstance(self.records[s].get("model_stats"), dict)
        ]

    def status(self) -> str:
        preempts = [e for e in self.events if e.get("event") == "preempt_save"]
        crashes = [e for e in self.events if e.get("event") == "crash"]
        if crashes:
            return f"CRASHED: {crashes[-1].get('error', '?')}"
        if preempts:
            return f"preempted at step {preempts[-1].get('step', '?')} ({preempts[-1].get('cause', '?')})"
        if self.flight_header and self.flight_header.get("reason"):
            return f"flight dump: {self.flight_header['reason']}"
        return "completed (no crash/preempt markers)"


def find_artifacts(args) -> RunData:
    data = RunData()
    metrics, flight, trace = args.metrics, args.flight, args.trace
    if args.run_dir:
        d = args.run_dir
        metrics = metrics or _first_existing(
            os.path.join(d, "metrics.jsonl"), os.path.join(d, "m.jsonl")
        )
        flight = flight or _first_existing(
            os.path.join(d, "flight_recorder.jsonl"),
            os.path.join(d, "artifacts", "flight_recorder.jsonl"),
        )
        trace = trace or _first_existing(
            os.path.join(d, "trace.json"),
            os.path.join(d, "artifacts", "trace.json"),
        )
    for path, add, label in (
        (metrics, data.add_metrics, "metrics JSONL"),
        (flight, data.add_flight, "flight-recorder dump"),
        (trace, data.add_trace, "trace export"),
    ):
        if not path:
            data.notes.append(f"no {label} given — section skipped")
            continue
        try:
            add(path)
        except (OSError, ValueError, TypeError, AttributeError, KeyError) as e:
            # the contract: an unreadable/foreign artifact is a loud
            # note and the rest of the report still renders — never a
            # traceback on a crashed run's half-written files
            data.notes.append(f"could not read {label} {path}: {e!r}")
    return data


def _first_existing(*paths: str) -> Optional[str]:
    for p in paths:
        if os.path.exists(p):
            return p
    return None


def find_profile_summary(args) -> Optional[str]:
    """Resolve an on-demand profile capture's ``profile_summary.json``
    (tools/serve.py POST /admin/profile): an explicit ``--profile PATH``
    wins, then the NEWEST capture under the conventional
    ``<dir>/profiles/<ts>/`` layout in --run-dir / $PFX_FLIGHT_DIR /
    ./artifacts."""
    import glob

    prof = getattr(args, "profile", None)
    if prof and prof != "auto":
        return prof
    roots = []
    if getattr(args, "run_dir", None):
        roots += [args.run_dir, os.path.join(args.run_dir, "artifacts")]
    roots.append(os.environ.get("PFX_FLIGHT_DIR") or "artifacts")
    for root in roots:
        hits = sorted(glob.glob(
            os.path.join(root, "profiles", "*", "profile_summary.json")
        ))
        if hits:
            return hits[-1]
    return None


# ---------------------------------------------------------------------------
# fleet artifact (core/router.FleetLog JSONL)
# ---------------------------------------------------------------------------


class FleetData:
    """The router's fleet_metrics.jsonl, parsed: per-replica sample rows
    (time-ordered), router self-samples, and controller scale events —
    whatever subset a crashed router managed to append (torn tail lines
    land as ``unparseable`` and are skipped loudly in the notes)."""

    def __init__(self) -> None:
        self.sources: List[str] = []
        self.notes: List[str] = []
        self.samples: Dict[str, List[Dict[str, Any]]] = {}  # replica -> rows
        self.router_rows: List[Dict[str, Any]] = []
        self.scale_events: List[Dict[str, Any]] = []
        self.t0: Optional[float] = None
        self.profile: Optional[Dict[str, Any]] = None

    def add_profile(self, path: str) -> None:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"profile summary {path} is not an object")
        self.profile = doc
        self.sources.append(f"profile: {path}")

    def add(self, path: str) -> None:
        bad = 0
        for row in load_jsonl(path):
            kind = row.get("event")
            ts = row.get("ts")
            if kind == "unparseable" or not isinstance(ts, (int, float)):
                bad += 1
                continue
            if self.t0 is None or ts < self.t0:
                self.t0 = ts
            if kind == "replica_sample" and row.get("replica"):
                self.samples.setdefault(str(row["replica"]), []).append(row)
            elif kind == "router_sample":
                self.router_rows.append(row)
            elif kind == "scale":
                self.scale_events.append(row)
        for rows in self.samples.values():
            rows.sort(key=lambda r: r["ts"])
        self.router_rows.sort(key=lambda r: r["ts"])
        if bad:
            self.notes.append(
                f"{bad} unparseable/partial line(s) skipped in {path} "
                "(a crashed run's torn tail is expected)"
            )
        self.sources.append(f"fleet: {path}")

    def rel(self, ts: float) -> float:
        return round(ts - (self.t0 or 0.0), 1)

    def series(self, replica: str, key: str) -> List[Tuple[float, float]]:
        out = []
        for r in self.samples.get(replica, []):
            v = r.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out.append((self.rel(r["ts"]), float(v)))
        return out

    def last(self, replica: str) -> Dict[str, Any]:
        rows = self.samples.get(replica, [])
        return rows[-1] if rows else {}

    def replicas(self) -> List[str]:
        return sorted(self.samples)

    def markers(self) -> List[Tuple[float, str, str]]:
        """Scale events as ``(x, color, label)`` chart markers (x =
        relative seconds; a LIST — two pools scaling in the same tick
        must both render, a time-keyed dict would keep only one)."""
        out: List[Tuple[float, str, str]] = []
        for e in self.scale_events:
            color = "#dc2626" if e.get("action") == "scale_down" else "#059669"
            out.append((
                self.rel(e["ts"]), color,
                f"{e.get('pool', 'fleet')} {e.get('action', '?')}: "
                f"{e.get('reason', '')}",
            ))
        return out


# ---------------------------------------------------------------------------
# SVG primitives (hand-rolled: self-contained, no chart deps)
# ---------------------------------------------------------------------------

W, H, PAD = 640, 180, 36


def _scale(vals: Sequence[float], lo_px: float, hi_px: float):
    lo, hi = min(vals), max(vals)
    if hi == lo:
        hi = lo + 1.0
    span = hi - lo

    def f(v: float) -> float:
        return lo_px + (v - lo) / span * (hi_px - lo_px)

    return f, lo, hi


def _fmt(v: float) -> str:
    return f"{v:.4g}"


def svg_line(
    title: str,
    series: Sequence[Tuple[int, float]],
    color: str = "#2563eb",
    markers: Optional[Dict[int, Tuple[str, str]]] = None,
) -> str:
    """One line chart; ``markers`` maps step -> (color, label) vertical
    annotation lines (rollback / preempt / compile)."""
    if not series:
        return (
            f'<div class="chart"><h3>{html.escape(title)}</h3>'
            "<p class='note'>no data</p></div>"
        )
    xs = [s for s, _ in series]
    ys = [v for _, v in series]
    fx, xlo, xhi = _scale(xs, PAD, W - 8)
    fy, ylo, yhi = _scale(ys, H - 20, 12)  # y grows downward in SVG
    pts = " ".join(f"{fx(x):.1f},{fy(y):.1f}" for x, y in series)
    parts = [
        f'<svg viewBox="0 0 {W} {H}" role="img" aria-label="{html.escape(title)}">',
        f'<rect x="0" y="0" width="{W}" height="{H}" fill="#fafafa"/>',
        f'<line x1="{PAD}" y1="{H - 20}" x2="{W - 8}" y2="{H - 20}" stroke="#999"/>',
        f'<line x1="{PAD}" y1="12" x2="{PAD}" y2="{H - 20}" stroke="#999"/>',
    ]
    for step, (mcolor, label) in sorted((markers or {}).items()):
        if xlo <= step <= xhi:
            x = fx(step)
            parts.append(
                f'<line x1="{x:.1f}" y1="12" x2="{x:.1f}" y2="{H - 20}" '
                f'stroke="{mcolor}" stroke-dasharray="3,2">'
                f"<title>{html.escape(label)} @ step {step}</title></line>"
            )
    parts.append(
        f'<polyline fill="none" stroke="{color}" stroke-width="1.5" points="{pts}"/>'
    )
    parts += [
        f'<text x="{PAD}" y="{H - 6}" class="ax">{_fmt(xlo)}</text>',
        f'<text x="{W - 8}" y="{H - 6}" text-anchor="end" class="ax">{_fmt(xhi)}</text>',
        f'<text x="{PAD - 4}" y="{H - 20}" text-anchor="end" class="ax">{_fmt(ylo)}</text>',
        f'<text x="{PAD - 4}" y="16" text-anchor="end" class="ax">{_fmt(yhi)}</text>',
        "</svg>",
    ]
    return (
        f'<div class="chart"><h3>{html.escape(title)}</h3>' + "".join(parts) + "</div>"
    )


_SERIES_PALETTE = (
    "#2563eb", "#d97706", "#059669", "#dc2626", "#7c3aed",
    "#0891b2", "#be123c", "#4d7c0f",
)


def svg_multi_line(
    title: str,
    series_by_label: Dict[str, Sequence[Tuple[float, float]]],
    markers: Optional[Sequence[Tuple[float, str, str]]] = None,
) -> str:
    """One chart, one polyline per labeled series (per-replica fleet
    curves), shared axes, inline legend; ``markers`` is a list of
    ``(x, color, label)`` vertical annotation lines (a list, not a
    dict keyed by x — coincident events must all render)."""
    series_by_label = {k: list(v) for k, v in series_by_label.items() if v}
    if not series_by_label:
        return (
            f'<div class="chart"><h3>{html.escape(title)}</h3>'
            "<p class='note'>no data</p></div>"
        )
    xs = [x for s in series_by_label.values() for x, _ in s]
    ys = [y for s in series_by_label.values() for _, y in s]
    fx, xlo, xhi = _scale(xs, PAD, W - 8)
    fy, ylo, yhi = _scale(ys, H - 20, 12)
    parts = [
        f'<svg viewBox="0 0 {W} {H}" role="img" aria-label="{html.escape(title)}">',
        f'<rect x="0" y="0" width="{W}" height="{H}" fill="#fafafa"/>',
        f'<line x1="{PAD}" y1="{H - 20}" x2="{W - 8}" y2="{H - 20}" stroke="#999"/>',
        f'<line x1="{PAD}" y1="12" x2="{PAD}" y2="{H - 20}" stroke="#999"/>',
    ]
    for x, mcolor, label in sorted(markers or []):
        if xlo <= x <= xhi:
            parts.append(
                f'<line x1="{fx(x):.1f}" y1="12" x2="{fx(x):.1f}" '
                f'y2="{H - 20}" stroke="{mcolor}" stroke-dasharray="3,2">'
                f"<title>{html.escape(label)} @ {x:g}s</title></line>"
            )
    legend = []
    for i, (label, series) in enumerate(sorted(series_by_label.items())):
        color = _SERIES_PALETTE[i % len(_SERIES_PALETTE)]
        pts = " ".join(f"{fx(x):.1f},{fy(y):.1f}" for x, y in series)
        parts.append(
            f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
            f'points="{pts}"><title>{html.escape(label)}</title></polyline>'
        )
        lx = PAD + 6 + 90 * i
        legend.append(
            f'<rect x="{lx}" y="2" width="8" height="8" fill="{color}"/>'
            f'<text x="{lx + 11}" y="10" class="ax">{html.escape(label)}</text>'
        )
    parts += legend
    parts += [
        f'<text x="{PAD}" y="{H - 6}" class="ax">{_fmt(xlo)}s</text>',
        f'<text x="{W - 8}" y="{H - 6}" text-anchor="end" class="ax">{_fmt(xhi)}s</text>',
        f'<text x="{PAD - 4}" y="{H - 20}" text-anchor="end" class="ax">{_fmt(ylo)}</text>',
        f'<text x="{PAD - 4}" y="16" text-anchor="end" class="ax">{_fmt(yhi)}</text>',
        "</svg>",
    ]
    return (
        f'<div class="chart"><h3>{html.escape(title)}</h3>' + "".join(parts) + "</div>"
    )


def _heat_color(t: float) -> str:
    """0..1 -> light blue .. deep red ramp."""
    t = min(1.0, max(0.0, t))
    r = int(40 + 215 * t)
    g = int(90 + 60 * (1 - t) - 60 * t)
    b = int(220 * (1 - t) + 40 * t)
    return f"rgb({r},{max(0, g)},{b})"


def svg_heatmap(title: str, groups: List[str], steps: List[int],
                matrix: List[List[Optional[float]]], log_scale: bool = True) -> str:
    """groups x steps heatmap (matrix[g][s]); log10 color scale by
    default (norms span decades), non-finite cells black."""
    if not groups or not steps:
        return (
            f'<div class="chart"><h3>{html.escape(title)}</h3>'
            "<p class='note'>no model_stats records</p></div>"
        )
    label_w = 8 + max(len(g) for g in groups) * 7
    cw = max(4, min(28, (W - label_w - 8) // max(1, len(steps))))
    ch = 16
    width = label_w + cw * len(steps) + 8
    height = 24 + ch * len(groups) + 18
    flat = [
        v for row in matrix for v in row
        if v is not None and math.isfinite(v) and (not log_scale or v > 0)
    ]
    if log_scale:
        flat = [math.log10(v) for v in flat]
    lo, hi = (min(flat), max(flat)) if flat else (0.0, 1.0)
    if hi == lo:
        hi = lo + 1.0
    parts = [
        f'<svg viewBox="0 0 {width} {height}" role="img" aria-label="{html.escape(title)}">'
    ]
    for gi, g in enumerate(groups):
        y = 20 + gi * ch
        parts.append(
            f'<text x="{label_w - 6}" y="{y + ch - 4}" text-anchor="end" '
            f'class="ax">{html.escape(g)}</text>'
        )
        for si, step in enumerate(steps):
            v = matrix[gi][si]
            if v is None or not math.isfinite(v) or (log_scale and v <= 0):
                fill = "#111"
                tip = f"{g} @ step {step}: non-finite/none"
            else:
                t = ((math.log10(v) if log_scale else v) - lo) / (hi - lo)
                fill = _heat_color(t)
                tip = f"{g} @ step {step}: {_fmt(v)}"
            parts.append(
                f'<rect x="{label_w + si * cw}" y="{y}" width="{cw - 1}" '
                f'height="{ch - 1}" fill="{fill}"><title>{html.escape(tip)}</title></rect>'
            )
    parts.append(
        f'<text x="{label_w}" y="{height - 4}" class="ax">steps '
        f"{steps[0]}..{steps[-1]}; color = log10 scale {_fmt(lo)}..{_fmt(hi)}</text>"
    )
    parts.append("</svg>")
    return (
        f'<div class="chart"><h3>{html.escape(title)}</h3>' + "".join(parts) + "</div>"
    )


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------


def summarize(data: RunData) -> List[Tuple[str, Any]]:
    steps = data.steps()
    loss = data.series("loss")
    mfu = data.series("mfu")
    dw = data.series("data_wait_s")
    rollbacks = [e for e in data.events if e.get("event") == "rollback"]
    preempts = [e for e in data.events if e.get("event") == "preempt_save"]
    skips = [e for e in data.events if e.get("event") == "data_skip"]
    nonfinite = [
        s for s in steps
        if data.records[s].get("found_inf")
        or (isinstance(data.records[s].get("loss"), float)
            and math.isnan(data.records[s]["loss"]))
    ]
    mem_peak = max(
        (r.get("mem", {}).get("fit_peak_bytes", 0) for r in data.records.values()),
        default=0,
    )
    rows: List[Tuple[str, Any]] = [
        ("status", data.status()),
        ("steps logged", f"{steps[0]}..{steps[-1]} ({len(steps)} records)"
         if steps else "none"),
        ("final loss", _fmt(loss[-1][1]) if loss else "n/a"),
        ("best loss", _fmt(min(v for _, v in loss)) if loss else "n/a"),
        ("mean MFU", _fmt(sum(v for _, v in mfu) / len(mfu)) if mfu else "n/a"),
        ("total data wait", f"{dw[-1][1]:.2f}s" if dw else "n/a"),
        ("non-finite steps", f"{len(nonfinite)} ({nonfinite[:8]})"
         if nonfinite else "0"),
        ("rollbacks", len(rollbacks)),
        ("preempt saves", len(preempts)),
        ("data skips", len(skips)),
        ("compiles observed",
         f"{len(data.compiles)} ({sum(c.get('elapsed_s', 0) for c in data.compiles):.1f}s total)"
         if data.compiles else "0"),
        ("peak memory watermark", _bytes(mem_peak) if mem_peak else "n/a"),
    ]
    if data.trace_summary:
        ts = data.trace_summary
        rows.append((
            "trace export",
            f"{ts['events']} events / {ts['lanes']} lanes / "
            f"{ts['span_seconds']}s total span",
        ))
    return rows


def _bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} TiB"


def annotation_markers(data: RunData) -> Dict[int, Tuple[str, str]]:
    markers: Dict[int, Tuple[str, str]] = {}
    for e in data.events:
        step = e.get("step")
        if not isinstance(step, (int, float)):
            continue
        kind = e.get("event")
        if kind == "rollback":
            markers[int(step)] = ("#dc2626", f"rollback ({e.get('reason', '')})")
        elif kind == "preempt_save":
            markers[int(step)] = ("#d97706", f"preempt ({e.get('cause', '')})")
        elif kind == "eval_empty":
            markers.setdefault(int(step), ("#7c3aed", "eval_empty"))
    # compile events: flight rows carry wall-clock ts; map each onto the
    # nearest step record that has a ts (flight step copies do)
    step_ts = [
        (data.records[s]["ts"], s) for s in data.steps()
        if isinstance(data.records[s].get("ts"), (int, float))
    ]
    if step_ts:
        step_ts.sort()
        for c in data.compiles:
            ts = c.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            nearest = min(step_ts, key=lambda p: abs(p[0] - ts))[1]
            markers.setdefault(
                nearest,
                ("#059669",
                 f"compile {c.get('fn', '?')} {c.get('elapsed_s', 0)}s"),
            )
    return markers


def event_rows(data: RunData) -> List[List[str]]:
    rows = []
    for e in data.events:
        kind = e.get("event", "?")
        detail = {
            k: v for k, v in e.items()
            if k not in ("event", "seq", "ts") and v is not None
        }
        rows.append([str(kind), str(e.get("step", "")),
                     json.dumps(detail, default=str)[:240]])
    for c in data.compiles:
        rows.append([
            "compile", "",
            f"{c.get('fn', '?')}: {c.get('elapsed_s', '?')}s, "
            f"{c.get('diff', '')}"
            + (" [persistent-cache hit]" if c.get("cache_hit") else ""),
        ])
    return rows


def heatmap_inputs(data: RunData, key: str):
    ms_rows = data.model_stats_rows()
    if not ms_rows:
        return [], [], []
    groups = ms_rows[0].get("groups", [])
    steps = [int(r.get("step", i)) for i, r in enumerate(ms_rows)]
    matrix: List[List[Optional[float]]] = []
    for gi in range(len(groups)):
        row = []
        for r in ms_rows:
            vals = r.get(key) or []
            v = vals[gi] if gi < len(vals) else None
            row.append(float(v) if isinstance(v, (int, float)) else None)
        matrix.append(row)
    return groups, steps, matrix


CSS = """
body { font: 14px/1.45 system-ui, sans-serif; margin: 24px auto; max-width: 900px; color: #1f2937; }
h1 { font-size: 20px; } h2 { font-size: 16px; margin-top: 28px; border-bottom: 1px solid #e5e7eb; }
h3 { font-size: 13px; margin: 8px 0 2px; color: #374151; }
table { border-collapse: collapse; width: 100%; font-size: 13px; }
td, th { border: 1px solid #e5e7eb; padding: 3px 8px; text-align: left; vertical-align: top; }
th { background: #f3f4f6; }
.note { color: #92400e; background: #fef3c7; padding: 2px 8px; display: inline-block; }
.ax { font-size: 9px; fill: #6b7280; }
svg { width: 100%; height: auto; }
.chart { margin-bottom: 10px; }
code { background: #f3f4f6; padding: 0 3px; }
"""


def render_html(data: RunData, title: str) -> str:
    markers = annotation_markers(data)
    out = [
        "<!doctype html>",
        '<html lang="en"><head><meta charset="utf-8">',
        f"<title>{html.escape(title)}</title>",
        f"<style>{CSS}</style></head><body>",
        f"<h1>{html.escape(title)}</h1>",
        "<p>" + " · ".join(html.escape(s) for s in data.sources) + "</p>",
    ]
    for n in data.notes:
        out.append(f'<p class="note">{html.escape(n)}</p>')

    out.append("<h2>Summary</h2><table>")
    for k, v in summarize(data):
        out.append(
            f"<tr><th>{html.escape(str(k))}</th><td>{html.escape(str(v))}</td></tr>"
        )
    out.append("</table>")

    gp = train_goodput_rows(data)
    if gp:
        out.append("<h2>Goodput ledger</h2>")
        out.append(_html_table(_GOODPUT_TRAIN_COLS, gp))
    if data.profile:
        out.append("<h2>On-demand profile</h2>")
        out.append(f"<p>{html.escape(profile_caption(data.profile))}</p>")
        out.append(_html_table(_PROFILE_COLS, profile_rows(data.profile)))

    out.append("<h2>Curves</h2>")
    out.append(svg_line("loss", data.series("loss"), "#2563eb", markers))
    out.append(svg_line("learning rate", data.series("lr"), "#7c3aed", markers))
    out.append(svg_line("MFU", data.series("mfu"), "#059669", markers))
    out.append(svg_line(
        "data wait (cumulative s)", data.series("data_wait_s"), "#d97706", markers
    ))
    out.append(svg_line(
        "tokens/s", data.series("tokens_per_sec"), "#0891b2", markers
    ))

    out.append("<h2>Per-layer-group statistics</h2>")
    for key, label in (
        ("grad_norm", "grad norm by layer group"),
        ("update_ratio", "update/param ratio by layer group"),
    ):
        groups, steps, matrix = heatmap_inputs(data, key)
        out.append(svg_heatmap(label, groups, steps, matrix))

    out.append("<h2>Memory watermarks</h2>")
    out.append(svg_line(
        "host RSS (bytes)", data.series("mem", "host_rss_bytes"), "#be123c", markers
    ))
    dev = data.series("mem", "device_peak_bytes")
    if dev:
        out.append(svg_line("device peak (bytes)", dev, "#9d174d", markers))

    out.append("<h2>Events &amp; compiles</h2>")
    rows = event_rows(data)
    if rows:
        out.append("<table><tr><th>event</th><th>step</th><th>detail</th></tr>")
        for r in rows:
            out.append(
                "<tr>" + "".join(f"<td>{html.escape(c)}</td>" for c in r) + "</tr>"
            )
        out.append("</table>")
    else:
        out.append("<p>no events recorded</p>")
    out.append("</body></html>")
    return "\n".join(out) + "\n"


def fleet_summary(data: FleetData) -> List[Tuple[str, Any]]:
    reps = data.replicas()
    span = 0.0
    all_ts = [r["ts"] for rows in data.samples.values() for r in rows]
    all_ts += [r["ts"] for r in data.router_rows]
    if all_ts:
        span = max(all_ts) - min(all_ts)
    pools = sorted({data.last(r).get("pool", "?") for r in reps})
    proxied = max(
        (r.get("handoff_bytes_proxied", 0) or 0 for r in data.router_rows),
        default=0,
    )
    direct = sum(
        data.last(r).get("handoff_bytes_direct", 0) or 0 for r in reps
    )
    ups = sum(1 for e in data.scale_events if e.get("action") == "scale_up")
    downs = sum(
        1 for e in data.scale_events if e.get("action") == "scale_down"
    )
    mig_sent = sum(
        int(data.last(r).get("migrate_sent_total", 0) or 0) for r in reps
    )
    mig_adopted = sum(
        int(data.last(r).get("migrate_adopted_total", 0) or 0) for r in reps
    )
    mig_failed = sum(
        int(data.last(r).get("migrate_failed_total", 0) or 0) for r in reps
    )
    return [
        ("replicas seen", f"{len(reps)} ({', '.join(reps)})" if reps else "0"),
        ("pools", ", ".join(pools) if pools else "n/a"),
        ("window", f"{span:.1f}s of samples"),
        ("scale events", f"{ups} up / {downs} down"),
        ("handoff bytes", f"{_bytes(direct)} direct / "
                          f"{_bytes(proxied)} proxied via router"),
        ("prefix migrations", f"{mig_sent} sent / {mig_adopted} blocks "
                              f"adopted / {mig_failed} failed"),
        ("router samples", len(data.router_rows)),
    ]


def tenant_rows(data: FleetData) -> List[List[str]]:
    """Per-tenant front-door rows off the LAST router sample.  The
    router folds tenant labels through its top-k cardinality cap before
    logging (docs/serving.md "Multi-tenant isolation"), so this table is
    bounded no matter how many tenant names traffic invents; a None
    quota knob renders as unlimited."""
    if not data.router_rows:
        return []
    tenants = data.router_rows[-1].get("tenants") or {}
    rows = []
    for name in sorted(tenants):
        t = tenants[name] or {}

        def knob(k):
            v = t.get(k)
            return "unlimited" if v is None else str(v)

        rows.append([
            str(name), str(t.get("weight", "")), knob("rps"),
            knob("max_inflight"), str(int(t.get("in_flight", 0) or 0)),
        ])
    return rows


_TENANT_COLS = ("tenant", "weight", "rps", "max in-flight", "in flight")


# ---------------------------------------------------------------------------
# goodput ledger + on-demand profile views (docs/observability.md
# "Goodput ledger" / "On-demand profiling")
# ---------------------------------------------------------------------------


def _html_table(cols, rows) -> str:
    out = ["<table><tr>" + "".join(
        f"<th>{html.escape(str(c))}</th>" for c in cols) + "</tr>"]
    for r in rows:
        out.append("<tr>" + "".join(
            f"<td>{html.escape(str(c))}</td>" for c in r) + "</tr>")
    out.append("</table>")
    return "\n".join(out)


def _md_table(cols, rows) -> List[str]:
    lines = ["| " + " | ".join(str(c) for c in cols) + " |",
             "|" + "---|" * len(cols)]
    for r in rows:
        lines.append("| " + " | ".join(
            str(c).replace("|", "\\|") for c in r) + " |")
    return lines


def train_goodput_rows(data: RunData) -> List[List[str]]:
    """Stacked time-ledger breakdown off the LAST step record carrying
    one (core/engine.py ``time_ledger``: the fit's cumulative wall
    seconds per bucket, exhaustive by construction)."""
    for s in reversed(data.steps()):
        led = data.records[s].get("time_ledger")
        if isinstance(led, dict) and led:
            total = sum(float(v) for v in led.values()) or 1.0
            return [
                [k, f"{float(v):.3f}", f"{100.0 * float(v) / total:.1f}%"]
                for k, v in sorted(
                    led.items(), key=lambda kv: -float(kv[1])
                )
            ]
    return []


_GOODPUT_TRAIN_COLS = ("bucket", "seconds", "share")


def fleet_goodput_rows(data: FleetData) -> List[List[str]]:
    """Per-replica serving goodput off each replica's LAST fleet-log
    sample (the federated scheduler time ledger): goodput_frac =
    device-COVERED seconds / non-idle wall, where covered = non-idle
    wall minus host_gap_s (host time the device sat starved waiting for
    its next dispatch — same definition bench_decode's overlap case
    pins).  device_util = the same numerator over TOTAL wall including
    idle."""
    rows = []
    for r in data.replicas():
        last = data.last(r)
        wall = float(last.get("sched_wall_s", 0) or 0)
        if wall <= 0:
            continue
        dd = float(last.get("sched_device_decode_s", 0) or 0)
        dp = float(last.get("sched_device_prefill_s", 0) or 0)
        rb = float(last.get("sched_readback_s", 0) or 0)
        idle = float(last.get("sched_idle_s", 0) or 0)
        gap = float(last.get("sched_host_gap_s", 0) or 0)
        busy = max(wall - idle, 1e-9)
        covered = max(busy - gap, 0.0)
        rows.append([
            r, f"{covered / busy:.3f}", f"{covered / wall:.3f}",
            f"{dd:.2f}", f"{dp:.2f}",
            f"{float(last.get('sched_host_sched_s', 0) or 0):.2f}",
            f"{rb:.2f}",
            f"{float(last.get('sched_stream_flush_s', 0) or 0):.2f}",
            f"{gap:.3f}", f"{idle:.2f}", f"{wall:.2f}",
        ])
    return rows


_FLEET_GOODPUT_COLS = (
    "replica", "goodput_frac", "device_util", "decode_s", "prefill_s",
    "host_s", "readback_s", "stream_s", "gap_s", "idle_s", "wall_s",
)


def fleet_token_rows(data: FleetData) -> List[List[str]]:
    """Per-replica token-ledger dispositions off the last sample, with
    the closure remainder made explicit: admitted minus the terminal
    dispositions is exactly the tokens still in live decode slots."""
    rows = []
    for r in data.replicas():
        last = data.last(r)
        adm = last.get("tok_admitted")
        if adm is None:
            continue
        adm = int(adm)
        dlv = int(last.get("tok_delivered", 0) or 0)
        ev = int(last.get("tok_evicted_lost", 0) or 0)
        pr = int(last.get("tok_preempt_refunded", 0) or 0)
        sh = int(last.get("tok_shed_after_admit", 0) or 0)
        rem = adm - (dlv + ev + pr + sh)
        rows.append([
            r, str(adm), str(dlv), str(ev), str(pr), str(sh),
            "closed" if rem == 0 else f"{rem} in flight",
        ])
    return rows


_FLEET_TOKEN_COLS = (
    "replica", "admitted", "delivered", "evicted_lost",
    "preempt_refunded", "shed_after_admit", "books",
)


def profile_rows(profile: Dict[str, Any]) -> List[List[str]]:
    rows = []
    for op in (profile.get("top_ops") or [])[:20]:
        rows.append([
            str(op.get("op", "?"))[:60],
            str(op.get("category", "")),
            str(int(op.get("occurrences", 0) or 0)),
            f"{float(op.get('total_us', 0) or 0):.1f}",
            f"{float(op.get('self_us', 0) or 0):.1f}",
            f"{100.0 * float(op.get('self_frac', 0) or 0):.1f}%",
        ])
    return rows


_PROFILE_COLS = ("op", "category", "#", "total us", "self us", "self %")


def profile_caption(profile: Dict[str, Any]) -> str:
    dev = float(profile.get("device_us", 0) or 0)
    host = float(profile.get("host_us", 0) or 0)
    tot = (dev + host) or 1.0
    who = profile.get("replica_id") or (
        f"{profile.get('captured', '?')}/{profile.get('requested', '?')} "
        "replicas" if "captured" in profile else "?"
    )
    return (
        f"{profile.get('seconds', '?')}s capture on {who}, "
        f"source: {profile.get('source', 'fleet aggregate')}; "
        f"device {dev / 1e6:.3f}s ({100 * dev / tot:.1f}%) / "
        f"host {host / 1e6:.3f}s ({100 * host / tot:.1f}%)"
    )


_FLEET_CURVES = (
    ("ttft_p99_s", "TTFT p99 (s) per replica"),
    ("itl_p99_s", "ITL p99 (s) per replica"),
    ("latency_p99_s", "latency p99 (s) per replica"),
    ("occupancy", "continuous-batch occupancy per replica"),
    ("depth", "reported queue depth per replica"),
    ("kv_blocks_used", "KV arena blocks used per replica"),
    # cache-survival view (docs/serving.md "KV lifecycle"): published
    # prefix blocks per replica across drains/migrations — a survivor
    # adopting a drained peer's prefixes shows as a step UP here while
    # the drained replica's curve ends — plus the spill tier's traffic
    ("prefix_cached_blocks", "prefix-cache survival: published prefix "
                             "blocks per replica"),
    ("prefix_spill_entries", "host-RAM spill tier entries per replica"),
    ("prefix_readmits_total", "spill readmits (cumulative) per replica"),
)

_FLEET_STATE_COLS = (
    "pool", "state", "depth", "occupancy", "ttft_p99_s", "itl_p99_s",
    "latency_p99_s",
    "kv_blocks_used", "kv_blocks_available", "tokens_out_total",
    "handoff_exports_total", "handoff_adopts_total",
    "prefix_cached_blocks", "prefix_spill_entries",
    "prefix_spills_total", "prefix_readmits_total",
    "migrate_sent_total", "migrate_adopted_total", "migrate_failed_total",
)


def render_fleet_html(data: FleetData, title: str) -> str:
    markers = data.markers()
    out = [
        "<!doctype html>",
        '<html lang="en"><head><meta charset="utf-8">',
        f"<title>{html.escape(title)}</title>",
        f"<style>{CSS}</style></head><body>",
        f"<h1>{html.escape(title)}</h1>",
        "<p>" + " · ".join(html.escape(s) for s in data.sources) + "</p>",
    ]
    for n in data.notes:
        out.append(f'<p class="note">{html.escape(n)}</p>')
    out.append("<h2>Summary</h2><table>")
    for k, v in fleet_summary(data):
        out.append(
            f"<tr><th>{html.escape(str(k))}</th><td>{html.escape(str(v))}</td></tr>"
        )
    out.append("</table>")

    out.append("<h2>Per-replica curves</h2>")
    for key, label in _FLEET_CURVES:
        out.append(svg_multi_line(
            label,
            {r: data.series(r, key) for r in data.replicas()},
            markers,
        ))

    out.append("<h2>Handoff breakdown</h2>")
    out.append("<table><tr><th>replica</th><th>pool</th>"
               "<th>direct bytes</th><th>proxy bytes</th>"
               "<th>exports</th><th>adopts</th></tr>")
    for r in data.replicas():
        last = data.last(r)
        out.append(
            "<tr>" + "".join(
                f"<td>{html.escape(str(c))}</td>" for c in (
                    r, last.get("pool", "?"),
                    _bytes(last.get("handoff_bytes_direct", 0) or 0),
                    _bytes(last.get("handoff_bytes_proxy", 0) or 0),
                    int(last.get("handoff_exports_total", 0) or 0),
                    int(last.get("handoff_adopts_total", 0) or 0),
                )
            ) + "</tr>"
        )
    if data.router_rows:
        rr = data.router_rows[-1]
        out.append(
            "<tr>" + "".join(
                f"<td>{html.escape(str(c))}</td>" for c in (
                    "(router)", "front door",
                    "—", _bytes(rr.get("handoff_bytes_proxied", 0) or 0),
                    f"{int(rr.get('handoff_count', 0) or 0)} chains",
                    f"{(rr.get('handoff_seconds_sum', 0) or 0):.2f}s total",
                )
            ) + "</tr>"
        )
    out.append("</table>")

    gp = fleet_goodput_rows(data)
    if gp:
        out.append("<h2>Goodput breakdown</h2>")
        out.append(_html_table(_FLEET_GOODPUT_COLS, gp))
    toks = fleet_token_rows(data)
    if toks:
        out.append("<h2>Token ledger</h2>")
        out.append(_html_table(_FLEET_TOKEN_COLS, toks))
    if data.profile:
        out.append("<h2>On-demand profile</h2>")
        out.append(f"<p>{html.escape(profile_caption(data.profile))}</p>")
        out.append(_html_table(_PROFILE_COLS, profile_rows(data.profile)))

    trs = tenant_rows(data)
    if trs:
        out.append("<h2>Tenants (front door)</h2>")
        out.append("<table><tr>" + "".join(
            f"<th>{c}</th>" for c in _TENANT_COLS) + "</tr>")
        for tr in trs:
            out.append("<tr>" + "".join(
                f"<td>{html.escape(c)}</td>" for c in tr) + "</tr>")
        out.append("</table>")

    out.append("<h2>Last known per-replica state</h2>")
    out.append("<table><tr><th>replica</th>" + "".join(
        f"<th>{c}</th>" for c in _FLEET_STATE_COLS) + "</tr>")
    for r in data.replicas():
        last = data.last(r)
        out.append("<tr><td>" + html.escape(r) + "</td>" + "".join(
            f"<td>{html.escape(str(last.get(c, '')))}</td>"
            for c in _FLEET_STATE_COLS
        ) + "</tr>")
    out.append("</table>")

    if data.scale_events:
        out.append("<h2>Scale events</h2>")
        out.append("<table><tr><th>t (s)</th><th>pool</th><th>action</th>"
                   "<th>target</th><th>reason</th></tr>")
        for e in data.scale_events:
            out.append("<tr>" + "".join(
                f"<td>{html.escape(str(c))}</td>" for c in (
                    f"{data.rel(e['ts']):g}", e.get("pool", "fleet"),
                    e.get("action", "?"), e.get("target", ""),
                    str(e.get("reason", ""))[:160],
                )
            ) + "</tr>")
        out.append("</table>")
    out.append("</body></html>")
    return "\n".join(out) + "\n"


def render_fleet_markdown(data: FleetData, title: str) -> str:
    lines = [f"# {title}", "", "sources: " + "; ".join(data.sources), ""]
    for n in data.notes:
        lines.append(f"> NOTE: {n}")
    lines += ["", "## Summary", "", "| key | value |", "|---|---|"]
    for k, v in fleet_summary(data):
        lines.append(f"| {k} | {v} |")
    gp = fleet_goodput_rows(data)
    if gp:
        lines += ["", "## Goodput breakdown", ""]
        lines += _md_table(_FLEET_GOODPUT_COLS, gp)
    toks = fleet_token_rows(data)
    if toks:
        lines += ["", "## Token ledger", ""]
        lines += _md_table(_FLEET_TOKEN_COLS, toks)
    if data.profile:
        lines += ["", "## On-demand profile", "",
                  profile_caption(data.profile), ""]
        lines += _md_table(_PROFILE_COLS, profile_rows(data.profile))
    trs = tenant_rows(data)
    if trs:
        lines += ["", "## Tenants (front door)", "",
                  "| " + " | ".join(_TENANT_COLS) + " |",
                  "|" + "---|" * len(_TENANT_COLS)]
        for tr in trs:
            lines.append("| " + " | ".join(tr) + " |")
    lines += ["", "## Last known per-replica state", "",
              "| replica | " + " | ".join(_FLEET_STATE_COLS) + " |",
              "|" + "---|" * (len(_FLEET_STATE_COLS) + 1)]
    for r in data.replicas():
        last = data.last(r)
        lines.append("| " + " | ".join(
            [r] + [str(last.get(c, "")) for c in _FLEET_STATE_COLS]
        ) + " |")
    if data.scale_events:
        lines += ["", "## Scale events", "",
                  "| t (s) | pool | action | reason |", "|---|---|---|---|"]
        for e in data.scale_events:
            lines.append(
                f"| {data.rel(e['ts']):g} | {e.get('pool', 'fleet')} | "
                f"{e.get('action', '?')} | "
                f"{str(e.get('reason', ''))[:120]} |"
            )
    return "\n".join(lines) + "\n"


def find_fleet_artifact(args) -> Optional[str]:
    """Resolve the fleet JSONL: an explicit ``--fleet PATH`` wins, then
    the conventional names under ``--run-dir``, ``$PFX_FLIGHT_DIR``, and
    ``./artifacts``."""
    if args.fleet and args.fleet != "auto":
        return args.fleet
    candidates = []
    if args.run_dir:
        candidates += [
            os.path.join(args.run_dir, "fleet_metrics.jsonl"),
            os.path.join(args.run_dir, "artifacts", "fleet_metrics.jsonl"),
        ]
    candidates.append(os.path.join(
        os.environ.get("PFX_FLIGHT_DIR") or "artifacts",
        "fleet_metrics.jsonl",
    ))
    return _first_existing(*candidates)


def render_markdown(data: RunData, title: str) -> str:
    lines = [f"# {title}", "", "sources: " + "; ".join(data.sources), ""]
    for n in data.notes:
        lines.append(f"> NOTE: {n}")
    lines += ["", "## Summary", "", "| key | value |", "|---|---|"]
    for k, v in summarize(data):
        lines.append(f"| {k} | {v} |")
    gp = train_goodput_rows(data)
    if gp:
        lines += ["", "## Goodput ledger", ""]
        lines += _md_table(_GOODPUT_TRAIN_COLS, gp)
    if data.profile:
        lines += ["", "## On-demand profile", "",
                  profile_caption(data.profile), ""]
        lines += _md_table(_PROFILE_COLS, profile_rows(data.profile))
    loss = data.series("loss")
    if loss:
        lines += ["", "## Loss", "", "| step | loss |", "|---|---|"]
        stride = max(1, len(loss) // 40)
        for s, v in loss[::stride]:
            lines.append(f"| {s} | {_fmt(v)} |")
    ms = data.model_stats_rows()
    if ms:
        last = ms[-1]
        lines += ["", f"## Layer groups (step {last.get('step', '?')})", "",
                  "| group | grad_norm | param_norm | update_ratio | nonfinite_frac |",
                  "|---|---|---|---|---|"]
        for i, g in enumerate(last.get("groups", [])):
            cells = [
                _fmt(last[k][i]) if i < len(last.get(k) or []) else ""
                for k in ("grad_norm", "param_norm", "update_ratio",
                          "nonfinite_frac")
            ]
            lines.append("| " + " | ".join([g] + cells) + " |")
    rows = event_rows(data)
    if rows:
        lines += ["", "## Events", "", "| event | step | detail |", "|---|---|---|"]
        for r in rows:
            lines.append("| " + " | ".join(c.replace("|", "\\|") for c in r) + " |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--metrics", help="engine metrics JSONL")
    ap.add_argument("--flight", help="flight_recorder.jsonl dump")
    ap.add_argument("--trace", help="Chrome-trace JSON export")
    ap.add_argument("--run-dir", help="directory to scan for the conventional names")
    ap.add_argument("--profile", nargs="?", const="auto", default=None,
                    help="inline an on-demand profile capture's "
                    "profile_summary.json (optional path; default scans "
                    "--run-dir / $PFX_FLIGHT_DIR profiles/)")
    ap.add_argument("--fleet", nargs="?", const="auto", default=None,
                    help="render the FLEET report from the router's "
                    "fleet_metrics.jsonl instead of a training run "
                    "(optional path; default scans --run-dir / "
                    "$PFX_FLIGHT_DIR / ./artifacts)")
    ap.add_argument("-o", "--out", default="report.html",
                    help="output path ('-' = stdout)")
    ap.add_argument("--format", choices=("html", "md"), default=None,
                    help="default: by --out extension (html unless .md)")
    ap.add_argument("--title", default="PaddleFleetX-TPU run report")
    args = ap.parse_args(argv)

    fmt = args.format or ("md" if args.out.endswith(".md") else "html")
    if args.fleet is not None:
        path = find_fleet_artifact(args)
        data = FleetData()
        if path:
            try:
                data.add(path)
            except OSError as e:
                data.notes.append(f"could not read fleet artifact {path}: {e!r}")
        if not data.sources:
            print("report.py: no readable fleet artifact (give --fleet "
                  "PATH or point --run-dir/$PFX_FLIGHT_DIR at the "
                  "router's artifacts)", file=sys.stderr)
            return 2
        if args.title == "PaddleFleetX-TPU run report":
            args.title = "PaddleFleetX-TPU fleet report"
        ppath = find_profile_summary(args)
        if ppath:
            try:
                data.add_profile(ppath)
            except (OSError, ValueError) as e:
                data.notes.append(
                    f"could not read profile summary {ppath}: {e!r}")
        doc = (render_fleet_markdown if fmt == "md"
               else render_fleet_html)(data, args.title)
        return _emit(doc, args, fmt, what=(
            f"{sum(len(v) for v in data.samples.values())} replica "
            f"samples, {len(data.scale_events)} scale events"
        ))

    data = find_artifacts(args)
    ppath = find_profile_summary(args)
    if ppath:
        try:
            data.add_profile(ppath)
        except (OSError, ValueError) as e:
            data.notes.append(f"could not read profile summary {ppath}: {e!r}")
    if not data.sources:
        print("report.py: no readable artifact (give --metrics/--flight/"
              "--trace or --run-dir)", file=sys.stderr)
        for n in data.notes:
            print(f"  {n}", file=sys.stderr)
        return 2
    doc = (render_markdown if fmt == "md" else render_html)(data, args.title)
    return _emit(doc, args, fmt, what=(
        f"{len(data.records)} step records, {len(data.events)} events, "
        f"{len(data.compiles)} compiles"
    ))


def _emit(doc: str, args, fmt: str, what: str) -> int:
    if args.out == "-":
        sys.stdout.write(doc)
    else:
        with open(args.out, "w") as f:
            f.write(doc)
        kind = "markdown" if fmt == "md" else "self-contained HTML"
        print(f"report.py: wrote {kind} report to {args.out} ({what})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
