"""Offline run-report renderer: one self-contained HTML (or markdown)
page from a training run's artifacts — no server, no deps beyond stdlib,
no jax import, so it runs on CI artifacts and laptops alike.

Inputs (any subset; missing ones get a loud note in the report):

  - the engine's metrics JSONL (``Engine.metrics_file``) — step records
    + structured events (rollback / preempt_save / data_skip /
    eval_empty);
  - a flight-recorder dump (``<output_dir>/flight_recorder.jsonl`` or
    ``<PFX_FLIGHT_DIR>/flight_recorder.jsonl``) — for a CRASHED run this
    is usually the only artifact, and its ring carries the step records
    the metrics stream never flushed, plus compile events (retrace
    attribution) and the dump reason;
  - a Chrome-trace export (``<PFX_FLIGHT_DIR>/trace.json``).

Rendered: loss / lr / MFU / data-wait curves (rollback, preempt and
compile markers overlaid), the per-layer-group norm heatmap from the
observatory's ``model_stats`` records, a memory-watermark timeline, and
an annotated event table.  Usage::

    python tools/report.py --metrics m.jsonl --flight out/flight_recorder.jsonl \
        --trace artifacts/trace.json -o report.html
    python tools/report.py --run-dir out/ --format md -o report.md

``--run-dir`` scans for the conventional file names.  Exit is nonzero
only when NO input artifact could be read.
"""

import argparse
import html
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

STEP_EVENT_KINDS = ("rollback", "preempt_save", "data_skip", "eval_empty")


# ---------------------------------------------------------------------------
# artifact loading
# ---------------------------------------------------------------------------


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                rows.append({"event": "unparseable", "raw": line[:200]})
    return rows


class RunData:
    """Everything the renderer needs, merged from whichever artifacts
    exist.  Step records from the metrics stream win over flight-ring
    copies of the same step (the stream is the durable writer); a
    crashed run with no metrics file still gets records from the ring."""

    def __init__(self) -> None:
        self.sources: List[str] = []
        self.notes: List[str] = []
        self.records: Dict[int, Dict[str, Any]] = {}
        self.events: List[Dict[str, Any]] = []
        self.compiles: List[Dict[str, Any]] = []
        self.flight_header: Optional[Dict[str, Any]] = None
        self.trace_summary: Optional[Dict[str, Any]] = None

    def _ingest_row(self, row: Dict[str, Any], prefer: bool) -> None:
        kind = row.get("event", "step" if "loss" in row else None)
        if kind == "step" and isinstance(row.get("step"), (int, float)):
            step = int(row["step"])
            if prefer or step not in self.records:
                self.records[step] = row
            elif "ts" in row:
                # a metrics-stream record won, but only the flight-ring
                # copy carries a wall-clock ts — backfill it so compile
                # events (ts-only) can be mapped onto the step axis
                self.records[step].setdefault("ts", row["ts"])
        elif kind == "compile":
            self.compiles.append(row)
        elif kind == "flight_recorder_dump":
            self.flight_header = row
        elif kind in STEP_EVENT_KINDS:
            self.events.append(row)
        elif kind in ("crash", "span", "unparseable"):
            self.events.append(row)

    def add_metrics(self, path: str) -> None:
        for row in load_jsonl(path):
            self._ingest_row(row, prefer=True)
        self.sources.append(f"metrics: {path}")

    def add_flight(self, path: str) -> None:
        seen = {
            (e.get("event"), e.get("step"), e.get("reason"))
            for e in self.events
        }
        for row in load_jsonl(path):
            kind = row.get("event", "step" if "loss" in row else None)
            if kind in STEP_EVENT_KINDS:
                key = (kind, row.get("step"), row.get("reason"))
                if key in seen:
                    continue  # already ingested from the metrics stream
            self._ingest_row(row, prefer=False)
        self.sources.append(f"flight: {path}")

    def add_trace(self, path: str) -> None:
        with open(path) as f:
            doc = json.load(f)
        # both Chrome-trace containers are valid: the object form
        # ({"traceEvents": [...]}) our exporter writes, and the bare
        # JSON-array form many Perfetto tools emit
        evs = doc if isinstance(doc, list) else doc.get("traceEvents", [])
        evs = [e for e in evs if isinstance(e, dict)]
        dur = sum(e.get("dur", 0) for e in evs if e.get("ph") == "X")
        self.trace_summary = {
            "path": path,
            "events": len(evs),
            "lanes": len({(e.get("pid"), e.get("tid")) for e in evs}),
            "span_seconds": round(dur / 1e6, 3),
        }
        self.sources.append(f"trace: {path}")

    # -- derived views --------------------------------------------------
    def steps(self) -> List[int]:
        return sorted(self.records)

    def series(self, key: str, sub: Optional[str] = None) -> List[Tuple[int, float]]:
        out = []
        for s in self.steps():
            rec = self.records[s]
            v = rec.get(key)
            if sub is not None and isinstance(v, dict):
                v = v.get(sub)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out.append((s, float(v)))
        return out

    def model_stats_rows(self) -> List[Dict[str, Any]]:
        return [
            self.records[s]["model_stats"] for s in self.steps()
            if isinstance(self.records[s].get("model_stats"), dict)
        ]

    def status(self) -> str:
        preempts = [e for e in self.events if e.get("event") == "preempt_save"]
        crashes = [e for e in self.events if e.get("event") == "crash"]
        if crashes:
            return f"CRASHED: {crashes[-1].get('error', '?')}"
        if preempts:
            return f"preempted at step {preempts[-1].get('step', '?')} ({preempts[-1].get('cause', '?')})"
        if self.flight_header and self.flight_header.get("reason"):
            return f"flight dump: {self.flight_header['reason']}"
        return "completed (no crash/preempt markers)"


def find_artifacts(args) -> RunData:
    data = RunData()
    metrics, flight, trace = args.metrics, args.flight, args.trace
    if args.run_dir:
        d = args.run_dir
        metrics = metrics or _first_existing(
            os.path.join(d, "metrics.jsonl"), os.path.join(d, "m.jsonl")
        )
        flight = flight or _first_existing(
            os.path.join(d, "flight_recorder.jsonl"),
            os.path.join(d, "artifacts", "flight_recorder.jsonl"),
        )
        trace = trace or _first_existing(
            os.path.join(d, "trace.json"),
            os.path.join(d, "artifacts", "trace.json"),
        )
    for path, add, label in (
        (metrics, data.add_metrics, "metrics JSONL"),
        (flight, data.add_flight, "flight-recorder dump"),
        (trace, data.add_trace, "trace export"),
    ):
        if not path:
            data.notes.append(f"no {label} given — section skipped")
            continue
        try:
            add(path)
        except (OSError, ValueError, TypeError, AttributeError, KeyError) as e:
            # the contract: an unreadable/foreign artifact is a loud
            # note and the rest of the report still renders — never a
            # traceback on a crashed run's half-written files
            data.notes.append(f"could not read {label} {path}: {e!r}")
    return data


def _first_existing(*paths: str) -> Optional[str]:
    for p in paths:
        if os.path.exists(p):
            return p
    return None


# ---------------------------------------------------------------------------
# SVG primitives (hand-rolled: self-contained, no chart deps)
# ---------------------------------------------------------------------------

W, H, PAD = 640, 180, 36


def _scale(vals: Sequence[float], lo_px: float, hi_px: float):
    lo, hi = min(vals), max(vals)
    if hi == lo:
        hi = lo + 1.0
    span = hi - lo

    def f(v: float) -> float:
        return lo_px + (v - lo) / span * (hi_px - lo_px)

    return f, lo, hi


def _fmt(v: float) -> str:
    return f"{v:.4g}"


def svg_line(
    title: str,
    series: Sequence[Tuple[int, float]],
    color: str = "#2563eb",
    markers: Optional[Dict[int, Tuple[str, str]]] = None,
) -> str:
    """One line chart; ``markers`` maps step -> (color, label) vertical
    annotation lines (rollback / preempt / compile)."""
    if not series:
        return (
            f'<div class="chart"><h3>{html.escape(title)}</h3>'
            "<p class='note'>no data</p></div>"
        )
    xs = [s for s, _ in series]
    ys = [v for _, v in series]
    fx, xlo, xhi = _scale(xs, PAD, W - 8)
    fy, ylo, yhi = _scale(ys, H - 20, 12)  # y grows downward in SVG
    pts = " ".join(f"{fx(x):.1f},{fy(y):.1f}" for x, y in series)
    parts = [
        f'<svg viewBox="0 0 {W} {H}" role="img" aria-label="{html.escape(title)}">',
        f'<rect x="0" y="0" width="{W}" height="{H}" fill="#fafafa"/>',
        f'<line x1="{PAD}" y1="{H - 20}" x2="{W - 8}" y2="{H - 20}" stroke="#999"/>',
        f'<line x1="{PAD}" y1="12" x2="{PAD}" y2="{H - 20}" stroke="#999"/>',
    ]
    for step, (mcolor, label) in sorted((markers or {}).items()):
        if xlo <= step <= xhi:
            x = fx(step)
            parts.append(
                f'<line x1="{x:.1f}" y1="12" x2="{x:.1f}" y2="{H - 20}" '
                f'stroke="{mcolor}" stroke-dasharray="3,2">'
                f"<title>{html.escape(label)} @ step {step}</title></line>"
            )
    parts.append(
        f'<polyline fill="none" stroke="{color}" stroke-width="1.5" points="{pts}"/>'
    )
    parts += [
        f'<text x="{PAD}" y="{H - 6}" class="ax">{_fmt(xlo)}</text>',
        f'<text x="{W - 8}" y="{H - 6}" text-anchor="end" class="ax">{_fmt(xhi)}</text>',
        f'<text x="{PAD - 4}" y="{H - 20}" text-anchor="end" class="ax">{_fmt(ylo)}</text>',
        f'<text x="{PAD - 4}" y="16" text-anchor="end" class="ax">{_fmt(yhi)}</text>',
        "</svg>",
    ]
    return (
        f'<div class="chart"><h3>{html.escape(title)}</h3>' + "".join(parts) + "</div>"
    )


def _heat_color(t: float) -> str:
    """0..1 -> light blue .. deep red ramp."""
    t = min(1.0, max(0.0, t))
    r = int(40 + 215 * t)
    g = int(90 + 60 * (1 - t) - 60 * t)
    b = int(220 * (1 - t) + 40 * t)
    return f"rgb({r},{max(0, g)},{b})"


def svg_heatmap(title: str, groups: List[str], steps: List[int],
                matrix: List[List[Optional[float]]], log_scale: bool = True) -> str:
    """groups x steps heatmap (matrix[g][s]); log10 color scale by
    default (norms span decades), non-finite cells black."""
    if not groups or not steps:
        return (
            f'<div class="chart"><h3>{html.escape(title)}</h3>'
            "<p class='note'>no model_stats records</p></div>"
        )
    label_w = 8 + max(len(g) for g in groups) * 7
    cw = max(4, min(28, (W - label_w - 8) // max(1, len(steps))))
    ch = 16
    width = label_w + cw * len(steps) + 8
    height = 24 + ch * len(groups) + 18
    flat = [
        v for row in matrix for v in row
        if v is not None and math.isfinite(v) and (not log_scale or v > 0)
    ]
    if log_scale:
        flat = [math.log10(v) for v in flat]
    lo, hi = (min(flat), max(flat)) if flat else (0.0, 1.0)
    if hi == lo:
        hi = lo + 1.0
    parts = [
        f'<svg viewBox="0 0 {width} {height}" role="img" aria-label="{html.escape(title)}">'
    ]
    for gi, g in enumerate(groups):
        y = 20 + gi * ch
        parts.append(
            f'<text x="{label_w - 6}" y="{y + ch - 4}" text-anchor="end" '
            f'class="ax">{html.escape(g)}</text>'
        )
        for si, step in enumerate(steps):
            v = matrix[gi][si]
            if v is None or not math.isfinite(v) or (log_scale and v <= 0):
                fill = "#111"
                tip = f"{g} @ step {step}: non-finite/none"
            else:
                t = ((math.log10(v) if log_scale else v) - lo) / (hi - lo)
                fill = _heat_color(t)
                tip = f"{g} @ step {step}: {_fmt(v)}"
            parts.append(
                f'<rect x="{label_w + si * cw}" y="{y}" width="{cw - 1}" '
                f'height="{ch - 1}" fill="{fill}"><title>{html.escape(tip)}</title></rect>'
            )
    parts.append(
        f'<text x="{label_w}" y="{height - 4}" class="ax">steps '
        f"{steps[0]}..{steps[-1]}; color = log10 scale {_fmt(lo)}..{_fmt(hi)}</text>"
    )
    parts.append("</svg>")
    return (
        f'<div class="chart"><h3>{html.escape(title)}</h3>' + "".join(parts) + "</div>"
    )


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------


def summarize(data: RunData) -> List[Tuple[str, Any]]:
    steps = data.steps()
    loss = data.series("loss")
    mfu = data.series("mfu")
    dw = data.series("data_wait_s")
    rollbacks = [e for e in data.events if e.get("event") == "rollback"]
    preempts = [e for e in data.events if e.get("event") == "preempt_save"]
    skips = [e for e in data.events if e.get("event") == "data_skip"]
    nonfinite = [
        s for s in steps
        if data.records[s].get("found_inf")
        or (isinstance(data.records[s].get("loss"), float)
            and math.isnan(data.records[s]["loss"]))
    ]
    mem_peak = max(
        (r.get("mem", {}).get("fit_peak_bytes", 0) for r in data.records.values()),
        default=0,
    )
    rows: List[Tuple[str, Any]] = [
        ("status", data.status()),
        ("steps logged", f"{steps[0]}..{steps[-1]} ({len(steps)} records)"
         if steps else "none"),
        ("final loss", _fmt(loss[-1][1]) if loss else "n/a"),
        ("best loss", _fmt(min(v for _, v in loss)) if loss else "n/a"),
        ("mean MFU", _fmt(sum(v for _, v in mfu) / len(mfu)) if mfu else "n/a"),
        ("total data wait", f"{dw[-1][1]:.2f}s" if dw else "n/a"),
        ("non-finite steps", f"{len(nonfinite)} ({nonfinite[:8]})"
         if nonfinite else "0"),
        ("rollbacks", len(rollbacks)),
        ("preempt saves", len(preempts)),
        ("data skips", len(skips)),
        ("compiles observed",
         f"{len(data.compiles)} ({sum(c.get('elapsed_s', 0) for c in data.compiles):.1f}s total)"
         if data.compiles else "0"),
        ("peak memory watermark", _bytes(mem_peak) if mem_peak else "n/a"),
    ]
    if data.trace_summary:
        ts = data.trace_summary
        rows.append((
            "trace export",
            f"{ts['events']} events / {ts['lanes']} lanes / "
            f"{ts['span_seconds']}s total span",
        ))
    return rows


def _bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} TiB"


def annotation_markers(data: RunData) -> Dict[int, Tuple[str, str]]:
    markers: Dict[int, Tuple[str, str]] = {}
    for e in data.events:
        step = e.get("step")
        if not isinstance(step, (int, float)):
            continue
        kind = e.get("event")
        if kind == "rollback":
            markers[int(step)] = ("#dc2626", f"rollback ({e.get('reason', '')})")
        elif kind == "preempt_save":
            markers[int(step)] = ("#d97706", f"preempt ({e.get('cause', '')})")
        elif kind == "eval_empty":
            markers.setdefault(int(step), ("#7c3aed", "eval_empty"))
    # compile events: flight rows carry wall-clock ts; map each onto the
    # nearest step record that has a ts (flight step copies do)
    step_ts = [
        (data.records[s]["ts"], s) for s in data.steps()
        if isinstance(data.records[s].get("ts"), (int, float))
    ]
    if step_ts:
        step_ts.sort()
        for c in data.compiles:
            ts = c.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            nearest = min(step_ts, key=lambda p: abs(p[0] - ts))[1]
            markers.setdefault(
                nearest,
                ("#059669",
                 f"compile {c.get('fn', '?')} {c.get('elapsed_s', 0)}s"),
            )
    return markers


def event_rows(data: RunData) -> List[List[str]]:
    rows = []
    for e in data.events:
        kind = e.get("event", "?")
        detail = {
            k: v for k, v in e.items()
            if k not in ("event", "seq", "ts") and v is not None
        }
        rows.append([str(kind), str(e.get("step", "")),
                     json.dumps(detail, default=str)[:240]])
    for c in data.compiles:
        rows.append([
            "compile", "",
            f"{c.get('fn', '?')}: {c.get('elapsed_s', '?')}s, "
            f"{c.get('diff', '')}"
            + (" [persistent-cache hit]" if c.get("cache_hit") else ""),
        ])
    return rows


def heatmap_inputs(data: RunData, key: str):
    ms_rows = data.model_stats_rows()
    if not ms_rows:
        return [], [], []
    groups = ms_rows[0].get("groups", [])
    steps = [int(r.get("step", i)) for i, r in enumerate(ms_rows)]
    matrix: List[List[Optional[float]]] = []
    for gi in range(len(groups)):
        row = []
        for r in ms_rows:
            vals = r.get(key) or []
            v = vals[gi] if gi < len(vals) else None
            row.append(float(v) if isinstance(v, (int, float)) else None)
        matrix.append(row)
    return groups, steps, matrix


CSS = """
body { font: 14px/1.45 system-ui, sans-serif; margin: 24px auto; max-width: 900px; color: #1f2937; }
h1 { font-size: 20px; } h2 { font-size: 16px; margin-top: 28px; border-bottom: 1px solid #e5e7eb; }
h3 { font-size: 13px; margin: 8px 0 2px; color: #374151; }
table { border-collapse: collapse; width: 100%; font-size: 13px; }
td, th { border: 1px solid #e5e7eb; padding: 3px 8px; text-align: left; vertical-align: top; }
th { background: #f3f4f6; }
.note { color: #92400e; background: #fef3c7; padding: 2px 8px; display: inline-block; }
.ax { font-size: 9px; fill: #6b7280; }
svg { width: 100%; height: auto; }
.chart { margin-bottom: 10px; }
code { background: #f3f4f6; padding: 0 3px; }
"""


def render_html(data: RunData, title: str) -> str:
    markers = annotation_markers(data)
    out = [
        "<!doctype html>",
        '<html lang="en"><head><meta charset="utf-8">',
        f"<title>{html.escape(title)}</title>",
        f"<style>{CSS}</style></head><body>",
        f"<h1>{html.escape(title)}</h1>",
        "<p>" + " · ".join(html.escape(s) for s in data.sources) + "</p>",
    ]
    for n in data.notes:
        out.append(f'<p class="note">{html.escape(n)}</p>')

    out.append("<h2>Summary</h2><table>")
    for k, v in summarize(data):
        out.append(
            f"<tr><th>{html.escape(str(k))}</th><td>{html.escape(str(v))}</td></tr>"
        )
    out.append("</table>")

    out.append("<h2>Curves</h2>")
    out.append(svg_line("loss", data.series("loss"), "#2563eb", markers))
    out.append(svg_line("learning rate", data.series("lr"), "#7c3aed", markers))
    out.append(svg_line("MFU", data.series("mfu"), "#059669", markers))
    out.append(svg_line(
        "data wait (cumulative s)", data.series("data_wait_s"), "#d97706", markers
    ))
    out.append(svg_line(
        "tokens/s", data.series("tokens_per_sec"), "#0891b2", markers
    ))

    out.append("<h2>Per-layer-group statistics</h2>")
    for key, label in (
        ("grad_norm", "grad norm by layer group"),
        ("update_ratio", "update/param ratio by layer group"),
    ):
        groups, steps, matrix = heatmap_inputs(data, key)
        out.append(svg_heatmap(label, groups, steps, matrix))

    out.append("<h2>Memory watermarks</h2>")
    out.append(svg_line(
        "host RSS (bytes)", data.series("mem", "host_rss_bytes"), "#be123c", markers
    ))
    dev = data.series("mem", "device_peak_bytes")
    if dev:
        out.append(svg_line("device peak (bytes)", dev, "#9d174d", markers))

    out.append("<h2>Events &amp; compiles</h2>")
    rows = event_rows(data)
    if rows:
        out.append("<table><tr><th>event</th><th>step</th><th>detail</th></tr>")
        for r in rows:
            out.append(
                "<tr>" + "".join(f"<td>{html.escape(c)}</td>" for c in r) + "</tr>"
            )
        out.append("</table>")
    else:
        out.append("<p>no events recorded</p>")
    out.append("</body></html>")
    return "\n".join(out) + "\n"


def render_markdown(data: RunData, title: str) -> str:
    lines = [f"# {title}", "", "sources: " + "; ".join(data.sources), ""]
    for n in data.notes:
        lines.append(f"> NOTE: {n}")
    lines += ["", "## Summary", "", "| key | value |", "|---|---|"]
    for k, v in summarize(data):
        lines.append(f"| {k} | {v} |")
    loss = data.series("loss")
    if loss:
        lines += ["", "## Loss", "", "| step | loss |", "|---|---|"]
        stride = max(1, len(loss) // 40)
        for s, v in loss[::stride]:
            lines.append(f"| {s} | {_fmt(v)} |")
    ms = data.model_stats_rows()
    if ms:
        last = ms[-1]
        lines += ["", f"## Layer groups (step {last.get('step', '?')})", "",
                  "| group | grad_norm | param_norm | update_ratio | nonfinite_frac |",
                  "|---|---|---|---|---|"]
        for i, g in enumerate(last.get("groups", [])):
            cells = [
                _fmt(last[k][i]) if i < len(last.get(k) or []) else ""
                for k in ("grad_norm", "param_norm", "update_ratio",
                          "nonfinite_frac")
            ]
            lines.append("| " + " | ".join([g] + cells) + " |")
    rows = event_rows(data)
    if rows:
        lines += ["", "## Events", "", "| event | step | detail |", "|---|---|---|"]
        for r in rows:
            lines.append("| " + " | ".join(c.replace("|", "\\|") for c in r) + " |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--metrics", help="engine metrics JSONL")
    ap.add_argument("--flight", help="flight_recorder.jsonl dump")
    ap.add_argument("--trace", help="Chrome-trace JSON export")
    ap.add_argument("--run-dir", help="directory to scan for the conventional names")
    ap.add_argument("-o", "--out", default="report.html",
                    help="output path ('-' = stdout)")
    ap.add_argument("--format", choices=("html", "md"), default=None,
                    help="default: by --out extension (html unless .md)")
    ap.add_argument("--title", default="PaddleFleetX-TPU run report")
    args = ap.parse_args(argv)

    data = find_artifacts(args)
    if not data.sources:
        print("report.py: no readable artifact (give --metrics/--flight/"
              "--trace or --run-dir)", file=sys.stderr)
        for n in data.notes:
            print(f"  {n}", file=sys.stderr)
        return 2
    fmt = args.format or ("md" if args.out.endswith(".md") else "html")
    doc = (render_markdown if fmt == "md" else render_html)(data, args.title)
    if args.out == "-":
        sys.stdout.write(doc)
    else:
        with open(args.out, "w") as f:
            f.write(doc)
        kind = "markdown" if fmt == "md" else "self-contained HTML"
        print(f"report.py: wrote {kind} report to {args.out} "
              f"({len(data.records)} step records, {len(data.events)} events, "
              f"{len(data.compiles)} compiles)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
