"""Generation serving CLI: stdin REPL or a minimal HTTP JSON endpoint.

TPU-native counterpart of the reference's deploy path (InferenceEngine
multi-rank predictor + projects/gpt/inference scripts): one process per
host, TP over the serving mesh, bucketed prompts so repeat traffic reuses
compiled decode artifacts (`core/serving.py`).

The HTTP path runs on an admission-controlled request queue
(`core/request_queue.py`): bounded depth (full -> 429 + Retry-After),
per-request deadlines (expired -> 503 before a decode is wasted), a
single scheduler thread that coalesces compatible waiting requests into
one batched decode riding the existing compile buckets, SIGTERM/SIGINT
graceful drain (stop admitting -> answer all admitted work -> exit 0;
second signal force-quits), and a wedged-generation watchdog that flips
`/healthz` to degraded.  Operations runbook: docs/serving.md.

Observability (docs/observability.md): every counter rides the unified
telemetry registry (`utils/telemetry.py`); `GET /metrics` renders it as
Prometheus text exposition and `/healthz` renders the SAME locked
snapshot as operator JSON — the two can never disagree.  Each request's
lifecycle (admission -> queue_wait -> decode -> respond) is recorded as
a span feeding TTFT / per-token-latency histograms and the crash flight
recorder, which dumps its postmortem under PFX_FLIGHT_DIR (default
./artifacts/; PFX_FLIGHT_RECORDER overrides the exact path) on
watchdog-degraded, force-quit, and uncaught crashes.

Deep-dive layer (`utils/tracing.py`): sampled per-request trace
timelines (`PFX_TRACE_SAMPLE`/`PFX_TRACE_CAP`; 200 responses carry
`trace_id`), the continuous scheduler's per-iteration decision log, and
read-only live introspection — `GET /debug/state` (queue ages, per-row
positions, arena occupancy, compile families), `GET /debug/trace?id=`
(one request's timeline), `GET /debug/traces` (the sampled window as
Perfetto-loadable Chrome-trace JSON).  Configured SLOs (`--slo-ttft-p99`,
`--slo-error-rate`) export `pfx_slo_*` burn-rate gauges and an `slo`
block (with breach reason) on `/healthz`.

Usage:
  python tools/serve.py -c configs/gpt/pretrain_gpt_345M_single.yaml            # REPL
  python tools/serve.py -c ... --port 8000                                       # HTTP
      POST /generate {"prompt": "...", "max_tokens": 64, "deadline_s": 30}
      GET  /healthz
      GET  /metrics
      GET  /debug/state | /debug/trace?id=<trace_id> | /debug/traces
      POST /admin/drain            # authenticated remote drain
      POST /admin/adopt_prefixes   # migration receiver (PFXH1 body)

/admin/* and /debug/* are gated by the fleet-shared ``PFX_ADMIN_TOKEN``
bearer token (unset = loopback-only, loudly — core/router.check_admin);
``POST /admin/drain`` is the remote spelling of the SIGTERM drain
contract, so rolling deploys work cross-host (docs/serving.md "Elastic
control plane").
"""

import argparse
import json
import math
import os
import secrets
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init


def build_server(config: str, overrides):
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import get_config

    cfg = get_config(config, overrides=overrides)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    from paddlefleetx_tpu.utils.checkpoint import load_pretrained_params

    params = load_pretrained_params(cfg)

    tok = None
    tokenizer_dir = cfg.get("Generation", {}).get("tokenizer_dir")
    if tokenizer_dir:
        from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

        tok = GPTTokenizer.from_pretrained(tokenizer_dir)

    return GenerationServer(cfg, mesh, module, params=params, tokenizer=tok)


def clamp_max_tokens(requested, default: int, cap: int) -> int:
    """Resolve a request's max_tokens: the configured default when the
    client sent none, clamped to ``cap`` (> 0) either way, floored at 1.
    A huge client value must not key an enormous decode buffer/compile or
    occupy the scheduler for minutes (Generation.max_tokens_cap /
    --max-tokens-cap)."""
    val = default if requested is None else int(requested)
    if cap > 0:
        val = min(val, cap)
    return max(1, val)


def plan_request(prompts_ids, max_toks: int, *, bucket: int, context: int):
    """Predict `GenerationServer.generate_ids` bucketing for one request:
    returns (trim, coalesce_key) where ``trim`` is the request's own
    decode cap after context clamping and ``coalesce_key`` is
    (prompt-length bucket, 32-bucketed decode length) — two requests with
    equal keys pad identically whether served together or apart, so
    coalescing them reuses an already-compiled artifact and (greedy)
    stays token-identical to sequential serving.  Built on the SAME
    helpers generate_ids pads/clamps with (`bucket_len`, `plan_decode`),
    so the prediction cannot drift from the padding.  Raises ValueError
    when the padded prompt leaves no decode room (HTTP 400, before
    admission)."""
    from paddlefleetx_tpu.core.serving import plan_decode
    from paddlefleetx_tpu.models.gpt.generation import bucket_len

    pbucket = bucket_len(max(len(p) for p in prompts_ids), bucket)
    trim, run = plan_decode(pbucket, max_toks, context=context)
    return trim, (pbucket, run)


# /healthz "queue" block: healthz key -> registry metric (one snapshot
# feeds both /metrics and /healthz, so the two endpoints cannot disagree)
_QUEUE_HEALTH_KEYS = {
    "submitted": "pfx_queue_submitted_total",
    "completed": "pfx_queue_completed_total",
    "batches": "pfx_queue_batches_total",
    "coalesced_batches": "pfx_queue_coalesced_batches_total",
    "coalesced_requests": "pfx_queue_coalesced_requests_total",
    "shed_deadline": "pfx_queue_shed_deadline_total",
    "rejected_full": "pfx_queue_rejected_full_total",
    "rejected_closed": "pfx_queue_rejected_closed_total",
    "gen_errors": "pfx_queue_gen_errors_total",
}


def _record_request_span(reg, recorder, t0, fut, code, tokens=None,
                         streamed=False):
    """Turn one /generate lifecycle into telemetry: span phases
    (admission -> queue_wait -> decode -> respond) from the queue's
    monotonic stamps, TTFT + per-token histograms, and a flight-recorder
    event so the last N request spans survive into a crash dump.  A
    request shed before pickup has no decode phase (labeled ``shed``).
    The request's sampled deep-dive trace (if any) gets its terminal
    ``respond`` stamp here and is finished — ``/debug/trace?id=`` then
    replays the full timeline."""
    from paddlefleetx_tpu.utils.telemetry import Span

    trace = getattr(fut, "trace", None) if fut is not None else None
    if trace is not None:
        trace.event("respond", code=code, tokens=tokens)
        trace.finish()
    span = Span("request", t0=t0)
    times = dict(getattr(fut, "times", {}) or {}) if fut is not None else {}
    if "enqueued" in times:
        span.mark("admission", t=times["enqueued"])
    if "picked" in times:
        span.mark("queue_wait", t=times["picked"])
    if "resolved" in times:
        span.mark("decode" if "picked" in times else "shed",
                  t=times["resolved"])
    span.mark("respond")
    phases = span.phases()
    if "queue_wait" in phases:
        reg.histogram("pfx_request_queue_wait_seconds").observe(
            phases["queue_wait"]
        )
    if "decode" in phases:
        reg.histogram("pfx_request_decode_seconds").observe(phases["decode"])
        if tokens:
            reg.histogram("pfx_request_per_token_seconds").observe(
                phases["decode"] / max(1, tokens)
            )
    if "resolved" in times and code == 200 and not streamed:
        # non-streamed decode: the whole completion lands at once, so
        # first-token time IS resolution time.  STREAMED requests
        # (POST /generate?stream=1, the SSE path) observe their own
        # TTFT at the FIRST token flush and their total latency at
        # stream close — this branch skips them (``streamed``) so
        # nothing double-counts.  Success-only either way, like the
        # latency histogram: a shed request's ~deadline wait is not a
        # "time to first token" — it delivered none, and letting it in
        # would turn TTFT p99 into the shed deadline exactly when
        # operators alert
        reg.histogram("pfx_request_ttft_seconds").observe(
            max(0.0, times["resolved"] - t0)
        )
    recorder.record(span.event(code=code, tokens=tokens))


def build_scheduler(server, scheduler: str, *, queue_depth: int,
                    max_coalesce: int, cb_batch: int = 8,
                    kv_blocks: int = 0, name: str = "serve",
                    role: str = "monolith", prefix_cache_blocks: int = 0,
                    prefill_chunk: int = 0, prefix_spill_bytes: int = 0,
                    tenant_config=None, preempt_min_tokens: int = 8):
    """Construct the serving scheduler behind ``--scheduler``:

    - ``coalesce`` (default): the PR 3 `RequestQueue` — same-bucket
      waiting requests merge into one batched decode.
    - ``continuous``: iteration-level scheduling over the block-paged KV
      cache (`core/continuous_batching.py`) — rows join and leave the
      running decode batch at every step boundary, so a request arriving
      mid-decode no longer waits a full decode (head-of-line blocking).
      Flips to the default once the paged drills have soaked on a chip
      window (docs/serving.md).

    ``role="prefill"`` (disaggregated serving, docs/serving.md
    "Multi-host serving") instead wires a `RequestQueue` whose runner is
    `PagedDecodeEngine.prefill_export`: each admitted request prefills
    one prompt into the arena and leaves as a KV-handoff payload — the
    whole admission/deadline/drain contract rides the queue unchanged.

    All spellings expose the same surface (submit/try_remove/depth/
    busy_seconds/close/join/stats), so the HTTP layer below is
    scheduler-agnostic."""
    from paddlefleetx_tpu.core.request_queue import RequestQueue

    if role == "prefill":
        from paddlefleetx_tpu.core.continuous_batching import (
            PagedDecodeEngine,
        )

        engine = PagedDecodeEngine(
            server, max_batch=cb_batch, num_blocks=kv_blocks,
            # prefix reuse on the prefill pool: a shared system prefix
            # is computed once per prefill replica — prefill_export
            # consults/publishes the radix index (docs/serving.md
            # "Disaggregated operations")
            prefix_cache_blocks=prefix_cache_blocks,
            prefill_chunk=prefill_chunk,
            prefix_spill_bytes=prefix_spill_bytes,
        )

        def prefill_runner(prompts, max_new):
            # per-prompt traces ride RequestQueue.batch_traces (set by
            # the scheduler thread for the duration of this call), so
            # the export's fine-grained prefill_export span lands on
            # the request's own timeline — the prefill leg a stitched
            # fleet trace shows is the real export window, not just
            # the queue's coarse decode envelope
            traces = queue.batch_traces or [None] * len(prompts)
            return [
                engine.prefill_export(p, max_new, trace=tr)
                for p, tr in zip(prompts, traces)
            ]

        queue = RequestQueue(
            prefill_runner, max_depth=queue_depth, max_coalesce=1,
            name=name, tenant_config=tenant_config,
        )
        queue.engine = engine  # warmup + /debug introspection
        return queue
    if scheduler == "coalesce":
        return RequestQueue(
            lambda prompts, max_new: server.generate_ids(
                prompts, max_dec_len=max_new
            ),
            max_depth=queue_depth, max_coalesce=max_coalesce, name=name,
            tenant_config=tenant_config,
        )
    if scheduler == "continuous":
        from paddlefleetx_tpu.core.continuous_batching import (
            ContinuousScheduler,
            PagedDecodeEngine,
        )

        engine = PagedDecodeEngine(
            server, max_batch=cb_batch, num_blocks=kv_blocks,
            prefix_cache_blocks=prefix_cache_blocks,
            prefill_chunk=prefill_chunk,
            prefix_spill_bytes=prefix_spill_bytes,
        )
        return ContinuousScheduler(
            engine, max_depth=queue_depth, name=name,
            tenant_config=tenant_config,
            preempt_min_tokens=preempt_min_tokens,
        )
    raise ValueError(
        f"unknown scheduler {scheduler!r}; valid: coalesce, continuous"
    )


def serve_http(server, port: int, host: str = "127.0.0.1", *,
               queue_depth: int = 64, max_coalesce: int = 8,
               default_deadline_s: float = 120.0, max_deadline_s: float = 600.0,
               shed_slack_s: float = 2.0,
               watchdog_s: float = 300.0, max_tokens_cap: int = 0,
               scheduler: str = "coalesce", cb_batch: int = 8,
               kv_blocks: int = 0, prefix_cache_blocks: int = 0,
               prefill_chunk: int = 0, prefix_spill_bytes: int = 0,
               cb_warmup=(),
               slo_ttft_p99_s: float = 0.0, slo_error_rate: float = 0.0,
               slo_windows_s=(60.0, 600.0),
               role: str = "monolith", replica_id: str = "",
               tenants_path: str = "", preempt_min_tokens: int = 8,
               router_url: str = ""):
    import signal
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from queue import Empty as SinkEmpty, Queue as SinkQueue
    from urllib.parse import parse_qs, urlsplit

    from paddlefleetx_tpu.core.request_queue import (
        DeadlineExceeded,
        QueueClosed,
        QueueFull,
    )
    from paddlefleetx_tpu.core.router import check_admin
    from paddlefleetx_tpu.core.tenancy import (
        PRIORITY_HEADER,
        TENANT_HEADER,
        TenantConfig,
        TenantLabelCap,
        normalize_tenant,
        parse_priority,
    )
    from paddlefleetx_tpu.utils.log import log_server_error
    from paddlefleetx_tpu.utils.telemetry import (
        SLOTracker,
        atomic_artifact_write,
        flight_dir,
        get_flight_recorder,
        get_registry,
    )
    from paddlefleetx_tpu.utils import tracing
    from paddlefleetx_tpu.utils.tracing import (
        SPAN_SUMMARY_HEADER,
        chrome_trace,
        get_trace_buffer,
        parse_span_summaries,
        remote_parent,
        remote_parent_from_headers,
        span_summary,
    )

    reg = get_registry()
    recorder = get_flight_recorder()
    # a crash anywhere in the serving process leaves a postmortem ring
    recorder.install_excepthook()
    # retrace attribution (utils/model_stats.py): mid-traffic compiles
    # land in the flight ring + pfx_compile_* with the aval diff that
    # keyed them (PFX_COMPILE_LOG=0 disables)
    from paddlefleetx_tpu.utils.model_stats import install_compile_watcher

    install_compile_watcher()
    trace_buffer = get_trace_buffer()

    # SLO burn-rate layer (docs/observability.md): objectives evaluated
    # over rolling multi-window burn rates, exported as pfx_slo_* gauges
    # and surfaced as the /healthz "slo" block.  Observed per RESPONSE
    # in the HTTP layer — the decode hot path never touches it.
    # multi-tenant isolation (docs/serving.md): quota/weight config +
    # the process-wide label fold the per-tenant series ride
    tenant_config = (TenantConfig.from_file(tenants_path)
                     if tenants_path else TenantConfig())
    tenant_labels = TenantLabelCap(seed=tenant_config.known_tenants())
    slo = SLOTracker(
        ttft_p99_s=slo_ttft_p99_s, error_rate=slo_error_rate,
        windows_s=slo_windows_s, tenant_label_fn=tenant_labels.label,
    )
    if slo.enabled:
        reg.register_collector(slo)

    def _slo_observe(code, fut, t0, tenant=None):
        # per-tenant TTFT is observed regardless of SLO objectives: the
        # flood drill reads isolation off this histogram
        ttft = None
        times = getattr(fut, "times", {}) if fut is not None else {}
        if code == 200 and "resolved" in times:
            ttft = max(0.0, times["resolved"] - t0)
        if ttft is not None:
            reg.histogram(
                "pfx_tenant_ttft_seconds",
                tenant=tenant_labels.label(normalize_tenant(tenant)),
            ).observe(ttft)
        if not slo.enabled:
            return
        # contract outcomes: 200 is budget-neutral; 429/500/503 spend the
        # error budget; 400/404 are the client's fault and observe nothing
        if code in (400, 404):
            return
        slo.observe_request(ttft_s=ttft, ok=code == 200, tenant=tenant)

    cap = max_tokens_cap or int(
        server.cfg.get("Generation", {}).get("max_tokens_cap", 0) or 0
    )
    context = int(server.module.config.max_position_embeddings)
    bucket = server.bucket

    # the scheduler thread is the ONLY caller of generation once traffic
    # starts: generation mutates server state (RNG key split, stats,
    # cache pool / paged arena) and shares one compiled-artifact cache,
    # so the queue replaces the old global gen_lock outright.  Behind
    # --scheduler this is either the PR 3 coalescing RequestQueue or the
    # continuous-batching ContinuousScheduler (same surface).
    queue = build_scheduler(
        server, scheduler, queue_depth=queue_depth,
        max_coalesce=max_coalesce, cb_batch=cb_batch, kv_blocks=kv_blocks,
        name="serve", role=role, prefix_cache_blocks=prefix_cache_blocks,
        prefill_chunk=prefill_chunk, prefix_spill_bytes=prefix_spill_bytes,
        tenant_config=tenant_config, preempt_min_tokens=preempt_min_tokens,
    )
    # the paged engine behind the scheduler (None on the coalesce path):
    # the /healthz prefix-affinity advertisement and the drain-time
    # prefix migration read it directly
    engine = getattr(queue, "engine", None)
    # token streaming (docs/serving.md "Token streaming"): only the
    # continuous scheduler has a per-step commit hook (submit(stream=));
    # the coalesce scheduler resolves whole completions, so its streamed
    # responses degrade to a single flush at completion — still SSE, so
    # clients need one code path
    stream_capable = scheduler == "continuous" and role != "prefill"

    # /healthz identity block (docs/serving.md "Multi-host serving"):
    # the router (and a human with curl) can tell replicas apart, and
    # the pid is what lets `tools/router.py drain` ride the SIGTERM
    # drain contract on same-host topologies
    # boot_id is random PER PROCESS START: pid+boot_id names this exact
    # incarnation, so the router's re-adoption and legacy drain-by-pid
    # paths can never mistake a recycled pid for this replica
    # (docs/serving.md "Control-plane recovery")
    identity = {
        "replica_id": replica_id or f"{host}:{port}",
        "role": role,
        "scheduler": "queue" if role == "prefill" else scheduler,
        "listen": f"{host}:{port}",
        "pid": os.getpid(),
        "boot_id": secrets.token_hex(8),
        "started_at": round(time.time(), 3),
    }
    # label this process's spans for cross-process exports: the fleet's
    # stitched timelines name their Perfetto lanes off this identity
    tracing.set_process_identity(
        replica_id=identity["replica_id"], role=role,
    )

    # in-flight /generate requests (admission + wait + response write);
    # /healthz surfaces it so an operator tells "busy" from "wedged".
    # All HTTP accounting lives on the telemetry registry: /healthz and
    # /metrics read ONE locked snapshot instead of the old half-locked
    # Counter + latency deque (the reservoir rides the latency histogram)
    in_flight_gauge = reg.gauge("pfx_http_requests_in_flight")
    client_gone = reg.counter("pfx_http_client_gone_total")
    latency_hist = reg.histogram("pfx_request_latency_seconds")
    draining_gauge = reg.gauge("pfx_serve_draining")
    degraded_gauge = reg.gauge("pfx_serve_degraded")
    # health state flags (process-local booleans drive control flow; the
    # gauges mirror them for scrapes)
    flags = {"draining": False, "degraded": False}
    stop_event = threading.Event()

    # direct prefill->decode transfer (docs/serving.md "Disaggregated
    # operations"): one process-wide send counter so
    # PFX_FAULT=handoff_drop:K targets the Kth direct send exactly —
    # locked, because handler threads increment it concurrently
    direct_state = {"n": 0}
    direct_lock = threading.Lock()

    def _direct_handoff(payload: bytes, url: str, fwd_deadline: float,
                        parent=None, extra_headers=None):
        """POST one KV-handoff payload straight to the ticketed decode
        replica (auth via the fleet PFX_ADMIN_TOKEN rule, bounded
        timeout, ONE retry for sends that provably never arrived).
        Returns ``(code, body, content_type, headers)`` for the
        /prefill response:

          - decode answered 200 -> relay its JSON completion (the
            payload bytes never transit the router);
          - send never arrived (refused / injected drop / not sent),
            twice, or decode answered 429/503 (capacity/draining) or
            401/403 (this replica's admin token rejected — the router
            authenticates the proxy leg itself) -> PROXY FALLBACK:
            return the payload octet-stream for the router to carry —
            any decode replica can take it, nothing was adopted;
          - any other non-200 -> relay the decode replica's verdict
            (a 400 payload rejection repeats at every pool member);
          - lost MID-exchange -> structured 502 naming the decode leg:
            the row may be adopted there, so the router must run its
            re-prefill failover through a healthy pair instead of ever
            replaying at that replica."""
        from paddlefleetx_tpu.core.router import (
            ReplicaUnavailable,
            RequestNotSent,
            _http_request,
            admin_headers,
        )
        from paddlefleetx_tpu.utils.resilience import maybe_fire

        with direct_lock:
            direct_state["n"] += 1
            seq = direct_state["n"]
        # the direct hop carries the ROUTER's trace identity onward so
        # the decode leg's spans stitch under the same fleet timeline
        # (prefill -> decode is the one hop the router never sees)
        fwd_trace = dict(parent and {
            tracing.TRACE_ID_HEADER: parent["trace_id"],
            tracing.PARENT_SPAN_HEADER: "handoff_direct",
        } or {})
        last_err = "send failed"
        t_send = time.monotonic()
        for _attempt in range(2):  # the send + one retry
            # the ticket budget keeps burning across attempts: a retry
            # after a stalled first send must not offer /decode the
            # full budget again (the router's clock expired with the
            # stall — a doomed decode would just pin arena blocks)
            left = fwd_deadline - (time.monotonic() - t_send)
            if left <= 0:
                last_err = (f"{last_err}; ticket budget spent before "
                            "retry")
                break
            if maybe_fire("handoff_drop", seq):
                # deterministic drop drill: this send never goes out
                last_err = "injected handoff_drop"
                continue
            try:
                status, body, _, hdrs = _http_request(
                    url, "POST",
                    f"/decode?deadline_s={left:.3f}",
                    body=payload,
                    headers={
                        "Content-Type": "application/octet-stream",
                        "X-Handoff-Transport": "direct",
                        # tenant/priority ride the prefill->decode hop
                        # verbatim (the one hop the router never sees)
                        **(extra_headers or {}),
                        **admin_headers(),
                        **fwd_trace,
                    },
                    # the remaining ticket budget is bounded by the
                    # router's --max-deadline: give the socket the same
                    # grace the proxy leg gets — a cap below the
                    # deadline would misclassify a slow but legitimate
                    # decode as a dead replica
                    timeout=left + 5.0,
                )
            except ConnectionRefusedError as e:
                last_err = f"refused: {e}"
                continue
            except RequestNotSent as e:
                last_err = str(e)
                continue
            except ReplicaUnavailable as e:
                reg.counter("pfx_handoff_direct_total",
                            outcome="decode_dead").inc()
                return (502, json.dumps({
                    "error": f"direct decode leg lost mid-exchange ({e})",
                    "handoff_leg": "decode",
                }).encode(), "application/json", None)
            if status == 200:
                reg.counter("pfx_handoff_bytes_total",
                            transport="direct").inc(len(payload))
                reg.counter("pfx_handoff_direct_total",
                            outcome="ok").inc()
                # the decode replica's span summary rides the relay back
                # (the /prefill response appends this replica's own, so
                # the router stitches both legs off one hop)
                child = hdrs.get(SPAN_SUMMARY_HEADER)
                return (200, body, "application/json",
                        {SPAN_SUMMARY_HEADER: child} if child else None)
            if status in (401, 403, 429, 503):
                # 429/503: capacity/draining — any pool member can take
                # the payload off the router's proxy leg. 401/403: the
                # decode pool rejected THIS replica's admin token; the
                # router authenticates the proxy leg with its OWN
                # credentials, so a prefill-side token misconfiguration
                # must degrade to the carry, not surface as a
                # transport-specific client error
                last_err = f"decode answered HTTP {status}"
                break
            reg.counter("pfx_handoff_direct_total",
                        outcome="rejected").inc()
            return (status, body, "application/json", None)
        reg.counter("pfx_handoff_direct_total", outcome="fallback").inc()
        # loud on the replica, not just a response header the router
        # consumes: a PERSISTENT degradation (token misconfiguration,
        # firewalled decode pool) defeats the direct transport's whole
        # point while every request still succeeds via the proxy carry
        print(f"DIRECT-TRANSFER DEGRADED to proxy carry "
              f"(send #{seq}): {last_err}", flush=True)
        return (200, payload, "application/octet-stream",
                {"X-Direct-Error": last_err})

    class Handler(BaseHTTPRequestHandler):
        timeout = 120  # a silent client can't pin a handler thread forever

        def log_message(self, *a):  # route through our logger instead
            pass

        def _send(self, code: int, body: bytes, ctype: str, headers=None):
            if code >= 500:
                # one structured line per 5xx (utils/log.log_server_error):
                # greppable key=value carrying whatever the handler knew —
                # trace_id when the request was sampled (it rides the
                # response headers), tenant, and the error body as outcome
                outcome = None
                if ctype == "application/json":
                    try:
                        outcome = json.loads(body.decode()).get("error")
                    except (ValueError, UnicodeDecodeError):
                        pass
                log_server_error(
                    "serve", code, self.path,
                    replica_id=identity["replica_id"],
                    tenant=self.headers.get(TENANT_HEADER),
                    trace_id=(headers or {}).get("X-Trace-Id"),
                    outcome=outcome,
                )
            # disconnect-tolerant: a client that hung up while we write
            # (including on an error path) is counted as client_gone —
            # never a stack trace, never a skewed http_* counter
            try:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError, TimeoutError):
                # TimeoutError: the handler socket timeout fired while a
                # stalled client refused our bytes — same client_gone class
                client_gone.inc()
            else:
                reg.counter("pfx_http_responses_total", code=str(code)).inc()

        def _json(self, code: int, obj, headers=None):
            self._send(code, json.dumps(obj).encode(), "application/json",
                       headers)

        def do_GET(self):
            parts = urlsplit(self.path)
            if parts.path == "/healthz":
                # ONE registry snapshot renders the whole health view —
                # the same snapshot function /metrics exposes, so the two
                # endpoints agree and no field is read outside a lock
                snap = reg.snapshot()
                state = ("draining" if flags["draining"]
                         else "degraded" if flags["degraded"] else "ok")
                counts = {}
                for lab, v in snap.get(
                    "pfx_http_responses_total", {"values": []}
                )["values"]:
                    counts[f"http_{lab.get('code', '?')}"] = int(v)
                gone = int(reg.value("pfx_http_client_gone_total", snap=snap))
                if gone:
                    counts["client_gone"] = gone
                lat = reg.value(
                    "pfx_request_latency_seconds",
                    default={"p50": 0.0, "p99": 0.0}, snap=snap,
                )
                ttft = reg.value(
                    "pfx_request_ttft_seconds",
                    default={"p50": 0.0, "p99": 0.0}, snap=snap,
                )
                itl = reg.value(
                    "pfx_request_itl_seconds",
                    default={"p50": 0.0, "p99": 0.0}, snap=snap,
                )
                # serving numerics come from the SAME snapshot (not a
                # second read of server.stats) so /healthz and /metrics
                # can never disagree; instance-local extras (last_error,
                # warmup_s) overlay from the stats view
                serving_keys = {
                    "requests": ("pfx_serving_requests_total", int),
                    "tokens_out": ("pfx_serving_tokens_out_total", int),
                    "time_s": ("pfx_serving_gen_seconds_total", float),
                    "traces": ("pfx_serving_traces_total", int),
                    "gen_errors": ("pfx_serving_gen_errors_total", int),
                    "last_latency_s":
                        ("pfx_serving_last_latency_seconds", float),
                }
                serving_view = {
                    k: v for k, v in server.stats.items()
                    if k not in serving_keys
                }
                serving_view.update({
                    k: cast(reg.value(m, snap=snap))
                    for k, (m, cast) in serving_keys.items()
                })
                body = {
                    "ok": not flags["degraded"],
                    "state": state,
                    "identity": identity,
                    "in_flight": int(reg.value(
                        "pfx_http_requests_in_flight", snap=snap)),
                    "queue_depth": int(reg.value("pfx_queue_depth",
                                                 snap=snap)),
                    "busy_s": round(
                        reg.value("pfx_queue_busy_seconds", snap=snap), 3),
                    # elastic-control signal (core/controller.py): the
                    # continuous scheduler's rows/capacity (0 elsewhere)
                    "occupancy": round(float(reg.value(
                        "pfx_batch_occupancy", snap=snap)), 4),
                    # decode-pool scale + routing signal: arena blocks
                    # an admission can actually obtain (continuous
                    # scheduler replicas only; absent elsewhere)
                    **({"available_blocks": int(reg.value(
                        "pfx_kv_blocks_available", snap=snap))}
                       if "pfx_kv_blocks_available" in snap else {}),
                    # prefix-affinity routing signal (core/router.py):
                    # how many shared-prefix blocks this replica has
                    # published, plus a compact digest of the hottest
                    # cached prefixes (crc32 path hashes) — the router
                    # scores requests toward the replica already
                    # holding their prefill (absent when the prefix
                    # cache is off)
                    **({"prefix_cached_blocks": int(reg.value(
                        "pfx_prefix_cached_blocks", snap=snap))}
                       if "pfx_prefix_cached_blocks" in snap else {}),
                    **({"prefix_hashes": engine.cache.prefix.digest(),
                        "prefix_block": int(engine.block)}
                       if engine is not None
                       and getattr(engine, "prefix_enabled", False)
                       else {}),
                    "queue": {
                        k: int(reg.value(m, snap=snap))
                        for k, m in _QUEUE_HEALTH_KEYS.items()
                    },
                    "counters": counts,
                    "latency_p50_s": round(lat["p50"], 4),
                    "latency_p99_s": round(lat["p99"], 4),
                    "ttft_p50_s": round(ttft["p50"], 4),
                    "ttft_p99_s": round(ttft["p99"], 4),
                    # inter-token latency (streamed /generate flushes):
                    # first-class next to TTFT — the fleet log + report
                    # panels read these per replica
                    "itl_p50_s": round(itl["p50"], 4),
                    "itl_p99_s": round(itl["p99"], 4),
                    **serving_view,
                }
                if slo.enabled:
                    # burn-rate view with the breach reason: an operator
                    # reads WHY /healthz is angry without a dashboard
                    body["slo"] = slo.evaluate()
                if parse_qs(parts.query).get("metrics", ["0"])[0] not in (
                    "0", "",
                ):
                    # fleet federation source (core/router.py): the FULL
                    # Prometheus exposition rendered from the SAME
                    # snapshot the health fields above came from — the
                    # router's poll loop scores routing on these fields
                    # and re-exports these samples, and because both
                    # ride one snapshot they can never tell two stories
                    body["metrics_text"] = reg.render_prometheus(snap)
                self._json(200, body)
            elif parts.path == "/metrics":
                # Prometheus text exposition of the same registry snapshot
                self._send(
                    200, reg.render_prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif parts.path.startswith("/debug/"):
                self._debug_get()
            else:
                self._json(404, {"error": "unknown path"})

        def _authorized(self, what: str) -> bool:
            """Gate an /admin or /debug request on the shared
            PFX_ADMIN_TOKEN (core/router.check_admin): token set ->
            bearer match required; unset -> loopback-only, loudly.
            Answers 401/403 itself when the check fails."""
            ok, code, msg = check_admin(
                self.headers, self.client_address, what=what
            )
            if not ok:
                self._json(code, {"error": msg})
            return ok

        def _debug_get(self):
            """Live introspection (docs/observability.md): read-only,
            lock-consistent snapshots that never block the scheduler
            thread; prompt/token CONTENTS are never exposed.  Gated by
            the same PFX_ADMIN_TOKEN rule as /admin/* — introspection
            must not ship unauthenticated on a non-loopback bind."""
            if not self._authorized("/debug"):
                return
            parts = urlsplit(self.path)
            if parts.path == "/debug/state":
                # one registry snapshot rides along so the debug view and
                # the scraped gauges can be compared from a single read
                snap = reg.snapshot()
                dbg = queue.debug_state()
                dbg["serving"] = {
                    "compiled_families": len(getattr(server, "_compiled", {})),
                    "traces": int(server.stats["traces"]),
                    "gen_errors": int(server.stats["gen_errors"]),
                }
                dbg["flags"] = dict(flags)
                dbg["trace_buffer"] = {
                    "sample": trace_buffer.sample,
                    "cap": trace_buffer.cap,
                    "retained": len(trace_buffer.traces()),
                }
                if slo.enabled:
                    dbg["slo"] = slo.evaluate()
                gauges = {}
                for name in (
                    "pfx_queue_depth", "pfx_queue_busy_seconds",
                    "pfx_http_requests_in_flight", "pfx_batch_occupancy",
                    "pfx_kv_blocks_used", "pfx_kv_blocks_free",
                    "pfx_kv_bytes", "pfx_prefill_admits_total",
                    "pfx_request_evictions_total", "pfx_spec_accept_rate",
                    "pfx_spec_accepted_total", "pfx_spec_proposed_total",
                    "pfx_prefix_hits_total", "pfx_prefix_misses_total",
                    "pfx_prefix_hit_tokens_total",
                    "pfx_prefix_evictions_total", "pfx_prefix_cached_blocks",
                    "pfx_prefill_chunks_total",
                    "pfx_prefix_spill_bytes", "pfx_prefix_spill_entries",
                    "pfx_prefix_spills_total", "pfx_prefix_readmits_total",
                    "pfx_prefix_spill_discards_total",
                    "pfx_migrate_sent_total", "pfx_migrate_adopted_total",
                    "pfx_migrate_failed_total",
                ):
                    if name in snap:
                        gauges[name] = reg.value(name, snap=snap)
                dbg["metrics"] = gauges
                return self._json(200, dbg)
            if parts.path == "/debug/trace":
                tid = (parse_qs(parts.query).get("id") or [""])[0]
                if not tid:
                    return self._json(400, {"error": "need ?id=<trace_id>"})
                tc = trace_buffer.get(tid)
                if tc is None:
                    return self._json(404, {
                        "error": f"trace {tid!r} not in the sampled window "
                                 f"(cap {trace_buffer.cap}, sample "
                                 f"{trace_buffer.sample:g})"
                    })
                return self._json(200, tc.timeline())
            if parts.path == "/debug/traces":
                # the retained window as Perfetto/chrome://tracing JSON
                return self._json(200, chrome_trace(trace_buffer.traces()))
            return self._json(404, {"error": "unknown debug path"})

        def _parse_prompts(self, req):
            """(prompts_ids, mode) from a /generate body; raises
            ValueError with a client-facing message (HTTP 400)."""
            if "prompt" in req or "prompts" in req:
                if server.tokenizer is None:
                    raise ValueError(
                        "no tokenizer configured (Generation.tokenizer_dir); "
                        "send prompt_ids/prompts_ids"
                    )
                if "prompt" in req:
                    texts, mode = [req["prompt"]], "prompt"
                else:
                    texts, mode = list(req["prompts"]), "prompts"
                if not texts or not all(
                    isinstance(t, str) and t for t in texts
                ):
                    raise ValueError("prompts must be non-empty strings")
                return [server.tokenizer.encode(t) for t in texts], mode
            if "prompt_ids" in req:
                ids, mode = [req["prompt_ids"]], "prompt_ids"
            elif "prompts_ids" in req:
                ids, mode = list(req["prompts_ids"]), "prompts_ids"
            else:
                raise ValueError("need prompt(s) or prompt(s)_ids")
            if not ids or any(not p for p in ids):
                raise ValueError(
                    "prompts must be a non-empty list of non-empty id lists"
                )
            return [[int(t) for t in p] for p in ids], mode

        def _check_batch_cap(self, prompts_ids):
            # one request may not smuggle an unbounded batch past the
            # admission bounds: a 4096-prompt entry would occupy ONE
            # queue slot yet key a giant padded-batch compile that wedges
            # the single scheduler thread for everyone else
            if len(prompts_ids) > max_coalesce:
                raise ValueError(
                    f"too many prompts in one request "
                    f"({len(prompts_ids)} > {max_coalesce}); split the batch"
                )

        def do_POST(self):
            parts = urlsplit(self.path)
            if parts.path.startswith("/admin/"):
                return self._admin(parts)
            if parts.path == "/generate":
                if role == "prefill":
                    # a prefill replica has no decode loop to finish a
                    # request: an honest 400 beats a silent wrong answer
                    return self._json(400, {
                        "error": "--role prefill serves POST /prefill "
                                 "only (disaggregated topology; see "
                                 "docs/serving.md)"
                    })
                return self._generate(parts)
            if parts.path == "/prefill":
                if role != "prefill":
                    return self._json(404, {"error": "not a prefill replica"})
                # fabric-internal endpoint: the fleet PFX_ADMIN_TOKEN
                # rule applies (token set -> bearer match; unset ->
                # loopback-only, loudly) — a KV-handoff surface must not
                # ship unauthenticated on a non-loopback bind
                if not self._authorized("/prefill"):
                    return
                return self._prefill()
            if parts.path == "/decode":
                if role != "decode":
                    return self._json(404, {"error": "not a decode replica"})
                if not self._authorized("/decode"):
                    return
                return self._decode(parts)
            return self._json(404, {"error": "unknown path"})

        def _admin(self, parts):
            """POST /admin/* — the authenticated operations surface
            (docs/serving.md "Elastic control plane").  ``/admin/drain``
            is the remote spelling of SIGTERM: the response is written
            first (the caller learns the drain STARTED), then admission
            closes, every admitted request is answered, and the process
            exits 0 — rolling deploys no longer need to share a host
            with the replica."""
            if not self._authorized("/admin"):
                return
            if parts.path == "/admin/drain":
                # optional JSON body: {"migrate_to": [peer_url, ...]}
                # names surviving peers to ship the hottest published
                # prefixes to before the listener dies (KV migration,
                # docs/serving.md "KV lifecycle").  Read BEFORE the
                # response — the body is gone once we answer.
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    req = {}  # a bare drain must keep working
                peers = tuple(
                    str(u) for u in (req.get("migrate_to") or []) if u
                )
                # response FIRST, then the drain: an idle replica can
                # finish its drain in milliseconds, and the caller must
                # learn the drain started before the listener dies
                already = flags["draining"]
                self._json(200, {
                    "state": "draining",
                    "already_draining": already,
                    "queued": queue.depth(),
                })
                # a drain initiated over a traced hop names the caller's
                # trace in the postmortem, so an operator can tie this
                # replica's drain_start to the router action behind it
                parent = remote_parent_from_headers(self.headers)
                initiate_drain(
                    "admin drain" + (
                        f" (trace {parent['trace_id']})" if parent else ""
                    ),
                    migrate_to=peers,
                )
                return
            if parts.path == "/admin/adopt_prefixes":
                return self._adopt_prefixes()
            if parts.path == "/admin/profile":
                return self._profile()
            return self._json(404, {"error": "unknown admin path"})

        def _profile(self):
            """POST /admin/profile {"seconds": T} — capture a
            jax.profiler trace of THIS live serving process and answer
            with the parsed summary (docs/observability.md "On-demand
            profiling").  Safety rails live in
            utils/profiler.capture_profile: one capture at a time
            (ProfileBusy -> 409) and the PFX_PROFILE_MAX_SECONDS hard
            cap (-> 400).  The capture observes the running scheduler —
            it drives nothing, so profiling a production replica under
            load is bounded and safe."""
            from paddlefleetx_tpu.utils.profiler import (
                ProfileBusy,
                capture_profile,
            )

            n = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
            except json.JSONDecodeError:
                return self._json(400, {"error": "body must be JSON"})
            seconds = req.get("seconds", 3.0)
            top = int(req.get("top", 20))
            # one dir per capture under the flight dir: the trace is a
            # postmortem artifact and lands next to the crash ring
            prof_dir = os.path.join(
                flight_dir(), "profiles",
                time.strftime("%Y%m%d-%H%M%S"),
            )
            try:
                summary = capture_profile(seconds, prof_dir, top=top)
            except ProfileBusy as e:
                print(f"[serve] /admin/profile refused: {e}", flush=True)
                return self._json(409, {"error": str(e)})
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            summary["replica_id"] = identity["replica_id"]
            # durable copy next to the trace itself, torn-write-proof,
            # so a fleet report can inline the op table later
            atomic_artifact_write(
                os.path.join(prof_dir, "profile_summary.json"),
                lambda f: json.dump(summary, f, indent=1),
            )
            recorder.record({
                "event": "profile_capture",
                "seconds": summary["seconds"],
                "trace_dir": prof_dir,
                "source": summary["source"],
            })
            return self._json(200, summary)

        def _adopt_prefixes(self):
            """POST /admin/adopt_prefixes — the migration-receiver half
            of KV durability (docs/serving.md "KV lifecycle"): a
            draining peer's exported prefix payload (PFXH1 binary body)
            is validated IN FULL before anything touches the arena,
            then folded in on the scheduler thread at an iteration
            boundary.  A torn or incompatible payload gets an honest
            400 and nothing is half-adopted; a draining/closed replica
            answers 503 so the sender's failover ladder moves on."""
            from paddlefleetx_tpu.core.paged_cache import unpack_handoff

            if not hasattr(queue, "submit_prefix_adoption"):
                return self._json(400, {
                    "error": "prefix adoption requires --scheduler "
                             "continuous (paged KV arena)"
                })
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            try:
                meta, arrays = unpack_handoff(body)
                fut = queue.submit_prefix_adoption(meta, arrays)
            except ValueError as e:
                # torn payload / wrong block size / pool-shape mismatch:
                # rejected whole, before any arena mutation
                return self._json(400, {"error": str(e)})
            except QueueClosed:
                return self._json(
                    503, {"error": "draining: not adopting prefixes"},
                    headers={"Retry-After": "5"},
                )
            try:
                adopted = fut.result(timeout=default_deadline_s)
            except TimeoutError:
                return self._json(
                    503, {"error": "adoption still pending; scheduler "
                                   "busy"},
                    headers={"Retry-After": "1"},
                )
            except Exception as e:  # noqa: BLE001 — arena reset et al.
                return self._json(500, {"error": str(e)})
            return self._json(200, {"adopted_blocks": int(adopted)})

        def _fail(self, code: int, msg: str, fut, t0, retry=None):
            """One failed-request epilogue: span + SLO accounting (400s
            are the client's fault and spend no SLO budget) + response."""
            _record_request_span(reg, recorder, t0, fut, code)
            if code != 400:
                _slo_observe(code, fut, t0)
            self._json(code, {"error": msg},
                       headers={"Retry-After": retry} if retry else None)

        def _await_result(self, fut, deadline_s: float, t0):
            """THE result-wait ladder, shared by /generate, /prefill and
            /decode: block bounded by deadline + scheduling slack; on any
            failure send the honest error (503 shed / 400 / 500) and
            return None — an unanswerable request never hangs a
            connection."""
            try:
                return fut.result(timeout=deadline_s + shed_slack_s)
            except TimeoutError:
                queue.try_remove(fut)  # shed it if still queued
                self._fail(503, f"deadline {deadline_s:g}s exceeded",
                           fut, t0, retry="1")
            except DeadlineExceeded as e:
                self._fail(503, str(e), fut, t0, retry="1")
            except QueueClosed as e:  # flushed by a forced shutdown
                self._fail(503, str(e), fut, t0, retry="5")
            except ValueError as e:  # bad request that got past checks
                self._fail(400, str(e), fut, t0)
            except Exception as e:  # noqa: BLE001 — report, keep serving
                self._fail(500, str(e), fut, t0)
            return None

        def _read_deadline(self, raw):
            """Validate a client deadline: positive, finite, capped by
            the server ceiling (raises ValueError -> HTTP 400)."""
            deadline_s = float(raw)
            if not (deadline_s > 0 and math.isfinite(deadline_s)):
                raise ValueError(
                    "deadline_s must be a positive finite number"
                )
            return min(deadline_s, max_deadline_s)

        def _submit_guarded(self, submit, t0):
            """THE admission-rejection contract, shared by /generate,
            /prefill and /decode: run the queue-submit callable and
            return its future, or answer 429 (full) / 503 (draining) /
            400 (pre-admission validation) and return None."""
            try:
                return submit()
            except QueueFull:
                _slo_observe(429, None, t0)
                self._json(
                    429,
                    {"error": f"queue full ({queue_depth} waiting); "
                              "retry later"},
                    headers={"Retry-After": "1"},
                )
            except QueueClosed:
                _slo_observe(503, None, t0)
                self._json(
                    503,
                    {"error": "draining: not admitting new requests"},
                    headers={"Retry-After": "5"},
                )
            except ValueError as e:
                # pre-admission validation (could-never-fit budget,
                # incompatible handoff payload): the client's fault
                self._json(400, {"error": str(e)})
            return None

        def _remote_parent_authed(self):
            """Parse the trace-propagation headers, honored only when
            the request passes the fleet admin rule (token set ->
            bearer match; unset -> loopback-only): an unauthenticated
            client must not force-sample traces past the accumulator
            or receive internal span summaries.  Degrades to untraced
            (no 401 — propagation is fabric plumbing, not a client
            API).  /prefill and /decode parse the headers directly:
            those surfaces are already behind ``_authorized``."""
            parent = remote_parent_from_headers(self.headers)
            if parent is None:
                return None
            ok, _, _ = check_admin(self.headers, self.client_address,
                                   what="trace propagation")
            return parent if ok else None

        def _span_headers(self, fut, parent, carried=None):
            """Fabric-internal response headers for a traced hop: this
            process's span summary (appended to any ``carried`` header
            value a downstream leg returned) + the local trace id.
            None for plain client traffic — summaries ride only hops
            that arrived with propagation headers."""
            if fut is None or fut.trace is None:
                return None
            headers = {"X-Trace-Id": fut.trace.trace_id}
            if parent is not None:
                summaries = (parse_span_summaries(carried)
                             if carried else [])
                summaries.append(span_summary(fut.trace))
                headers[SPAN_SUMMARY_HEADER] = json.dumps(summaries)
            return headers

        def _tenant_of(self):
            """The request's tenant label + clamped priority, from the
            X-Tenant / X-Priority headers (absent -> the anonymous
            tenant at priority 0).  The RAW header value also rides
            back out on forwarded hops, verbatim."""
            raw = self.headers.get(TENANT_HEADER)
            return (normalize_tenant(raw),
                    parse_priority(self.headers.get(PRIORITY_HEADER)))

        def _wants_stream(self, parts) -> bool:
            """Streamed response requested: ``POST /generate?stream=1``
            or ``Accept: text/event-stream`` (docs/serving.md)."""
            if parts is not None and parse_qs(parts.query).get(
                "stream", ["0"]
            )[0] not in ("0", ""):
                return True
            return "text/event-stream" in (
                self.headers.get("Accept") or ""
            )

        def _generate(self, parts=None):
            in_flight_gauge.add(1)
            t0 = time.monotonic()
            fut = None
            observed = False  # span + SLO recorded for this request
            parent = self._remote_parent_authed()
            tenant, priority = self._tenant_of()
            try:
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError as e:
                    return self._json(400, {"error": f"bad JSON: {e}"})
                # ---- validate BEFORE admission: a malformed request
                # must never occupy a queue slot or a decode ----
                try:
                    prompts_ids, mode = self._parse_prompts(req)
                    self._check_batch_cap(prompts_ids)
                    max_toks = clamp_max_tokens(
                        req.get("max_tokens"), server.gen.max_dec_len, cap
                    )
                    # finite floor AND server-side ceiling: an unbounded
                    # client deadline (or JSON Infinity) would pin the
                    # handler thread + connection for as long as the
                    # scheduler stays busy — the hung-connection mode
                    # this queue exists to prevent
                    deadline_s = self._read_deadline(
                        req.get("deadline_s", default_deadline_s)
                    )
                    trim, key = plan_request(
                        prompts_ids, max_toks, bucket=bucket, context=context
                    )
                except (ValueError, TypeError) as e:
                    return self._json(400, {"error": str(e)})
                if self._wants_stream(parts):
                    self._generate_stream(
                        prompts_ids, mode, trim, key, deadline_s,
                        parent, t0, tenant, priority,
                    )
                    observed = True  # the stream path did its accounting
                    return
                # ---- admission control ---- (a hop that arrived with
                # X-Trace-Id binds its parent so the attached trace is
                # force-sampled into the caller's stitched timeline)
                with remote_parent(parent):
                    fut = self._submit_guarded(
                        lambda: queue.submit(
                            prompts_ids, trim,
                            coalesce_key=key, deadline_s=deadline_s,
                            tenant=tenant, priority=priority,
                        ),
                        t0,
                    )
                if fut is None:
                    observed = True  # _submit_guarded answered + spent SLO
                    return
                # ---- wait, bounded by the deadline + scheduling slack:
                # an unanswerable request gets an honest 503, never a
                # hung connection ----
                rows = self._await_result(fut, deadline_s, t0)
                if rows is None:
                    observed = True  # _await_result spent the span + SLO
                    return
                if mode in ("prompt", "prompts"):
                    texts = [server.tokenizer.decode(r) for r in rows]
                    payload = ({"completion": texts[0]} if mode == "prompt"
                               else {"completions": texts})
                else:
                    payload = ({"completion_ids": rows[0]}
                               if mode == "prompt_ids"
                               else {"completions_ids": rows})
                if fut.trace is not None:
                    # the handle for GET /debug/trace?id= (sampled only)
                    payload["trace_id"] = fut.trace.trace_id
                latency_hist.observe(time.monotonic() - t0)
                _record_request_span(
                    reg, recorder, t0, fut, 200,
                    tokens=sum(len(r) for r in rows),
                )
                _slo_observe(200, fut, t0, tenant=tenant)
                observed = True
                return self._json(200, payload,
                                  headers=self._span_headers(fut, parent))
            except Exception as e:  # noqa: BLE001 — last-resort guard
                # a failure AFTER decode (tokenizer decode, payload
                # build) is still a failed request: it must spend SLO
                # budget and close its trace, or a bug here would be
                # invisible to the burn gauges exactly like the old
                # wedged-503 blind spot
                if not observed:
                    _record_request_span(reg, recorder, t0, fut, 500)
                    _slo_observe(500, fut, t0, tenant=tenant)
                return self._json(500, {"error": str(e)})
            finally:
                in_flight_gauge.add(-1)

        def _generate_stream(self, prompts_ids, mode, trim, key,
                             deadline_s, parent, t0,
                             tenant=None, priority=0):
            """SSE token streaming (docs/serving.md "Token streaming"):
            tokens leave the box as the engine commits them instead of
            when the row finishes.  The body is HTTP/1.0
            close-delimited (no Content-Length): ``event: token``
            frames carry ``{"row", "index", "tokens"}`` with per-row
            monotone indices, and a terminal ``event: summary`` frame
            carries usage plus — on authed traced hops — the span
            summaries the router stitches (the streamed stand-in for
            the X-Span-Summary header, which cannot be complete before
            the body starts).  Accounting: TTFT at the FIRST flush,
            per-gap ITL at every later flush, total latency at stream
            close; success-only, like the non-streamed path.  The
            coalesce scheduler has no per-step commit hook, so its
            stream degrades to a single flush at completion (same SSE
            framing either way)."""
            sink = SinkQueue()
            submit_kw = {"coalesce_key": key, "deadline_s": deadline_s,
                         "tenant": tenant, "priority": priority}
            if stream_capable:
                submit_kw["stream"] = (
                    lambda row, start, toks: sink.put((row, start, toks))
                )
            with remote_parent(parent):
                fut = self._submit_guarded(
                    lambda: queue.submit(prompts_ids, trim, **submit_kw),
                    t0,
                )
            if fut is None:
                return  # 429/503/400 answered + accounted
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                if fut.trace is not None:
                    self.send_header("X-Trace-Id", fut.trace.trace_id)
                self.end_headers()
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, TimeoutError):
                client_gone.inc()
                queue.try_remove(fut)
                return
            itl_hist = reg.histogram("pfx_request_itl_seconds")
            ttft_hist = reg.histogram("pfx_request_ttft_seconds")
            first_flush = None
            last_flush = None
            flushes = 0
            sent_tokens = 0
            client_lost = False
            stream_err = None
            code = 200
            hard_deadline = t0 + deadline_s + shed_slack_s

            def emit(event, obj):
                nonlocal client_lost
                if client_lost:
                    return False
                frame = (f"event: {event}\n"
                         f"data: {json.dumps(obj)}\n\n").encode()
                try:
                    self.wfile.write(frame)
                    self.wfile.flush()
                    return True
                except (BrokenPipeError, ConnectionResetError,
                        TimeoutError):
                    client_gone.inc()
                    client_lost = True
                    return False

            def flush_tokens(row, start, toks):
                nonlocal first_flush, last_flush, flushes, sent_tokens
                now = time.monotonic()
                if first_flush is None:
                    # TTFT at the moment bytes actually leave for the
                    # client — not at future resolution
                    first_flush = now
                    ttft_hist.observe(max(0.0, now - t0))
                else:
                    itl_hist.observe(max(0.0, now - last_flush))
                last_flush = now
                flushes += 1
                sent_tokens += len(toks)
                obj = {"row": row, "index": start, "tokens": toks}
                if mode in ("prompt", "prompts"):
                    obj["text"] = server.tokenizer.decode(toks)
                return emit("token", obj)

            while not (fut.done() and sink.empty()):
                try:
                    row, start, toks = sink.get(timeout=0.05)
                except SinkEmpty:
                    if time.monotonic() > hard_deadline and not fut.done():
                        queue.try_remove(fut)  # shed it if still queued
                        code = 503
                        stream_err = f"deadline {deadline_s:g}s exceeded"
                        break
                    continue
                if not flush_tokens(row, start, toks):
                    break  # client hung up: stop draining, decode finishes
            rows = None
            if stream_err is None:
                try:
                    rows = fut.result(timeout=deadline_s + shed_slack_s)
                except DeadlineExceeded as e:
                    code, stream_err = 503, str(e)
                except QueueClosed as e:
                    code, stream_err = 503, str(e)
                except TimeoutError:
                    queue.try_remove(fut)
                    code = 503
                    stream_err = f"deadline {deadline_s:g}s exceeded"
                except ValueError as e:
                    code, stream_err = 400, str(e)
                except Exception as e:  # noqa: BLE001 — report, keep serving
                    code, stream_err = 500, str(e)
            if stream_err is not None:
                # mid-stream failure (deadline shed, eviction, drain):
                # an honest terminal error frame — status PLUS how many
                # tokens were already committed to the wire, so a
                # client whose row was evicted mid-decode always sees a
                # closed stream with an accounting, never a silent hang
                # (the status line already said 200 — SSE's reality)
                emit("error", {"error": stream_err, "code": code,
                               "tokens_committed": sent_tokens})
                _record_request_span(reg, recorder, t0, fut, code,
                                     tokens=sent_tokens or None,
                                     streamed=True)
                if code != 400:
                    _slo_observe(code, fut, t0, tenant=tenant)
                return
            if flushes == 0 and not client_lost:
                # single-flush degradation (coalesce scheduler, or a
                # zero-token completion): everything arrives at once,
                # in the same frame shape
                for i, r in enumerate(rows):
                    if not flush_tokens(i, 0, list(r)):
                        break
            # success epilogue: total latency at stream CLOSE (the
            # non-streamed path observes at response build — same
            # success-only rule), span + SLO with the first-flush TTFT
            latency_hist.observe(time.monotonic() - t0)
            _record_request_span(
                reg, recorder, t0, fut, 200,
                tokens=sum(len(r) for r in rows), streamed=True,
            )
            if first_flush is not None:
                reg.histogram(
                    "pfx_tenant_ttft_seconds",
                    tenant=tenant_labels.label(normalize_tenant(tenant)),
                ).observe(max(0.0, first_flush - t0))
            if slo.enabled:
                slo.observe_request(
                    ttft_s=(max(0.0, first_flush - t0)
                            if first_flush is not None else None),
                    ok=True, tenant=tenant,
                )
            summary = {
                "usage": {
                    "prompts": len(rows),
                    "tokens": sum(len(r) for r in rows),
                },
                "flushes": flushes,
            }
            if fut.trace is not None:
                summary["trace_id"] = fut.trace.trace_id
                if parent is not None:
                    # computed AFTER _record_request_span finished the
                    # trace, exactly like _span_headers on the
                    # non-streamed path
                    summary["spans"] = [span_summary(fut.trace)]
            emit("summary", summary)

        def _prefill(self):
            """POST /prefill (role=prefill): run one prompt's paged
            prefill and answer with the binary KV-handoff payload the
            router hands to a decode replica.  Same admission surface
            as /generate: bounded queue (429), deadlines (503 shed),
            graceful drain.

            With a ``forward`` placement ticket in the request (the
            router's direct-transfer topology), the payload is POSTed
            STRAIGHT to the named decode replica instead — handoff
            bytes never transit the router — and the decode replica's
            JSON completion is relayed back.  A send that provably
            failed before the decode replica read it degrades to the
            proxy leg (the payload is returned, octet-stream, for the
            router to carry); a send lost MID-exchange answers a
            structured 502 naming the decode leg, so the router can run
            its re-prefill failover without ever replaying at the dead
            replica."""
            from paddlefleetx_tpu.core.paged_cache import pack_handoff

            in_flight_gauge.add(1)
            t0 = time.monotonic()
            fut = None
            parent = remote_parent_from_headers(self.headers)
            tenant, priority = self._tenant_of()
            try:
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError as e:
                    return self._json(400, {"error": f"bad JSON: {e}"})
                try:
                    ids = req.get("prompt_ids")
                    if not ids:
                        raise ValueError("need a non-empty prompt_ids list")
                    prompt_ids = [int(t) for t in ids]
                    max_toks = clamp_max_tokens(
                        req.get("max_tokens"), server.gen.max_dec_len, cap
                    )
                    deadline_s = self._read_deadline(
                        req.get("deadline_s", default_deadline_s)
                    )
                    fwd = req.get("forward") or None
                    fwd_url = fwd_deadline = None
                    if fwd is not None:
                        fwd_url = str(fwd["url"])
                        fwd_deadline = self._read_deadline(
                            fwd.get("deadline_s", deadline_s)
                        )
                except (KeyError, ValueError, TypeError) as e:
                    return self._json(400, {"error": str(e)})
                with remote_parent(parent):
                    fut = self._submit_guarded(
                        lambda: queue.submit(
                            [prompt_ids], max_toks,
                            coalesce_key=None, deadline_s=deadline_s,
                            tenant=tenant, priority=priority,
                        ),
                        t0,
                    )
                if fut is None:
                    return
                exports = self._await_result(fut, deadline_s, t0)
                if exports is None:
                    return
                payload = pack_handoff(*exports[0])
                if fwd_url is not None:
                    # the ticket's deadline burns down with queue wait
                    # and prefill compute: hand the decode replica only
                    # what is LEFT, and shed honestly when the export
                    # itself spent the budget — nothing was adopted
                    # anywhere, and the router has given up on its own
                    # clock already
                    fwd_left = fwd_deadline - (time.monotonic() - t0)
                    if fwd_left <= 0:
                        _record_request_span(reg, recorder, t0, fut, 503)
                        _slo_observe(503, fut, t0, tenant=tenant)
                        return self._json(503, {
                            "error": "deadline exhausted after prefill "
                                     "export (forward ticket spent)",
                        })
                    fwd_tenant = {
                        h: v for h, v in (
                            (TENANT_HEADER,
                             self.headers.get(TENANT_HEADER)),
                            (PRIORITY_HEADER,
                             self.headers.get(PRIORITY_HEADER)),
                        ) if v
                    }
                    code, body, ctype, headers = _direct_handoff(
                        payload, fwd_url, fwd_left, parent=parent,
                        extra_headers=fwd_tenant,
                    )
                    latency_hist.observe(time.monotonic() - t0)
                    _record_request_span(reg, recorder, t0, fut, code)
                    # every 5xx here is a DECODE-side verdict (a death
                    # report or a relayed decode error; this replica's
                    # own failures take the generic 500 path below) and
                    # must not spend the PREFILL SLO budget: the breach
                    # signal is always live, and burning it here would
                    # scale the prefill pool on decode-pool failures
                    _slo_observe(200 if code >= 500 else code, fut, t0,
                                 tenant=tenant)
                    # append THIS replica's summary to the decode leg's
                    # (carried back by _direct_handoff): one relayed
                    # header stitches both legs at the router
                    carried = (headers or {}).get(SPAN_SUMMARY_HEADER)
                    span_h = self._span_headers(fut, parent, carried)
                    if span_h or headers:
                        headers = {**(headers or {}), **(span_h or {})}
                    return self._send(code, body, ctype, headers)
                latency_hist.observe(time.monotonic() - t0)
                _record_request_span(reg, recorder, t0, fut, 200)
                _slo_observe(200, fut, t0, tenant=tenant)
                return self._send(
                    200, payload, "application/octet-stream",
                    headers=self._span_headers(fut, parent),
                )
            except Exception as e:  # noqa: BLE001 — last-resort guard
                _record_request_span(reg, recorder, t0, fut, 500)
                _slo_observe(500, fut, t0, tenant=tenant)
                return self._json(500, {"error": str(e)})
            finally:
                in_flight_gauge.add(-1)

        def _decode(self, parts):
            """POST /decode (role=decode): adopt a KV-handoff payload
            into the continuous scheduler's arena and decode it to
            completion — the other half of the disaggregated topology.
            ``?deadline_s=`` rides the query string (the body is the
            binary payload)."""
            from paddlefleetx_tpu.core.paged_cache import unpack_handoff

            in_flight_gauge.add(1)
            t0 = time.monotonic()
            fut = None
            parent = remote_parent_from_headers(self.headers)
            tenant, priority = self._tenant_of()
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                # handoff bytes through THIS replica, by transport: the
                # direct-transfer acceptance evidence (router-side byte
                # counters stay flat while these account the payload)
                transport = (self.headers.get("X-Handoff-Transport")
                             or "proxy")
                reg.counter(
                    "pfx_handoff_bytes_total",
                    transport="direct" if transport == "direct"
                    else "proxy",
                ).inc(len(body))
                try:
                    raw = (parse_qs(parts.query).get("deadline_s")
                           or [default_deadline_s])[0]
                    deadline_s = self._read_deadline(raw)
                    meta, arrays = unpack_handoff(body)
                except (ValueError, TypeError) as e:
                    return self._json(400, {"error": str(e)})
                with remote_parent(parent):
                    fut = self._submit_guarded(
                        lambda: queue.submit_handoff(
                            meta, arrays, deadline_s=deadline_s,
                            tenant=tenant, priority=priority,
                        ),
                        t0,
                    )
                if fut is None:
                    return
                rows = self._await_result(fut, deadline_s, t0)
                if rows is None:
                    return
                payload = {"completion_ids": rows[0]}
                if fut.trace is not None:
                    payload["trace_id"] = fut.trace.trace_id
                latency_hist.observe(time.monotonic() - t0)
                _record_request_span(
                    reg, recorder, t0, fut, 200, tokens=len(rows[0])
                )
                _slo_observe(200, fut, t0, tenant=tenant)
                return self._json(200, payload,
                                  headers=self._span_headers(fut, parent))
            except Exception as e:  # noqa: BLE001 — last-resort guard
                _record_request_span(reg, recorder, t0, fut, 500)
                _slo_observe(500, fut, t0, tenant=tenant)
                return self._json(500, {"error": str(e)})
            finally:
                in_flight_gauge.add(-1)

    class Server(ThreadingHTTPServer):
        # NON-daemon handler threads: socketserver only tracks (and
        # server_close only joins) non-daemon threads, and the drain
        # contract requires every admitted request's response bytes to be
        # written before the process exits.  A wedged handler cannot block
        # a force-quit — the second signal's default SIGTERM action kills
        # the process without waiting on threads — and the Handler socket
        # timeout bounds how long a stalled client can delay a drain.
        daemon_threads = False
        block_on_close = True  # graceful drain joins in-flight responses

        def handle_error(self, request, client_address):
            exc = sys.exc_info()[1]
            if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                                TimeoutError)):
                client_gone.inc()
                return
            super().handle_error(request, client_address)

    httpd = Server((host, port), Handler)

    def _watchdog():
        # a generation stuck past the watchdog budget flips /healthz to
        # degraded (ok=false) so orchestrators stop routing here; flips
        # back if the scheduler ever comes unstuck
        while not stop_event.wait(1.0):
            busy = queue.busy_seconds()
            if busy > watchdog_s and not flags["degraded"]:
                flags["degraded"] = True
                degraded_gauge.set(1)
                print(
                    f"WATCHDOG: generation wedged for {busy:.0f}s "
                    f"(budget {watchdog_s:.0f}s); /healthz degraded",
                    flush=True,
                )
                # postmortem while the wedge is live: the dump carries
                # the degrade event plus the last N request spans, so a
                # later kill -9 still leaves evidence on disk
                recorder.record({
                    "event": "watchdog_degraded",
                    "busy_s": round(busy, 3),
                    "budget_s": watchdog_s,
                })
                recorder.dump(reason="watchdog_degraded")
            elif flags["degraded"] and busy < watchdog_s:
                # recovered: the wedged generation finished.  Compare
                # against the budget, not exact idle — under a steady
                # backlog a 1 Hz sampler may never catch busy == 0
                flags["degraded"] = False
                degraded_gauge.set(0)
                recorder.record({"event": "watchdog_recovered"})
                print("WATCHDOG: generation recovered; /healthz ok",
                      flush=True)

    orig_handlers = {}
    drain_lock = threading.Lock()

    def _migrate_prefixes(peers) -> None:
        """Drain-time KV migration (docs/serving.md "KV lifecycle"):
        ship the hottest published prefixes to the first surviving peer
        that will take them.  STRICTLY best-effort and deadline-bounded
        — runs AFTER queue.join() (the scheduler thread has exited, so
        the index walk is single-threaded) and BEFORE httpd.shutdown(),
        and NO failure mode here may stall the drain contract: every
        send is capped by what remains of ``PFX_MIGRATE_DEADLINE_S``,
        a wedged receiver (PFX_FAULT=migrate_stall) burns the budget
        and the drain proceeds, and any exception is caught by the
        caller.  Counters: pfx_migrate_sent_total on the accepted send,
        pfx_migrate_failed_total when no peer adopted."""
        import urllib.request

        from paddlefleetx_tpu.core.paged_cache import pack_handoff
        from paddlefleetx_tpu.core.router import admin_headers
        from paddlefleetx_tpu.utils.resilience import maybe_fire

        deadline_s = float(os.environ.get("PFX_MIGRATE_DEADLINE_S",
                                          "10") or 10)
        top = int(os.environ.get("PFX_MIGRATE_TOP", "64") or 64)
        t_end = time.monotonic() + max(0.0, deadline_s)
        export = engine.export_hot_prefixes(top)
        if export is None:
            return  # nothing cached — nothing to migrate
        payload = pack_handoff(*export)
        nblocks = len(export[0]["prefixes"])
        attempts = 0
        for peer in peers:
            url = peer.rstrip("/") + "/admin/adopt_prefixes"
            backoff = 0.2
            for _ in range(2):  # bounded retry per peer
                left = t_end - time.monotonic()
                if left <= 0:
                    break
                attempts += 1
                if maybe_fire("migrate_stall", attempts):
                    # a wedged receiver, modeled here at the send site:
                    # the hang is capped at the REMAINING migration
                    # budget, so the drain deadline holds no matter
                    # what PFX_FAULT_HANG_S says
                    hang = float(os.environ.get("PFX_FAULT_HANG_S",
                                                "30") or 30)
                    time.sleep(min(hang,
                                   max(0.0, t_end - time.monotonic())))
                    left = t_end - time.monotonic()
                    if left <= 0:
                        break
                try:
                    req = urllib.request.Request(
                        url, data=payload, method="POST",
                        headers={
                            "Content-Type": "application/octet-stream",
                            **admin_headers(),
                        },
                    )
                    with urllib.request.urlopen(
                        req, timeout=max(0.1, left)
                    ) as resp:
                        body = json.loads(resp.read() or b"{}")
                    adopted = int(body.get("adopted_blocks", 0))
                    reg.counter("pfx_migrate_sent_total").inc()
                    recorder.record({
                        "event": "migrate_sent", "peer": peer,
                        "blocks": nblocks, "adopted_blocks": adopted,
                    })
                    print(
                        f"migrate: {peer} adopted {adopted} of "
                        f"{nblocks} prefix block(s)", flush=True,
                    )
                    return
                except Exception as e:  # noqa: BLE001 — ladder moves on
                    print(f"migrate: send to {peer} failed ({e})",
                          flush=True)
                    time.sleep(min(backoff,
                                   max(0.0,
                                       t_end - time.monotonic())))
                    backoff *= 2
        reg.counter("pfx_migrate_failed_total").inc()
        recorder.record({"event": "migrate_failed",
                         "peers": list(peers), "blocks": nblocks})
        print(
            f"migrate: no surviving peer adopted within "
            f"{deadline_s:g}s; {nblocks} prefix block(s) will be "
            f"recomputed on demand", flush=True,
        )

    # -- replica self-registration (docs/serving.md "Control-plane
    # recovery"): with --router-url, this replica announces itself to
    # the router on an admin-gated heartbeat, so a router restarted with
    # a lost or stale journal rediscovers the fleet from the replicas
    # themselves; on drain it says goodbye instead of making the router
    # wait out --eject-after failed polls ---------------------------------
    advertise_host = ("127.0.0.1" if host in ("0.0.0.0", "::", "")
                      else host)
    advertise_url = f"http://{advertise_host}:{port}"

    def _post_register(payload: dict, timeout: float) -> None:
        import urllib.request

        from paddlefleetx_tpu.core.router import admin_headers

        req = urllib.request.Request(
            router_url.rstrip("/") + "/admin/register",
            data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     **admin_headers()},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()

    def _register_heartbeat():
        interval = float(os.environ.get("PFX_REGISTER_INTERVAL_S", "2")
                         or 2)
        warned = False
        payload = {"url": advertise_url, "role": role,
                   "identity": identity}
        while not stop_event.is_set() and not flags["draining"]:
            try:
                _post_register(payload, timeout=5.0)
                warned = False
            except Exception as e:  # noqa: BLE001 — best-effort forever
                if not warned:
                    warned = True
                    print(
                        f"register: heartbeat to {router_url} failed "
                        f"({e}); retrying every {interval:g}s",
                        flush=True,
                    )
            stop_event.wait(interval)

    def _deregister_from_router():
        """Best-effort goodbye on drain exit — identity rides along so
        a delayed goodbye can never eject a redeployed successor."""
        try:
            _post_register({"deregister": True, "url": advertise_url,
                            "identity": identity}, timeout=3.0)
            print("register: deregistered from router", flush=True)
        except Exception as e:  # noqa: BLE001 — the drain must finish
            print(
                f"register: deregister failed ({e}); the router will "
                "eject this replica after failed polls", flush=True,
            )

    def initiate_drain(source: str, migrate_to=()) -> bool:
        """THE drain initiation, shared by the signal handler and the
        authenticated ``POST /admin/drain`` (the remote transport that
        makes rolling deploys work cross-host): close admission, answer
        every admitted request, exit 0 — the PR 3 contract unchanged.
        ``migrate_to`` (surviving-peer base URLs from the drain body)
        additionally ships the hottest published prefixes to a peer
        before the listener dies — best-effort, hard-bounded by
        PFX_MIGRATE_DEADLINE_S, and NEVER able to fail the drain.
        Idempotent: returns False when a drain is already underway."""
        with drain_lock:
            if flags["draining"]:
                return False
            flags["draining"] = True
        draining_gauge.set(1)
        recorder.record({"event": "drain_start", "source": source,
                         "queued": queue.depth(),
                         "migrate_to": list(migrate_to)})
        print(
            f"{source}: draining — admission closed, "
            f"{queue.depth()} queued request(s) will finish",
            flush=True,
        )

        def _drain():
            queue.close()
            queue.join()
            if migrate_to and engine is not None:
                try:
                    _migrate_prefixes(migrate_to)
                except Exception as e:  # noqa: BLE001 — drain wins
                    reg.counter("pfx_migrate_failed_total").inc()
                    print(f"migrate: failed ({e}); drain continues",
                          flush=True)
            if router_url:
                _deregister_from_router()
            httpd.shutdown()

        threading.Thread(target=_drain, name="serve-drain",
                         daemon=True).start()
        return True

    def _on_signal(signum, frame):
        # mirror the PR 2 engine contract: first signal drains (stop
        # admitting -> finish admitted work -> exit 0), handlers are
        # restored immediately so a second signal force-quits
        for sig, h in orig_handlers.items():
            signal.signal(sig, h)
        if initiate_drain(f"signal {signum}"):
            print("(send again to force-quit)", flush=True)

    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            orig_handlers[sig] = signal.signal(sig, _on_signal)
    except ValueError:
        print("warning: not on the main thread; graceful drain handlers "
              "unavailable", flush=True)

    if cb_warmup and role == "prefill":
        # compile the prefill-export family per bucket before the
        # listener opens (blocks are freed per export — nothing stays)
        queue.engine.warmup_prefill([int(n) for n in cb_warmup])
    elif cb_warmup and scheduler == "continuous":
        # compile (prefill, step) per bucket BEFORE the listener opens —
        # the continuous counterpart of the coalesce-path server.warmup
        queue.warmup([int(n) for n in cb_warmup])
    queue.start()
    threading.Thread(target=_watchdog, name="serve-watchdog",
                     daemon=True).start()
    if router_url:
        threading.Thread(target=_register_heartbeat,
                         name="serve-register", daemon=True).start()
    endpoint = {"prefill": "POST /prefill", "decode": "POST /decode + /generate"}.get(
        role, "POST /generate"
    )
    print(
        f"serving on {host}:{port} ({endpoint}, GET /healthz; "
        f"role {role}, replica {identity['replica_id']}, "
        f"scheduler {identity['scheduler']}, queue depth {queue_depth}, "
        f"coalesce {max_coalesce}, "
        f"deadline {default_deadline_s:g}s, watchdog {watchdog_s:g}s)",
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        # second Ctrl-C (the first restored default handlers): honor the
        # promised force-quit.  server_close would join non-daemon
        # handler threads — one blocked on a wedged decode would hold
        # the process for up to max_deadline + slack instead of quitting.
        print("force-quit on second interrupt", flush=True)
        # last act before the hard exit: the flight recorder ring (request
        # spans, watchdog events, the drain attempt) becomes a postmortem
        recorder.record({"event": "force_quit", "signum": int(signal.SIGINT)})
        recorder.dump(reason="force_quit")
        os._exit(130)
    finally:
        stop_event.set()
        # joins in-flight handler threads: every admitted request gets
        # its response bytes before the process exits
        httpd.server_close()
    if flags["draining"]:
        print("drained cleanly: all admitted requests answered", flush=True)
    return 0


def _csv_ints(raw: str):
    return [int(x) for x in raw.split(",") if x.strip()]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-c", "--config", required=True)
    ap.add_argument("-o", "--override", action="append", default=[])
    ap.add_argument("--port", type=int, default=0, help="HTTP port (0 = stdin REPL)")
    # loopback by default: the endpoint is unauthenticated, so exposing it
    # on all interfaces must be an explicit operator decision
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (use 0.0.0.0 to expose externally)")
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--warmup-buckets", default="",
                    help="comma-separated prompt-length buckets to compile "
                    "at boot (default: 8); warmup fails loudly if any "
                    "bucket cannot compile")
    ap.add_argument("--warmup-batches", default="",
                    help="comma-separated batch-size buckets to warm per "
                    "prompt bucket (default under --port: powers of two "
                    "up to --max-coalesce, so the first coalesced burst "
                    "never pays a mid-traffic compile; default REPL: 1)")
    ap.add_argument("--queue-depth", type=int, default=64,
                    help="bounded admission queue depth; a request "
                    "arriving when full gets HTTP 429 + Retry-After")
    ap.add_argument("--max-coalesce", type=int, default=8,
                    help="max prompts merged into one batched decode "
                    "(same-bucket waiting requests coalesce)")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="default per-request deadline seconds (client "
                    "overrides with deadline_s); expired requests are "
                    "shed with HTTP 503 before a decode is wasted")
    ap.add_argument("--max-deadline", type=float, default=600.0,
                    help="server-side ceiling on client deadline_s — an "
                    "unbounded deadline would pin a handler thread and "
                    "its connection indefinitely")
    ap.add_argument("--shed-slack", type=float, default=2.0,
                    help="scheduling slack added to the deadline before "
                    "the handler gives up waiting and sheds with 503")
    ap.add_argument("--watchdog", type=float, default=300.0,
                    help="seconds a single generation may run before "
                    "/healthz flips to degraded (wedged-decode detector)")
    ap.add_argument("--max-tokens-cap", type=int, default=0,
                    help="hard per-request max_tokens ceiling (0 = use "
                    "Generation.max_tokens_cap from the config, which "
                    "defaults to uncapped-within-context)")
    ap.add_argument("--scheduler", choices=("coalesce", "continuous"),
                    default="coalesce",
                    help="serving scheduler: 'coalesce' batches same-"
                    "bucket WAITING requests (PR 3); 'continuous' is "
                    "iteration-level scheduling over the block-paged KV "
                    "cache — requests join/leave the running decode "
                    "batch at step boundaries (docs/serving.md; flips "
                    "to default after chip-window soak)")
    ap.add_argument("--cb-batch", type=int, default=8,
                    help="continuous scheduler: running-batch row "
                    "capacity (fixed compile shape)")
    ap.add_argument("--kv-blocks", type=int, default=0,
                    help="continuous scheduler: total KV arena blocks "
                    "(0 = auto: cb-batch full-context rows + null "
                    "block); block size via PFX_KV_BLOCK")
    ap.add_argument("--prefix-cache-blocks", type=int, default=0,
                    help="continuous scheduler: shared-prefix KV cache "
                    "budget in arena blocks (finished rows publish their "
                    "prompt-prefix blocks; later admissions reuse them "
                    "and prefill only the suffix; 0 disables — "
                    "docs/serving.md)")
    ap.add_argument("--prefix-spill-bytes", type=int, default=0,
                    help="continuous scheduler: host-RAM budget (bytes) "
                    "for the prefix-spill tier — LRU-evicted prefix "
                    "blocks demote to pinned host memory and readmit "
                    "on a later prefix match instead of recomputing "
                    "(requires --prefix-cache-blocks; 0 disables — "
                    "docs/serving.md 'KV lifecycle')")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="continuous scheduler: admit long prompts in "
                    "chunks of this many tokens (multiple of "
                    "PFX_KV_BLOCK), one chunk per scheduler iteration "
                    "interleaved with decode steps; 0 = monolithic "
                    "prefill")
    ap.add_argument("--draft-k", type=int, default=-1,
                    help="speculative decoding: draft tokens per verify "
                    "step (overrides Generation.speculative.draft_k; "
                    "0 disables, -1 = leave the config value)")
    ap.add_argument("--kv-dtype", choices=("bf16", "int8"), default="",
                    help="KV-cache storage dtype (overrides Generation."
                    "speculative.kv_dtype; int8 halves decode HBM "
                    "bytes — docs/decode_path.md)")
    ap.add_argument("--slo-ttft-p99", type=float, default=0.0,
                    help="SLO objective: p99 time-to-first-token seconds "
                    "(0 = off).  Breach when >1%% of requests exceed it "
                    "on EVERY --slo-windows window — /healthz grows an "
                    "'slo' block and pfx_slo_* gauges appear in /metrics")
    ap.add_argument("--slo-error-rate", type=float, default=0.0,
                    help="SLO objective: allowed fraction of failed "
                    "requests (429/500/503; 0 = off), burn-rate "
                    "evaluated like --slo-ttft-p99")
    ap.add_argument("--slo-windows", default="60,600",
                    help="comma-separated rolling burn-rate window "
                    "seconds, short first (default 60,600)")
    ap.add_argument("--role", choices=("monolith", "prefill", "decode"),
                    default="monolith",
                    help="disaggregated serving role (docs/serving.md "
                    "'Multi-host serving'): 'prefill' serves POST "
                    "/prefill (prompt -> KV-handoff payload), 'decode' "
                    "adopts payloads via POST /decode and decodes them "
                    "on the continuous scheduler; 'monolith' (default) "
                    "is the single-process path")
    ap.add_argument("--replica-id", default="",
                    help="stable identity for the /healthz identity "
                    "block (default host:port) — how tools/router.py "
                    "and humans tell replicas apart")
    ap.add_argument("--tenants", default="",
                    help="per-tenant weight/quota config JSON "
                    "(docs/serving.md 'Multi-tenant isolation'); the "
                    "scheduler serves tenants deficit-round-robin by "
                    "weight; unset = one anonymous tenant, FCFS")
    ap.add_argument("--preempt-min-tokens", type=int, default=8,
                    help="protected minimum progress: an active row "
                    "must have committed at least this many tokens "
                    "since its last admission before a higher-priority "
                    "arrival may preempt it")
    ap.add_argument("--router-url", default="",
                    help="base URL of the fleet router (e.g. "
                    "http://127.0.0.1:8000): this replica self-registers "
                    "on an admin-gated POST /admin/register heartbeat "
                    "(every PFX_REGISTER_INTERVAL_S seconds) so a "
                    "restarted router rediscovers the fleet even with a "
                    "lost journal, and deregisters on drain exit instead "
                    "of waiting out the router's --eject-after "
                    "(docs/serving.md 'Control-plane recovery')")
    ap.add_argument("--compile-cache-dir", default="",
                    help="seed jax's persistent compilation cache from "
                    "this directory (warm boot: a scale-up replica "
                    "spawned by the elastic control plane reuses the "
                    "fleet's compiled artifacts instead of paying a "
                    "cold trace — docs/serving.md 'Elastic control "
                    "plane')")
    args = ap.parse_args(argv)
    # crash-loop fault site (PFX_FAULT=boot_crash:0, docs/
    # fault_tolerance.md): a replica that can never come up — drives
    # the supervisor's flap-budget quarantine drill
    from paddlefleetx_tpu.utils.resilience import maybe_fire

    maybe_fire("boot_crash", 0)
    if args.compile_cache_dir:
        import jax

        # same knobs as the test harness: cache even fast compiles so a
        # warm-booted replica's whole family set comes from disk
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(args.compile_cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # spec/quant CLI flags become plain config overrides so BOTH
    # schedulers (GenerationServer + PagedDecodeEngine read the same
    # Generation.speculative section) see one source of truth
    if args.draft_k >= 0:
        args.override.append(f"Generation.speculative.draft_k={args.draft_k}")
    if args.kv_dtype:
        args.override.append(f"Generation.speculative.kv_dtype={args.kv_dtype}")

    if args.role != "monolith" and not args.port:
        ap.error(f"--role {args.role} requires --port (HTTP serving); "
                 "the stdin REPL has no handoff transport")
    if args.role == "decode" and args.scheduler != "continuous":
        # adoption needs the paged arena + iteration-level scheduler;
        # force it loudly instead of booting a replica that 400s
        print(
            "note: --role decode forces --scheduler continuous "
            "(KV-handoff adoption runs on the paged engine)",
            file=sys.stderr, flush=True,
        )
        args.scheduler = "continuous"

    if args.scheduler == "continuous" and not args.port:
        # the REPL serves one prompt at a time through the contiguous
        # path — iteration-level scheduling only exists behind --port.
        # Fall back loudly rather than silently skipping warmup.
        print(
            "warning: --scheduler continuous requires --port (HTTP "
            "serving); REPL mode uses the contiguous path",
            file=sys.stderr, flush=True,
        )
        args.scheduler = "coalesce"

    server = build_server(args.config, args.override)
    if not args.no_warmup and (
        args.scheduler == "continuous" or args.role == "prefill"
    ):
        # the coalesce-path warmup would compile artifacts continuous/
        # prefill serving never calls; the engine warms its own families
        # inside serve_http before the listener opens
        pass
    elif not args.no_warmup:
        batches = _csv_ints(args.warmup_batches)
        if not batches and args.port:
            # HTTP serving coalesces: warm every power-of-two batch
            # bucket a coalesced burst can land on, so the first burst
            # rides compiled artifacts instead of paying a mid-traffic
            # compile on the single scheduler thread
            b, batches = 1, []
            while b < max(1, args.max_coalesce):
                batches.append(b)
                b *= 2
            batches.append(b)
        server.warmup(
            _csv_ints(args.warmup_buckets) or [8],
            batch_sizes=batches or [1],
        )

    if args.port:
        cb_warmup = ()
        if not args.no_warmup and (
            args.scheduler == "continuous" or args.role == "prefill"
        ):
            cb_warmup = tuple(_csv_ints(args.warmup_buckets) or [8])
        return serve_http(
            server, args.port, args.host,
            queue_depth=args.queue_depth,
            max_coalesce=args.max_coalesce,
            default_deadline_s=args.deadline,
            max_deadline_s=args.max_deadline,
            shed_slack_s=args.shed_slack,
            watchdog_s=args.watchdog,
            max_tokens_cap=args.max_tokens_cap,
            scheduler=args.scheduler,
            cb_batch=args.cb_batch,
            kv_blocks=args.kv_blocks,
            prefix_cache_blocks=args.prefix_cache_blocks,
            prefill_chunk=args.prefill_chunk,
            prefix_spill_bytes=args.prefix_spill_bytes,
            cb_warmup=cb_warmup,
            slo_ttft_p99_s=args.slo_ttft_p99,
            slo_error_rate=args.slo_error_rate,
            slo_windows_s=tuple(
                float(x) for x in args.slo_windows.split(",") if x.strip()
            ),
            role=args.role,
            replica_id=args.replica_id,
            tenants_path=args.tenants,
            preempt_min_tokens=args.preempt_min_tokens,
            router_url=args.router_url,
        )

    # REPL: one prompt per line -> completion (ids mode when no tokenizer)
    try:
        print("prompt> ", end="", flush=True)
        for line in sys.stdin:
            line = line.strip()
            if not line:
                break
            try:
                if server.tokenizer is not None:
                    print(server.generate_text([line])[0], flush=True)
                else:
                    ids = [int(t) for t in line.split()]
                    print(" ".join(map(str, server.generate_ids([ids])[0])),
                          flush=True)
            except ValueError as e:  # bad ids / empty prompt: report, keep serving
                print(f"error: {e}", flush=True)
            except Exception as e:  # noqa: BLE001 — a tokenizer/runtime
                # failure is reported without tearing down the session
                print(f"generation failed ({type(e).__name__}): {e}",
                      flush=True)
            print("prompt> ", end="", flush=True)
    except (EOFError, KeyboardInterrupt):
        pass  # clean exit on ^C / closed stdin
    print("", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
