"""Generation serving CLI: stdin REPL or a minimal HTTP JSON endpoint.

TPU-native counterpart of the reference's deploy path (InferenceEngine
multi-rank predictor + projects/gpt/inference scripts): one process per
host, TP over the serving mesh, bucketed prompts so repeat traffic reuses
compiled decode artifacts (`core/serving.py`).

Usage:
  python tools/serve.py -c configs/gpt/pretrain_gpt_345M_single.yaml            # REPL
  python tools/serve.py -c ... --port 8000                                       # HTTP
      POST /generate {"prompt": "...", "max_tokens": 64}
      GET  /healthz
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init


def build_server(config: str, overrides):
    from paddlefleetx_tpu.core.module import build_module
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.parallel.env import init_dist_env
    from paddlefleetx_tpu.utils.config import get_config

    cfg = get_config(config, overrides=overrides)
    mesh = init_dist_env(cfg)
    module = build_module(cfg)

    from paddlefleetx_tpu.utils.checkpoint import load_pretrained_params

    params = load_pretrained_params(cfg)

    tok = None
    tokenizer_dir = cfg.get("Generation", {}).get("tokenizer_dir")
    if tokenizer_dir:
        from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

        tok = GPTTokenizer.from_pretrained(tokenizer_dir)

    return GenerationServer(cfg, mesh, module, params=params, tokenizer=tok)


def clamp_max_tokens(requested, default: int, cap: int) -> int:
    """Resolve a request's max_tokens: the configured default when the
    client sent none, clamped to ``cap`` (> 0) either way, floored at 1.
    A huge client value must not key an enormous decode buffer/compile or
    hold the generation lock for minutes (Generation.max_tokens_cap /
    --max-tokens-cap)."""
    val = default if requested is None else int(requested)
    if cap > 0:
        val = min(val, cap)
    return max(1, val)


def serve_http(server, port: int, host: str = "127.0.0.1",
               gen_timeout_s: float = 120.0, max_tokens_cap: int = 0):
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    # generation mutates server state (RNG key split, stats) and shares one
    # compiled artifact cache — serialize it; the threading server still
    # keeps /healthz responsive while a long generation runs
    gen_lock = threading.Lock()
    # in-flight /generate requests (queued + running); /healthz surfaces it
    # so an operator can tell "busy" from "wedged" at a glance.  Handler
    # threads run concurrently, so the +=/-= pair needs its own lock or
    # lost updates would drift the gauge permanently.
    in_flight = {"n": 0}
    in_flight_lock = threading.Lock()
    cap = max_tokens_cap or int(
        server.cfg.get("Generation", {}).get("max_tokens_cap", 0) or 0
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # route through our logger instead
            pass

        def _json(self, code: int, obj, headers=None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                # stats include last_latency_s + traces (retrace counter)
                self._json(
                    200, {"ok": True, "in_flight": in_flight["n"], **server.stats}
                )
            else:
                self._json(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path != "/generate":
                return self._json(404, {"error": "unknown path"})
            with in_flight_lock:
                in_flight["n"] += 1
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                max_toks = clamp_max_tokens(
                    req.get("max_tokens"), server.gen.max_dec_len, cap
                )
                # bounded wait for the generation lock: a request stuck
                # behind a wedged/slow generation gets an honest 503 (with
                # Retry-After) instead of hanging its connection forever
                if not gen_lock.acquire(timeout=gen_timeout_s):
                    return self._json(
                        503,
                        {"error": f"generation busy for {gen_timeout_s:.0f}s; "
                                  "retry later"},
                        headers={"Retry-After": str(max(1, int(gen_timeout_s)))},
                    )
                # generate under the lock, respond AFTER releasing it: a
                # slow client blocked in the socket write must not stall
                # other requests behind a held lock
                payload = None
                try:
                    if "prompt" in req:
                        texts = server.generate_text([req["prompt"]], max_dec_len=max_toks)
                        payload = {"completion": texts[0]}
                    elif "prompts" in req:  # batched: rides the data axis together
                        texts = server.generate_text(req["prompts"], max_dec_len=max_toks)
                        payload = {"completions": texts}
                    elif "prompt_ids" in req:
                        ids = server.generate_ids([req["prompt_ids"]], max_dec_len=max_toks)
                        payload = {"completion_ids": ids[0]}
                    elif "prompts_ids" in req:
                        ids = server.generate_ids(req["prompts_ids"], max_dec_len=max_toks)
                        payload = {"completions_ids": ids}
                finally:
                    gen_lock.release()
                if payload is None:
                    return self._json(400, {"error": "need prompt(s) or prompt(s)_ids"})
                return self._json(200, payload)
            except ValueError as e:  # bad request (empty prompts, etc.)
                return self._json(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — report, keep serving
                return self._json(500, {"error": str(e)})
            finally:
                with in_flight_lock:
                    in_flight["n"] -= 1

    httpd = ThreadingHTTPServer((host, port), Handler)
    print(f"serving on {host}:{port} (POST /generate, GET /healthz)", flush=True)
    httpd.serve_forever()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-c", "--config", required=True)
    ap.add_argument("-o", "--override", action="append", default=[])
    ap.add_argument("--port", type=int, default=0, help="HTTP port (0 = stdin REPL)")
    # loopback by default: the endpoint is unauthenticated, so exposing it
    # on all interfaces must be an explicit operator decision
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (use 0.0.0.0 to expose externally)")
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--gen-timeout", type=float, default=120.0,
                    help="seconds a /generate request waits for the "
                    "generation lock before returning HTTP 503")
    ap.add_argument("--max-tokens-cap", type=int, default=0,
                    help="hard per-request max_tokens ceiling (0 = use "
                    "Generation.max_tokens_cap from the config, which "
                    "defaults to uncapped-within-context)")
    args = ap.parse_args(argv)

    server = build_server(args.config, args.override)
    if not args.no_warmup:
        server.warmup()

    if args.port:
        return serve_http(server, args.port, args.host,
                          gen_timeout_s=args.gen_timeout,
                          max_tokens_cap=args.max_tokens_cap)

    # REPL: one prompt per line -> completion (ids mode when no tokenizer)
    print("prompt> ", end="", flush=True)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            break
        try:
            if server.tokenizer is not None:
                print(server.generate_text([line])[0], flush=True)
            else:
                ids = [int(t) for t in line.split()]
                print(" ".join(map(str, server.generate_ids([ids])[0])), flush=True)
        except ValueError as e:  # bad ids / empty prompt: report, keep serving
            print(f"error: {e}", flush=True)
        print("prompt> ", end="", flush=True)


if __name__ == "__main__":
    main()
