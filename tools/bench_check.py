"""Bench-trajectory regression gate: compare the newest two
``BENCH_r*.json`` snapshots and exit nonzero on a >10% regression of any
shared metric (``make bench-check``).

Each ``BENCH_r<N>.json`` records one bench lap: ``{"n": N, "rc": ...,
"parsed": <row | [rows] | null>}`` where a row is ``{"metric", "value",
"unit", ...}``.  Comparison rules (honest by construction):

  - rows whose ``unit`` admits the lap failed are SKIPPED with a loud
    note — ``bench.py``'s honest-fallback rows spell the failure as a
    parenthetical unit suffix (``tokens/s/chip (tpu backend
    unreachable)``, ``(self-deadline 1200s exceeded)``, ``(killed by
    signal 15 before completion)``, ...) with value 0.0, so the skip
    rule is: unit matches the failure regex, OR value == 0 with ANY
    parenthetical annotation.  A dead backend is not a regression, and
    pretending the 0.0 is comparable would flag (or mask) nonsense;
  - only metrics present in BOTH snapshots **on the same backend** are
    compared (all bench metrics are higher-is-better throughputs):
    bench.py's dead-backend fallback laps carry ``platform: "cpu"``,
    and a cpu tokens/s is not comparable to a tpu tokens/s — the
    comparison walks further back to the newest snapshot sharing a
    same-platform metric, noting every platform change loudly (rows
    without a ``platform`` field — the pre-PR 5 spelling — only match
    each other);
  - fewer than two comparable snapshots → rc 0 with a loud note, never
    a silent green.

Usage: ``python tools/bench_check.py [--dir REPO] [--threshold 0.10]``
"""

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAILED_UNIT_RE = re.compile(
    r"unreachable|unavailable|no backend|exceeded|killed|timed? ?out|"
    r"before completion|no JSON|exited",
    re.IGNORECASE,
)


def load_rows(path: str) -> Tuple[int, List[dict]]:
    """(lap number, parsed rows) for one BENCH_r*.json; rows may be a
    single dict, a list, or null (a timed-out lap).  A corrupt/truncated
    snapshot raises ValueError — the caller skips it loudly instead of
    crashing the gate on it."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"unparseable JSON: {e}") from None
    if not isinstance(doc, dict):
        raise ValueError(f"expected a JSON object, got {type(doc).__name__}")
    parsed = doc.get("parsed")
    if parsed is None:
        rows: List[dict] = []
    elif isinstance(parsed, dict):
        rows = [parsed]
    else:
        rows = [r for r in parsed if isinstance(r, dict)]
    n = doc.get("n")
    if n is None:
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        n = int(m.group(1)) if m else -1
    return int(n), rows


def usable_metrics(rows: List[dict], label: str,
                   notes: List[str]) -> Dict[str, Tuple[float, str]]:
    """metric -> (value, platform) for the comparable rows; failed-lap
    rows (the honest-fallback spelling: failure reason in the unit,
    value 0.0) are skipped loudly.  ``platform`` is "" for rows that
    predate the field — those only compare against each other."""
    out: Dict[str, Tuple[float, str]] = {}
    for row in rows:
        metric = row.get("metric")
        value = row.get("value")
        unit = str(row.get("unit", ""))
        if not metric or not isinstance(value, (int, float)):
            continue
        if FAILED_UNIT_RE.search(unit) or (value == 0 and "(" in unit):
            notes.append(
                f"SKIP {label}: {metric} unit says the lap failed "
                f"({unit!r}) — not comparable"
            )
            continue
        out[str(metric)] = (float(value), str(row.get("platform", "")))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--dir", default=REPO, help="directory holding BENCH_r*.json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative drop that counts as a regression")
    args = ap.parse_args(argv)

    notes: List[str] = []
    # parse once up front (a corrupt snapshot is a loud skip, not a
    # traceback), then compare the newest two snapshots WITH comparable
    # rows: a timed-out or backend-dead lap in between must not blind
    # the gate
    loaded: List[Tuple[int, str, List[dict]]] = []
    for p in glob.glob(os.path.join(args.dir, "BENCH_r*.json")):
        try:
            n, rows = load_rows(p)
        except (OSError, ValueError) as e:
            notes.append(f"SKIP {os.path.basename(p)}: {e}")
            continue
        loaded.append((n, p, rows))
    usable: List[Tuple[int, str, Dict[str, Tuple[float, str]]]] = []
    for n, p, rows in sorted(loaded):
        metrics = usable_metrics(rows, os.path.basename(p), notes)
        if metrics:
            usable.append((n, p, metrics))
        else:
            notes.append(
                f"SKIP {os.path.basename(p)}: no comparable rows "
                "(failed lap or unparsed output)"
            )
    for note in notes:
        print(f"bench-check: {note}")
    if len(usable) < 2:
        print(
            f"bench-check: only {len(usable)} comparable snapshot(s) under "
            f"{args.dir} — nothing to compare, PASS by default (loudly)"
        )
        return 0

    # pick the comparison pair: the newest snapshot against the newest
    # OLDER one sharing at least one same-platform metric — a cpu
    # fallback lap after a tpu lap is a platform change, not a 98%
    # regression, and must not be compared (it falls through to the
    # previous cpu lap, or passes loudly when there is none)
    (n_new, p_new, new) = usable[-1]
    pair = None
    for n_old, p_old, old in reversed(usable[:-1]):
        shared = sorted(
            m for m in set(old) & set(new) if old[m][1] == new[m][1]
        )
        if shared:
            pair = (n_old, p_old, old, shared)
            break
        changed = sorted(
            f"{m}: {old[m][1] or '?'} -> {new[m][1] or '?'}"
            for m in set(old) & set(new)
        )
        print(
            f"bench-check: r{n_old} shares no same-platform metric with "
            f"r{n_new}"
            + (f" (platform changed: {'; '.join(changed)})" if changed
               else " (disjoint metric names)")
            + " — looking further back"
        )
    if pair is None:
        print(
            f"bench-check: no older snapshot comparable with r{n_new} "
            "(platform change or disjoint metrics) — nothing to "
            "compare, PASS by default (loudly)"
        )
        return 0
    n_old, p_old, old, shared = pair
    failures = 0
    for metric in shared:
        (ov, plat), (nv, _) = old[metric], new[metric]
        if ov <= 0:
            print(f"bench-check: {metric}: old value {ov} not comparable, skipped")
            continue
        drop = (ov - nv) / ov
        verdict = "REGRESSION" if drop > args.threshold else "ok"
        print(
            f"bench-check: {metric}"
            + (f" [{plat}]" if plat else "")
            + f": r{n_old}={ov:g} -> r{n_new}={nv:g} "
            f"({-drop:+.1%}) {verdict}"
        )
        failures += verdict == "REGRESSION"
    if failures:
        print(
            f"bench-check: {failures} metric(s) regressed >"
            f"{args.threshold:.0%} between {os.path.basename(p_old)} and "
            f"{os.path.basename(p_new)}"
        )
        return 1
    print(f"bench-check: {len(shared)} shared metric(s) within threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
