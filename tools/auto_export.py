"""Auto-parallel export entry point (reference tools/auto_export.py).

In the reference, auto-parallel training produces per-rank static programs
that need their own export path (`auto_dist{rank}.pdparams`,
utils/config.py:599-606).  Under pjit/GSPMD there is no separate "auto"
artifact: the same StableHLO export serves single-device and auto-parallel
models, with shardings baked in at AOT-compile time by the serving mesh
(core/inference_engine.py).  This entry point therefore delegates to
tools/export.py — kept as a distinct CLI so reference launch scripts
translate 1:1.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from export import main as export_main  # noqa: E402


def main(argv=None):
    return export_main(argv)


if __name__ == "__main__":
    main(sys.argv[1:] or None)
