"""Export entry point (reference tools/export.py:33-50): stage the model's
forward to a serialized StableHLO artifact + params checkpoint."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init


from paddlefleetx_tpu.core.module import build_module
from paddlefleetx_tpu.parallel.env import init_dist_env
from paddlefleetx_tpu.parallel.seed import get_seed_tracker
from paddlefleetx_tpu.utils.config import get_config, parse_args
from paddlefleetx_tpu.utils.export import export_inference_model


def main(argv=None):
    args = parse_args(argv)
    cfg = get_config(args.config, overrides=args.override)
    init_dist_env(cfg)
    module = build_module(cfg)

    from paddlefleetx_tpu.utils.checkpoint import load_pretrained_params

    params = load_pretrained_params(cfg)
    if params is None:
        params = module.init_params(get_seed_tracker().params_key())

    # family-generic: each module declares its inference forward + example
    # inputs (reference input_spec contract, basic_module.py:29-86)
    fwd, example_args = module.export_spec()

    out_dir = cfg.Engine.save_load.get("output_dir", "./output")
    export_inference_model(fwd, example_args, params, os.path.join(out_dir, "inference"))


if __name__ == "__main__":
    main()
