"""Repo lint gate — stdlib-only (no ruff/flake8 in the image).

The reference enforces code style via a pre-commit stack (pylint, cpplint,
clang-format, a docstring checker: /root/reference/codestyle/); this is the
TPU repo's equivalent, an AST + text checker covering the failure modes that
actually bite:

  E1  syntax error (file does not parse)
  E2  unused import (module scope; __init__.py re-export files exempt)
  E3  bare `except:`
  E4  tab characters in indentation
  E5  trailing whitespace
  E6  missing newline at end of file
  E7  `eval(` / `exec(` call (the reference's name-dispatch-by-eval is a
      design smell SURVEY.md §5.6 explicitly replaces with typed registries)
  E8  mutable default argument (def f(x=[]) / {} / set())
  E9  missing module docstring (package code under paddlefleetx_tpu/ only —
      the reference's docstring-checker analogue, codestyle/ SURVEY §4.3)
  E10 telemetry metric-name lint: every name passed to a registry
      `.counter(` / `.gauge(` / `.histogram(` call — and every string
      literal shaped like a metric name (`^pfx_[a-z0-9_]+$`, exposition
      suffixes _bucket/_sum/_count allowed) — must be declared in THE ONE
      `METRICS` table in paddlefleetx_tpu/utils/telemetry.py, so the
      /metrics namespace cannot fragment the way the per-module stats
      dicts once did (docs/observability.md)
  E11 metrics-docs agreement: every name in the `METRICS` table must
      have a row in the "### Metrics reference" table of
      docs/observability.md, and every row there must name a declared
      metric — the doc drifted from the table twice before this gate.
      (Repo-level check: runs once per invocation, not per file.)
  E12 env-knob docs agreement (two-way, like E11): every `PFX_*` env
      knob referenced in PACKAGE source (paddlefleetx_tpu/, tools/,
      benchmarks/, bench.py — tests excluded: a test-only helper knob
      is not an operator surface) must appear in a docs knob TABLE row
      (any docs/*.md markdown table line carrying the backticked name),
      and every documented knob must still exist in source — an
      operator reading the tracing/telemetry/serving/fault knob tables
      sees every knob that exists and no knob that does not.
      (Repo-level check: runs once per invocation, not per file.)

Suppress a finding with `# noqa` on the offending line.
Usage: python tools/lint.py [paths...]   (default: the whole repo)
"""

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_DIRS = [
    "paddlefleetx_tpu", "tools", "tests", "benchmarks", "examples", "tasks",
]
DEFAULT_FILES = ["bench.py", "__graft_entry__.py"]


# E10: telemetry metric naming
_METRIC_RE = re.compile(r"^pfx_[a-z0-9_]+$")
_EXPOSITION_SUFFIX = re.compile(r"_(bucket|sum|count)$")
_TELEMETRY_FNS = {"counter", "gauge", "histogram"}
_declared_metrics = ...  # lazy cache; None = telemetry module unavailable


def declared_metrics():
    """Metric names declared in telemetry.METRICS, parsed from the AST
    (never imported: lint stays jax-free).  None when the module or its
    table is missing — the E10 check then degrades to regex-only."""
    global _declared_metrics
    if _declared_metrics is not ...:
        return _declared_metrics
    path = os.path.join(REPO, "paddlefleetx_tpu", "utils", "telemetry.py")
    names = None
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in tree.body:
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AnnAssign) else []
            )
            if any(isinstance(t, ast.Name) and t.id == "METRICS" for t in targets):
                value = node.value
                if isinstance(value, ast.Dict):
                    names = {
                        k.value for k in value.keys
                        if isinstance(k, ast.Constant) and isinstance(k.value, str)
                    }
                break
    except (OSError, SyntaxError):
        names = None
    _declared_metrics = names
    return names


# E11: docs/observability.md "### Metrics reference" table
DOC_METRICS_HEADING = "### Metrics reference"


def documented_metrics(doc_path=None):
    """(names, line_numbers) documented in the Metrics reference table of
    docs/observability.md: rows matching ``| `pfx_...` | ...`` between
    the heading and the next heading.  (None, {}) when the doc or the
    heading is missing — E11 then reports the missing table itself."""
    path = doc_path or os.path.join(REPO, "docs", "observability.md")
    try:
        with open(path) as f:
            lines = f.read().split("\n")
    except OSError:
        return None, {}
    names, linenos = set(), {}
    in_table = False
    for i, ln in enumerate(lines, 1):
        if ln.strip() == DOC_METRICS_HEADING:
            in_table = True
            continue
        if in_table and ln.startswith("#"):
            break  # next heading ends the table's section
        if in_table:
            m = re.match(r"^\|\s*`(pfx_[a-z0-9_]+)`", ln)
            if m:
                names.add(m.group(1))
                linenos.setdefault(m.group(1), i)
    if not in_table:
        return None, {}
    return names, linenos


def check_metrics_docs():
    """E11 (repo-level, once per run): METRICS <-> docs/observability.md
    Metrics-reference agreement, both directions."""
    declared = declared_metrics()
    if declared is None:
        return []  # no table to check against (E10 degrades the same way)
    doc_path = os.path.join(REPO, "docs", "observability.md")
    tel_path = os.path.join(
        REPO, "paddlefleetx_tpu", "utils", "telemetry.py"
    )
    documented, linenos = documented_metrics(doc_path)
    if documented is None:
        return [(doc_path, 1, "E11",
                 f"missing '{DOC_METRICS_HEADING}' table documenting the "
                 "METRICS names")]
    findings = []
    for name in sorted(declared - documented):
        findings.append((
            tel_path, 1, "E11",
            f"metric '{name}' is declared in METRICS but has no row in "
            f"docs/observability.md '{DOC_METRICS_HEADING}'",
        ))
    for name in sorted(documented - declared):
        findings.append((
            doc_path, linenos.get(name, 1), "E11",
            f"documented metric '{name}' is not declared in "
            "telemetry.METRICS (stale doc row?)",
        ))
    return findings


# E12: env-knob docs agreement.  A knob is a FULL name (no trailing
# underscore: `f"PFX_RETRY_{field}"`-style prefixes are building blocks,
# not knobs); the docs side accepts any markdown table row in docs/*.md
# carrying the backticked name.
_ENV_KNOB_RE = re.compile(r"^PFX_[A-Z0-9]+(_[A-Z0-9]+)*$")
# source scope: operator-facing code only (tests set knobs too, but a
# test-only helper name is not an operator surface)
_ENV_KNOB_DIRS = ["paddlefleetx_tpu", "tools", "benchmarks"]
_ENV_KNOB_FILES = ["bench.py"]


def source_env_knobs():
    """name -> (file, lineno) of every PFX_* string literal in package
    source (first sighting wins)."""
    knobs = {}
    paths = (
        [os.path.join(REPO, d) for d in _ENV_KNOB_DIRS]
        + [os.path.join(REPO, f) for f in _ENV_KNOB_FILES]
    )
    for path in iter_py_files(paths):
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue  # E1 reports it
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _ENV_KNOB_RE.match(node.value)
            ):
                knobs.setdefault(node.value, (path, node.lineno))
    return knobs


def documented_env_knobs():
    """(names, first-sighting {name: (file, lineno)}) for every
    backticked PFX_* name on a markdown TABLE row in docs/*.md."""
    names, where = set(), {}
    docs_dir = os.path.join(REPO, "docs")
    try:
        files = sorted(os.listdir(docs_dir))
    except OSError:
        return names, where
    row_re = re.compile(r"`(PFX_[A-Z0-9_]+)`")
    for fn in files:
        if not fn.endswith(".md"):
            continue
        path = os.path.join(docs_dir, fn)
        try:
            with open(path) as f:
                lines = f.read().split("\n")
        except OSError:
            continue
        for i, ln in enumerate(lines, 1):
            if not ln.lstrip().startswith("|"):
                continue  # knob TABLE rows only, not prose mentions
            for m in row_re.finditer(ln):
                name = m.group(1)
                if _ENV_KNOB_RE.match(name):
                    names.add(name)
                    where.setdefault(name, (path, i))
    return names, where


def check_env_knob_docs():
    """E12 (repo-level, once per run): PFX_* knobs in source <-> docs
    knob tables, both directions."""
    knobs = source_env_knobs()
    documented, where = documented_env_knobs()
    findings = []
    for name in sorted(set(knobs) - documented):
        path, lineno = knobs[name]
        findings.append((
            path, lineno, "E12",
            f"env knob '{name}' is referenced in source but has no row "
            "in any docs/*.md knob table — document it "
            "(tracing/telemetry/serving/fault docs)",
        ))
    for name in sorted(documented - set(knobs)):
        path, lineno = where[name]
        findings.append((
            path, lineno, "E12",
            f"documented env knob '{name}' is not referenced anywhere "
            "in source (stale doc row?)",
        ))
    return findings


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d not in
                           ("__pycache__", ".jax_cache", "build", ".git")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


class ImportVisitor(ast.NodeVisitor):
    """Collect module-scope imported names and every name USED anywhere."""

    def __init__(self):
        self.imports = {}  # name -> (lineno, shown)
        self.used = set()
        self._depth = 0

    def visit_Import(self, node):
        if self._depth == 0:
            for a in node.names:
                name = a.asname or a.name.split(".")[0]
                self.imports[name] = (node.lineno, a.asname or a.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if self._depth == 0 and node.module != "__future__":
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                self.imports[name] = (node.lineno, name)
        self.generic_visit(node)

    def _scoped(self, node):
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1

    visit_FunctionDef = visit_AsyncFunctionDef = visit_ClassDef = _scoped

    def visit_Name(self, node):
        self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        # mark the root of dotted access (jax.numpy -> jax)
        n = node
        while isinstance(n, ast.Attribute):
            n = n.value
        if isinstance(n, ast.Name):
            self.used.add(n.id)
        self.generic_visit(node)


def check_file(path):
    findings = []
    with open(path, "rb") as f:
        raw = f.read()
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as e:
        return [(path, 1, "E1", f"not utf-8: {e}")]

    lines = text.split("\n")
    noqa = {i + 1 for i, ln in enumerate(lines) if "# noqa" in ln}

    def add(lineno, code, msg):
        if lineno not in noqa:
            findings.append((path, lineno, code, msg))

    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 1, "E1", f"syntax error: {e.msg}")]

    # E9: package modules document themselves (tests/tools/benches exempt)
    rel = os.path.relpath(path, REPO)
    if rel.startswith("paddlefleetx_tpu") and ast.get_docstring(tree) is None:
        add(1, "E9", "missing module docstring")

    # E2 unused imports (skip __init__.py: re-exports are the point)
    if os.path.basename(path) != "__init__.py":
        v = ImportVisitor()
        v.visit(tree)
        # names referenced inside string ANNOTATIONS and __all__ only —
        # harvesting every string constant would let a docstring mentioning
        # "os" mask a genuinely unused `import os`
        import re as _re

        def _id_words(s):
            return _re.findall(r"[A-Za-z_][A-Za-z0-9_]*", s[:2000])

        string_refs = set()
        ann_roots = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                for a in (
                    args.args + args.posonlyargs + args.kwonlyargs
                    + ([args.vararg] if args.vararg else [])
                    + ([args.kwarg] if args.kwarg else [])
                ):
                    if a.annotation is not None:
                        ann_roots.append(a.annotation)
                if node.returns is not None:
                    ann_roots.append(node.returns)
            elif isinstance(node, ast.AnnAssign):
                ann_roots.append(node.annotation)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        ann_roots.append(node.value)
        for root in ann_roots:
            for node in ast.walk(root):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    string_refs.update(_id_words(node.value))
        for name, (lineno, shown) in v.imports.items():
            if name not in v.used and name not in string_refs:
                add(lineno, "E2", f"unused import '{shown}'")

    # E10: metric names — call-site check (any name handed to a registry
    # accessor) + literal check (any metric-shaped string constant)
    declared = declared_metrics()
    flagged_metrics = set()

    def _check_metric_name(lineno, name):
        if (lineno, name) in flagged_metrics:
            return
        if not _METRIC_RE.match(name):
            flagged_metrics.add((lineno, name))
            add(lineno, "E10",
                f"metric name '{name}' does not match ^pfx_[a-z0-9_]+$")
        elif declared is not None and _EXPOSITION_SUFFIX.sub("", name) not in declared and name not in declared:
            flagged_metrics.add((lineno, name))
            add(lineno, "E10",
                f"metric '{name}' not declared in telemetry.METRICS "
                "(the one namespace table — declare it there)")

    for node in ast.walk(tree):
        # E3 bare except
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            add(node.lineno, "E3", "bare 'except:' (catch a class)")
        # E10 telemetry registry call sites
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _TELEMETRY_FNS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            _check_metric_name(node.args[0].lineno, node.args[0].value)
        # E10 metric-shaped string literals anywhere
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and _METRIC_RE.match(node.value)
        ):
            _check_metric_name(node.lineno, node.value)
        # E7 eval/exec
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("eval", "exec")
        ):
            add(node.lineno, "E7", f"'{node.func.id}()' call (use a typed registry)")
        # E8 mutable default args (literals and bare set()/dict()/list() calls)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id in ("set", "dict", "list")
                )
                if mutable:
                    add(d.lineno, "E8", "mutable default argument")

    # text-level checks
    for i, ln in enumerate(lines, 1):
        stripped_nl = ln.rstrip("\r")
        indent = stripped_nl[: len(stripped_nl) - len(stripped_nl.lstrip())]
        if "\t" in indent:
            add(i, "E4", "tab in indentation")
        if stripped_nl != stripped_nl.rstrip() and stripped_nl.strip():
            add(i, "E5", "trailing whitespace")
    if text and not text.endswith("\n"):
        add(len(lines), "E6", "missing newline at end of file")

    return findings


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    paths = argv or (
        [os.path.join(REPO, d) for d in DEFAULT_DIRS]
        + [os.path.join(REPO, f) for f in DEFAULT_FILES]
    )
    all_findings = []
    n_files = 0
    for path in iter_py_files(paths):
        n_files += 1
        all_findings.extend(check_file(path))
    # E11/E12 are repo-level invariants (code table <-> doc table),
    # checked once per run rather than per file
    all_findings.extend(check_metrics_docs())
    all_findings.extend(check_env_knob_docs())
    for path, lineno, code, msg in sorted(all_findings):
        rel = os.path.relpath(path, REPO)
        print(f"{rel}:{lineno}: {code} {msg}")
    if all_findings:
        print(f"\n{len(all_findings)} finding(s) in {n_files} files")
        return 1
    print(f"lint clean: {n_files} files")
    return 0


if __name__ == "__main__":
    sys.exit(main())
