"""Import a HuggingFace T5 checkpoint into the native format.

Same contract as tools/convert_hf_gpt2.py: params-only orbax checkpoint +
model.yaml, consumable via Engine.save_load.pretrained_params (train) or
ckpt_dir (export/inference).  Logits parity with transformers is covered
by tests/test_hf_convert.py.

Usage:
  python tools/convert_hf_t5.py --model /path/to/hf_t5_dir -o out/t5
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True, help="HF model dir (local)")
    ap.add_argument("-o", "--out", required=True)
    args = ap.parse_args(argv)

    from transformers import T5ForConditionalGeneration

    from paddlefleetx_tpu.models.t5.convert import (
        convert_hf_t5_state_dict,
        hf_t5_config,
    )

    m = T5ForConditionalGeneration.from_pretrained(args.model)
    cfg = hf_t5_config(m.config)
    params = convert_hf_t5_state_dict(m.state_dict(), cfg)

    import orbax.checkpoint as ocp

    out = os.path.abspath(args.out)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(out, "params"), params, force=True)
    ckptr.wait_until_finished()
    with open(os.path.join(out, "meta.json"), "w") as f:
        json.dump({"format": "params-only", "source": f"hf-t5:{args.model}"}, f)
    with open(os.path.join(out, "model.yaml"), "w") as f:
        f.write(
            "Model:\n"
            "  module: T5Module\n"
            f"  vocab_size: {cfg.vocab_size}\n"
            f"  d_model: {cfg.d_model}\n"
            f"  d_kv: {cfg.d_kv}\n"
            f"  d_ff: {cfg.d_ff}\n"
            f"  num_layers: {cfg.num_layers}\n"
            f"  num_decoder_layers: {cfg.num_decoder_layers}\n"
            f"  num_heads: {cfg.num_heads}\n"
            f"  relative_attention_num_buckets: {cfg.relative_attention_num_buckets}\n"
            f"  relative_attention_max_distance: {cfg.relative_attention_max_distance}\n"
            f"  feed_forward_proj: {cfg.feed_forward_proj}\n"
            f"  tie_word_embeddings: {cfg.tie_word_embeddings}\n"
            f"  pad_token_id: {cfg.pad_token_id}\n"
            f"  eos_token_id: {cfg.eos_token_id}\n"
            f"  decoder_start_token_id: {cfg.decoder_start_token_id}\n"
        )
    print(f"converted -> {out}")


if __name__ == "__main__":
    main()
