"""Import a HuggingFace T5 checkpoint into the native format.

Same contract as tools/convert_hf_gpt2.py: params-only orbax checkpoint +
model.yaml, consumable via Engine.save_load.pretrained_params (train) or
ckpt_dir (export/inference).  Logits parity with transformers is covered
by tests/test_hf_convert.py.

Usage:
  python tools/convert_hf_t5.py --model /path/to/hf_t5_dir -o out/t5
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True, help="HF model dir (local)")
    ap.add_argument("-o", "--out", required=True)
    args = ap.parse_args(argv)

    from transformers import T5ForConditionalGeneration

    from paddlefleetx_tpu.models.t5.convert import (
        convert_hf_t5_state_dict,
        hf_t5_config,
    )

    m = T5ForConditionalGeneration.from_pretrained(args.model)
    cfg = hf_t5_config(m.config)
    params = convert_hf_t5_state_dict(m.state_dict(), cfg)

    from paddlefleetx_tpu.utils.checkpoint import save_params_checkpoint

    out = save_params_checkpoint(
        args.out,
        params,
        f"hf-t5:{args.model}",
        {
            "module": "T5Module",
            "vocab_size": cfg.vocab_size,
            "d_model": cfg.d_model,
            "d_kv": cfg.d_kv,
            "d_ff": cfg.d_ff,
            "num_layers": cfg.num_layers,
            "num_decoder_layers": cfg.num_decoder_layers,
            "num_heads": cfg.num_heads,
            "relative_attention_num_buckets": cfg.relative_attention_num_buckets,
            "relative_attention_max_distance": cfg.relative_attention_max_distance,
            "feed_forward_proj": cfg.feed_forward_proj,
            "tie_word_embeddings": cfg.tie_word_embeddings,
            "pad_token_id": cfg.pad_token_id,
            "eos_token_id": cfg.eos_token_id,
            "decoder_start_token_id": cfg.decoder_start_token_id,
        },
    )
    print(f"converted -> {out}")


if __name__ == "__main__":
    main()
