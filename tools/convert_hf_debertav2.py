"""Import a HuggingFace DebertaV2 checkpoint into the native format.

Same contract as tools/convert_hf_gpt2.py: params-only orbax checkpoint +
model.yaml.  Hidden-state parity with transformers is covered by
tests/test_hf_convert.py (valid positions; HF pads differ by design).

Usage:
  python tools/convert_hf_debertav2.py --model /path/to/hf_deberta -o out/dv2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True, help="HF model dir (local)")
    ap.add_argument("-o", "--out", required=True)
    args = ap.parse_args(argv)

    from transformers import DebertaV2Model

    from paddlefleetx_tpu.models.debertav2.convert import (
        convert_hf_debertav2_state_dict,
        hf_debertav2_config,
    )

    m = DebertaV2Model.from_pretrained(args.model)
    cfg = hf_debertav2_config(m.config)
    params = convert_hf_debertav2_state_dict(m.state_dict(), cfg)

    from paddlefleetx_tpu.utils.checkpoint import save_params_checkpoint

    out = save_params_checkpoint(
        args.out,
        params,
        f"hf-debertav2:{args.model}",
        {
            "module": "DebertaV2Module",
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_attention_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_position_embeddings": cfg.max_position_embeddings,
            "relative_attention": cfg.relative_attention,
            "position_buckets": cfg.position_buckets,
            "max_relative_positions": cfg.max_relative_positions,
            "pos_att_type": list(cfg.pos_att_type),
            "conv_kernel_size": cfg.conv_kernel_size,
            "pad_token_id": cfg.pad_token_id,
        },
    )
    print(f"converted -> {out}")


if __name__ == "__main__":
    main()
