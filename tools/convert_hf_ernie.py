"""Import a HuggingFace ERNIE checkpoint into the native format.

Same contract as tools/convert_hf_gpt2.py: params-only orbax checkpoint +
model.yaml.  Hidden-state/pooled/MLM/NSP parity with transformers is
covered by tests/test_hf_convert.py.

Usage:
  python tools/convert_hf_ernie.py --model /path/to/hf_ernie -o out/ernie
      [--pretraining]   # load ErnieForPreTraining (maps MLM/NSP heads)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True, help="HF model dir (local)")
    ap.add_argument("-o", "--out", required=True)
    ap.add_argument("--pretraining", action="store_true")
    ap.add_argument(
        "--num-classes", type=int, default=0,
        help="emit a fresh zero cls_head this wide (for seq-cls finetuning)",
    )
    args = ap.parse_args(argv)

    from paddlefleetx_tpu.models.ernie.convert import (
        convert_hf_ernie_state_dict,
        hf_ernie_config,
    )

    if args.pretraining:
        from transformers import ErnieForPreTraining

        m = ErnieForPreTraining.from_pretrained(args.model)
    else:
        from transformers import ErnieModel

        m = ErnieModel.from_pretrained(args.model)
    cfg = hf_ernie_config(m.config, num_classes=args.num_classes)
    params = convert_hf_ernie_state_dict(m.state_dict(), cfg)

    from paddlefleetx_tpu.utils.checkpoint import save_params_checkpoint

    out = save_params_checkpoint(
        args.out,
        params,
        f"hf-ernie:{args.model}",
        {
            "module": "ErnieModule",
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_attention_heads,
            "ffn_hidden_size": cfg.ffn_hidden_size,
            "max_position_embeddings": cfg.max_position_embeddings,
            "type_vocab_size": cfg.type_vocab_size,
            "pad_token_id": cfg.pad_token_id,
            "num_classes": cfg.num_classes,
            "gelu_approximate": cfg.gelu_approximate,
        },
    )
    print(f"converted -> {out}")


if __name__ == "__main__":
    main()
