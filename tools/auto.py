"""Auto-parallel entry point + parallel-strategy tuner.

Re-design of the reference AutoEngine path (tools/auto.py:40-69 +
core/engine/auto_engine.py: fit :104, tune :146).  Under pjit/GSPMD the
"semi-auto parallel static graph" IS the normal path — `fit` here is
train.py's loop — so the part worth keeping is `tune()`: the reference
delegates to Paddle's parallel-strategy tuner; the TPU equivalent is a
mesh-layout sweep, timing a few real steps per candidate layout and
picking the highest tokens/s.

Usage:
  python tools/auto.py -c configs/gpt/pretrain_gpt_345M_single.yaml          # = train
  python tools/auto.py -c ... --tune [--tune-steps 8]                        # sweep
      [-o overrides...]   candidates: Tuning.candidates (list of
      {dp,mp,pp,sharding,sep} dicts) or auto-enumerated factorizations.

The sweep runs each candidate as a tools/train.py subprocess (fresh XLA
per layout) and writes auto_tune_results.json next to the config output.
"""

import argparse
import json
import os
import re
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlefleetx_tpu.utils.device import apply_platform_env

apply_platform_env()  # PFX_PLATFORM=cpu etc., before backend init

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
IPS_RE = re.compile(r"ips: ([\d,]+) tokens/s")


def enumerate_layouts(n_devices: int, max_candidates: int = 12):
    """Divisor factorizations n = dp * mp * pp (sharding folded into dp
    slot as a variant); smallest-mp-first so cheap layouts run first.

    Beyond pure layout, the grammar covers the execution knobs the
    reference tuner sweeps (auto Strategy tuning blocks, reference
    utils/config.py:515-590) and that docs/performance_tuning.md measures
    as dominant: recompute granularity, gradient accumulation, and
    precision mode — attached as variants of the leading layout."""
    outs = []
    for mp in [d for d in (1, 2, 4, 8) if n_devices % d == 0]:
        rest = n_devices // mp
        for pp in [d for d in (1, 2, 4) if rest % d == 0]:
            dp = rest // pp
            outs.append({"dp": dp, "mp": mp, "pp": pp})
            if dp > 1 and pp == 1:
                outs.append({"dp": 1, "mp": mp, "pp": 1, "sharding": dp})
    # non-layout knobs on the first (cheapest) layout: recompute trades
    # HBM for FLOPs, accumulate trades HBM for step latency, amp halves
    # the matmul cost — these frequently beat a layout change
    if outs:
        base = outs[0]
        outs[1:1] = [
            dict(base, recompute="selective"),
            dict(base, recompute="full"),
            dict(base, accumulate=2),
            dict(base, amp="bf16"),
            # bf16 grads: frees one param-size fp32 buffer per microbatch
            # accumulator (engine main_grad, measured 1.3B-fit lever)
            dict(base, amp="bf16", main_grad=False),
            # no fp32 masters at all: THE memory knob for models that
            # otherwise do not fit the chip (bf16 params + moments)
            dict(base, amp="bf16", main_grad=False, multi_precision=False),
        ]
    seen, uniq = set(), []
    for c in outs:
        key = tuple(sorted(c.items()))
        if key not in seen:
            seen.add(key)
            uniq.append(c)
    # the cap is authoritative: callers bound sweep wall-time by it, so
    # the knob variants spend slots WITHIN max_candidates (they sit right
    # after the lead layout, so they survive truncation and tail layout
    # factorizations drop first) — and a truncated grid must say so, not
    # silently report a "best" from an incomplete sweep
    if len(uniq) > max_candidates:
        print(
            f"tuner grid truncated: {len(uniq)} candidates -> "
            f"{max_candidates} (raise max_candidates to sweep all)",
            file=sys.stderr,
        )
    return uniq[:max_candidates]


def overrides_for(c: dict, global_batch: int) -> list:
    dp_world = c.get("dp", 1) * c.get("sharding", 1)
    local = max(global_batch // dp_world, 1)
    accum = max(int(c.get("accumulate", 1)), 1)
    if local % accum:
        # a non-dividing factor would either fail config validation or run
        # a different accumulation than the row reports — reject up front
        raise ValueError(
            f"accumulate={accum} does not divide local batch {local}"
        )
    micro = max(local // accum, 1)
    ov = [
        f"Distributed.dp_degree={c.get('dp', 1)}",
        f"Distributed.mp_degree={c.get('mp', 1)}",
        f"Distributed.pp_degree={c.get('pp', 1)}",
        f"Global.local_batch_size={local}",
        f"Global.micro_batch_size={micro}",
    ]
    if c.get("sharding"):
        ov += [
            f"Distributed.sharding.sharding_degree={c['sharding']}",
            f"Distributed.sharding.sharding_stage={int(c.get('sharding_stage', 2))}",
        ]
    if c.get("sep"):
        ov.append(f"Distributed.sep_degree={c['sep']}")
    if c.get("attn") is not None:
        # flash vs ring(+zigzag) is the lever long-context configs sweep
        ov.append(f"Model.attn_impl={c['attn']}")
    if c.get("zigzag") is not None:
        ov.append(f"Distributed.sep_zigzag={bool(c['zigzag'])}")
    if c.get("recompute") is not None:
        if c["recompute"] in (False, "none", "off"):
            ov.append("Model.use_recompute=False")
        else:
            ov += [
                "Model.use_recompute=True",
                f"Model.recompute_granularity={c['recompute']}",
            ]
    if c.get("amp") is not None:
        if c["amp"] in (False, "fp32", "off"):
            ov.append("Engine.mix_precision.enable=False")
        else:
            dtype = {"bf16": "bfloat16", "fp16": "float16"}.get(c["amp"], c["amp"])
            ov += [
                "Engine.mix_precision.enable=True",
                f"Engine.mix_precision.dtype={dtype}",
            ]
    if c.get("main_grad") is not None:
        ov.append(f"Engine.mix_precision.main_grad={bool(c['main_grad'])}")
    if c.get("multi_precision") is not None:
        ov.append(f"Optimizer.multi_precision={bool(c['multi_precision'])}")
    return ov


def run_candidate(config: str, base_overrides: list, cand: dict, tune_steps: int, global_batch: int):
    try:
        cand_overrides = overrides_for(cand, global_batch)
    except ValueError as e:
        return {"layout": cand, "ok": False, "ips": None, "error": str(e)}
    cmd = [sys.executable, os.path.join(ROOT, "tools", "train.py"), "-c", config]
    for o in base_overrides + cand_overrides + [
        f"Engine.max_steps={tune_steps}",
        "Engine.logging_freq=2",
        "Engine.eval_freq=0",
        "Engine.save_load.save_steps=0",
    ]:
        cmd += ["-o", o]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=900)
        log = proc.stdout + proc.stderr
        ips = [float(m.group(1).replace(",", "")) for m in IPS_RE.finditer(log)]
        return {"layout": cand, "ok": proc.returncode == 0 and bool(ips),
                "ips": ips[-1] if ips else None}
    except subprocess.TimeoutExpired:
        return {"layout": cand, "ok": False, "ips": None}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-c", "--config", required=True)
    ap.add_argument("-o", "--override", action="append", default=[])
    ap.add_argument("--tune", action="store_true")
    ap.add_argument("--tune-steps", type=int, default=8)
    args = ap.parse_args(argv)

    if not args.tune:
        # fit: pjit IS the auto-parallel engine — same loop as train.py
        from tools.train import main as train_main

        return train_main(["-c", args.config] + sum([["-o", o] for o in args.override], []))

    from paddlefleetx_tpu.utils.config import get_config

    cfg = get_config(args.config, overrides=args.override)
    import jax

    n = jax.device_count()
    cands = cfg.get("Tuning", {}).get("candidates") or enumerate_layouts(n)
    gbs = int(cfg.Global.global_batch_size)
    print(f"tuning over {len(cands)} layouts on {n} devices (steps={args.tune_steps})")
    results = []
    for cand in cands:
        r = run_candidate(args.config, args.override, cand, args.tune_steps, gbs)
        results.append(r)
        print(json.dumps(r))
    ok = [r for r in results if r["ok"]]
    out_path = os.path.join(
        cfg.get("Engine", {}).get("save_load", {}).get("output_dir", "."), "auto_tune_results.json"
    )
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    if not ok:
        print("no layout succeeded", file=sys.stderr)
        sys.exit(1)
    best = max(ok, key=lambda r: r["ips"])
    print(f"best layout: {json.dumps(best['layout'])} @ {best['ips']:,.0f} tokens/s")
    print(f"results -> {out_path}")


if __name__ == "__main__":
    main()
