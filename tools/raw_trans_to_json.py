"""Convert raw text files to the jsonl corpus format.

Re-design of the reference preprocessing step
(ppfleetx/data/data_tools/gpt/raw_trans_to_json.py): every input text file
becomes json lines {"text": ...}, one document per blank-line-separated
block (or per line with --per-line).

Usage:
  python tools/raw_trans_to_json.py --input_path dir_or_file --output_path out.jsonl
"""

import argparse
import glob
import json
import os


def iter_docs(path: str, per_line: bool):
    with open(path, errors="ignore") as f:
        if per_line:
            for line in f:
                line = line.strip()
                if line:
                    yield line
            return
        block = []
        for line in f:
            if line.strip():
                block.append(line.strip())
            elif block:
                yield " ".join(block)
                block = []
        if block:
            yield " ".join(block)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--input_path", required=True, help="file, dir, or glob")
    ap.add_argument("--output_path", required=True)
    ap.add_argument("--per-line", action="store_true", help="one doc per line")
    args = ap.parse_args(argv)

    if os.path.isdir(args.input_path):
        files = sorted(glob.glob(os.path.join(args.input_path, "**/*"), recursive=True))
        files = [f for f in files if os.path.isfile(f)]
    else:
        files = sorted(glob.glob(args.input_path)) or [args.input_path]

    n = 0
    os.makedirs(os.path.dirname(os.path.abspath(args.output_path)), exist_ok=True)
    with open(args.output_path, "w") as out:
        for path in files:
            for doc in iter_docs(path, args.per_line):
                out.write(json.dumps({"text": doc}, ensure_ascii=False) + "\n")
                n += 1
    print(f"wrote {n} documents from {len(files)} files -> {args.output_path}")


if __name__ == "__main__":
    main()
