"""Data layer: datasets, samplers, loaders, tokenizers, native index helpers.

Importing this package registers all built-in datasets/samplers (replacing
the reference's eval()-based name dispatch, data/__init__.py:69-119).
"""

from paddlefleetx_tpu.data import ernie_dataset as _ernie_dataset  # noqa: F401 (registers)
from paddlefleetx_tpu.data import glue_dataset as _glue_dataset  # noqa: F401 (registers)
from paddlefleetx_tpu.data import gpt_dataset as _gpt_dataset  # noqa: F401 (registers)
from paddlefleetx_tpu.data import mlm_dataset as _mlm_dataset  # noqa: F401 (registers)
from paddlefleetx_tpu.data import multimodal_dataset as _multimodal_dataset  # noqa: F401 (registers)
from paddlefleetx_tpu.data import protein_dataset as _protein_dataset  # noqa: F401 (registers)
from paddlefleetx_tpu.data import t5_dataset as _t5_dataset  # noqa: F401 (registers)
from paddlefleetx_tpu.data import vision_dataset as _vision_dataset  # noqa: F401 (registers)
from paddlefleetx_tpu.data.batch_sampler import (  # noqa: F401
    DataLoader,
    DistributedBatchSampler,
    collate_stack,
)
from paddlefleetx_tpu.data.builders import build_dataloader, build_dataset  # noqa: F401
