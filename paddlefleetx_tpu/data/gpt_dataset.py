"""Megatron-style GPT pretraining dataset over mmap token files.

Reference: ppfleetx/data/dataset/gpt_dataset.py:42-465 (GPTDataset).  Data
format: ``{prefix}_ids.npy`` — all documents' tokens concatenated (uint16/
uint32); ``{prefix}_idx.npz`` — document token lengths (key ``lens``).
Samples are fixed ``seq_length`` windows walked across shuffled documents;
index maps (doc_idx / sample_idx / shuffle_idx) are built once and cached
as .npy beside the data (atomic writes + a cross-process build lock +
validated loads with quarantine-on-corruption — data/index_cache.py).
Each item yields tokens / position_ids / labels / loss_mask (:153-171).

EPOCH-KEYED maps (a deliberate departure from the reference, which sizes
every map by the requested ``num_samples`` = max_steps x batch): each
epoch's doc order, window walk, and shuffle are derived independently from
``(seed, epoch)``, and sample ``i`` lives in epoch ``i //
samples_per_epoch``.  Extending ``max_steps`` therefore APPENDS epochs
without reshuffling history — sample ``i`` is the same tokens no matter
how long the run is — which is what makes checkpoint-resume and
rollback-rewind replay (docs/data_pipeline.md) stable across config
changes.  The cache key fingerprints dataset + split + seed + seq_length
+ num_epochs, never num_samples.

Also here: LM_Eval_Dataset (overlapping-window perplexity eval, reference
:484) and Lambada_Eval_Dataset (:589) used by the GPT eval module.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlefleetx_tpu.data.index_cache import (
    index_map_lock,
    load_index_cache,
    save_index_cache,
)
from paddlefleetx_tpu.data.indexed import (
    build_blending_indices,
    build_sample_idx,
)
from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.registry import DATASETS


def _split_docs(num_docs: int, split: Sequence[float]):
    """Train/valid/test doc ranges from fractions (reference :95-116)."""
    split = np.asarray(split, dtype=np.float64)
    split = split / split.sum()
    bounds = np.concatenate([[0], np.cumsum(split)])
    edges = (bounds * num_docs).astype(np.int64)
    return [(int(edges[i]), int(edges[i + 1])) for i in range(len(split))]


def _mode_doc_range(num_docs: int, split: Sequence[float], mode: str):
    """Doc range for a mode, falling back to all docs on a degenerate split."""
    lo, hi = _split_docs(num_docs, split)[GPTDataset.MODES[mode]]
    if hi <= lo:
        lo, hi = 0, num_docs
    return lo, hi


@DATASETS.register("GPTDataset")
class GPTDataset:
    MODES = {"Train": 0, "Eval": 1, "Test": 2}

    def __init__(
        self,
        input_dir: str = None,
        data_prefix: str = None,
        split: Sequence[float] = (949, 50, 1),
        max_seq_len: int = 1024,
        num_samples: int = None,
        mode: str = "Train",
        seed: int = 1234,
        build_cache: bool = True,
        **_unused,
    ):
        if data_prefix is None:
            files = sorted(
                f[: -len("_ids.npy")]
                for f in os.listdir(input_dir)
                if f.endswith("_ids.npy")
            )
            if not files:
                raise FileNotFoundError(f"no *_ids.npy under {input_dir}")
            if len(files) > 1:
                logger.warning(
                    f"{input_dir} holds {len(files)} corpora; GPTDataset uses "
                    f"'{files[0]}' only — use BlendedGPTDataset to mix them"
                )
            data_prefix = os.path.join(input_dir, files[0])
        self.prefix = data_prefix
        self.seq_len = int(max_seq_len)
        self.mode = mode

        self.tokens = np.load(data_prefix + "_ids.npy", mmap_mode="r")
        idx = np.load(data_prefix + "_idx.npz")
        lens = idx["lens"].astype(np.int32)
        self.doc_offsets = np.concatenate([[0], np.cumsum(lens.astype(np.int64))])

        lo, hi = _mode_doc_range(len(lens), split, mode)
        self.doc_lo = lo
        self.docs = np.arange(lo, hi, dtype=np.int32)
        self.sizes = lens[lo:hi]
        self.seed = int(seed)
        tokens_per_epoch = int(self.sizes.sum())
        self.tokens_per_epoch = tokens_per_epoch
        # windows are cut WITHIN an epoch's token stream (each window needs
        # seq_len+1 tokens; the +1 label overlaps the next window's first
        # token, Megatron-style), so per-epoch maps are independent of how
        # many epochs the run ultimately needs
        samples_per_epoch = (tokens_per_epoch - 1) // self.seq_len
        if samples_per_epoch < 1:
            raise ValueError(
                f"GPTDataset[{mode}]: split holds {tokens_per_epoch} tokens "
                f"— not one seq_len={self.seq_len}+1 window; shrink "
                "max_seq_len or feed a bigger corpus/split"
            )
        self.samples_per_epoch = samples_per_epoch
        if num_samples is None:
            num_samples = samples_per_epoch
        self.num_samples = int(num_samples)
        num_epochs = max(
            1, -(-self.num_samples // samples_per_epoch)  # ceil div
        )
        self.num_epochs = num_epochs

        # cache key fingerprints the actual doc lengths + split + seed +
        # seq_length + EPOCH COUNT — deliberately NOT num_samples: epoch
        # maps are built independently per (seed, epoch), so a longer run
        # reuses the identical history and merely appends epochs (a
        # regenerated corpus or changed split still can't reuse stale maps)
        hasher = hashlib.md5(
            json.dumps(
                [mode, self.seq_len, "epochs", num_epochs, self.seed,
                 list(map(float, split))]
            ).encode()
        )
        hasher.update(self.sizes.tobytes())
        cache = f"{data_prefix}_{mode.lower()}_{hasher.hexdigest()[:10]}"
        expect = {
            "doc_idx": ((num_epochs, len(self.sizes)), np.int32),
            "sample_idx": ((num_epochs, samples_per_epoch + 1, 2), np.int32),
            "shuffle_idx": ((num_epochs, samples_per_epoch), np.int32),
        }

        maps = load_index_cache(cache, expect) if build_cache else None
        if maps is None:
            if build_cache:
                # one builder per cache prefix across processes; waiters
                # re-check after acquiring so exactly one pays the build
                with index_map_lock(cache):
                    maps = load_index_cache(cache, expect)
                    if maps is None:
                        maps = self._build_epoch_maps(num_epochs)
                        save_index_cache(cache, maps)
            else:
                maps = self._build_epoch_maps(num_epochs)
        self.doc_idx = maps["doc_idx"]
        self.sample_idx = maps["sample_idx"]
        self.shuffle_idx = maps["shuffle_idx"]
        logger.info(
            f"GPTDataset[{mode}] docs={len(self.sizes)} epochs={num_epochs} "
            f"samples={self.num_samples} ({samples_per_epoch}/epoch) "
            f"seq={self.seq_len}"
        )

    def _build_epoch_maps(self, num_epochs: int) -> Dict[str, np.ndarray]:
        """Build doc/sample/shuffle maps for ``num_epochs`` epochs, each
        derived independently from ``(seed, epoch)`` — epoch e's maps are
        identical no matter how many later epochs exist."""
        n_docs = len(self.sizes)
        spe = self.samples_per_epoch
        doc_idx = np.empty((num_epochs, n_docs), dtype=np.int32)
        sample_idx = np.empty((num_epochs, spe + 1, 2), dtype=np.int32)
        shuffle_idx = np.empty((num_epochs, spe), dtype=np.int32)
        for e in range(num_epochs):
            rng = np.random.default_rng([self.seed, e])
            doc_idx[e] = rng.permutation(n_docs).astype(np.int32)
            sample_idx[e] = build_sample_idx(
                self.sizes, doc_idx[e], self.seq_len, 1, self.tokens_per_epoch
            )
            shuffle_idx[e] = rng.permutation(spe).astype(np.int32)
        return {
            "doc_idx": doc_idx,
            "sample_idx": sample_idx,
            "shuffle_idx": shuffle_idx,
        }

    def __len__(self) -> int:
        return self.num_samples

    def _doc_tokens(self, doc: int, start: int, end: Optional[int] = None) -> np.ndarray:
        g = self.doc_lo + doc  # global doc id
        a = self.doc_offsets[g] + start
        b = self.doc_offsets[g + 1] if end is None else self.doc_offsets[g] + end
        return self.tokens[a:b]

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        epoch, j = divmod(int(idx) % self.num_samples, self.samples_per_epoch)
        j = int(self.shuffle_idx[epoch, j])
        doc_row = self.doc_idx[epoch]
        sample_row = self.sample_idx[epoch]
        di_first, off_first = sample_row[j]
        di_last, off_last = sample_row[j + 1]
        parts: List[np.ndarray] = []
        if di_first == di_last:
            parts.append(
                self._doc_tokens(doc_row[di_first], off_first, off_last + 1)
            )
        else:
            parts.append(self._doc_tokens(doc_row[di_first], off_first))
            for di in range(di_first + 1, di_last):
                parts.append(self._doc_tokens(doc_row[di], 0))
            parts.append(self._doc_tokens(doc_row[di_last], 0, off_last + 1))
        seq = np.concatenate(parts).astype(np.int64)
        assert len(seq) == self.seq_len + 1, (len(seq), self.seq_len)
        return {
            "tokens": seq[:-1],
            "labels": seq[1:],
            "loss_mask": np.ones(self.seq_len, dtype=np.float32),
            "position_ids": np.arange(self.seq_len, dtype=np.int64),
        }


def _natural_samples(prefix: str, split: Sequence[float], mode: str, seq_len: int) -> int:
    """One-epoch sample count for a corpus split, from the lens file alone
    (no index-map build needed; same formula as GPTDataset.__init__)."""
    lens = np.load(prefix + "_idx.npz")["lens"].astype(np.int64)
    lo, hi = _mode_doc_range(len(lens), split, mode)
    toks = int(lens[lo:hi].sum())
    return max((toks - 1) // seq_len, 1)


@DATASETS.register("BlendedGPTDataset")
class BlendedGPTDataset:
    """Weighted mixture of GPT corpora (reference multi-dataset blending,
    fast_index_map_helpers.cpp build_blending_indices :693-697): sample i
    is drawn from the dataset whose emitted fraction lags its weight most,
    giving a deterministic interleave that matches the weights exactly in
    the limit.

    Config: ``data_prefixes`` (list of mmap prefixes) or ``input_dir``
    (every ``*_ids.npy`` found is a component); optional ``weights``
    (defaults to size-proportional — equivalent to concatenation odds).
    """

    def __init__(
        self,
        input_dir: str = None,
        data_prefixes: Optional[Sequence[str]] = None,
        weights: Optional[Sequence[float]] = None,
        split: Sequence[float] = (949, 50, 1),
        max_seq_len: int = 1024,
        num_samples: int = None,
        mode: str = "Train",
        seed: int = 1234,
        build_cache: bool = True,
        **_unused,
    ):
        if data_prefixes is None:
            files = sorted(
                f[: -len("_ids.npy")]
                for f in os.listdir(input_dir)
                if f.endswith("_ids.npy")
            )
            if not files:
                raise FileNotFoundError(f"no *_ids.npy under {input_dir}")
            data_prefixes = [os.path.join(input_dir, f) for f in files]
        if len(data_prefixes) < 1:
            raise ValueError("BlendedGPTDataset needs >=1 data_prefixes")

        # natural (one-epoch) sizes are only needed for defaulted weights
        # or num_samples — skip the N idx-file loads when both are explicit
        naturals = None
        if weights is None or num_samples is None:
            naturals = [
                _natural_samples(p, split, mode, int(max_seq_len))
                for p in data_prefixes
            ]
        if weights is None:
            weights = [float(n) for n in naturals]
        if len(weights) != len(data_prefixes):
            raise ValueError(
                f"{len(weights)} weights for {len(data_prefixes)} datasets"
            )
        w = np.asarray(weights, dtype=np.float64)
        if (w <= 0).any():
            raise ValueError(f"weights must be positive, got {weights}")
        w = w / w.sum()
        if num_samples is None:
            num_samples = int(sum(naturals))
        self.num_samples = int(num_samples)

        # each component must be able to serve its share (+0.5% slack, the
        # reference's margin for the greedy interleave running slightly hot)
        self.children = [
            GPTDataset(
                data_prefix=p,
                split=split,
                max_seq_len=max_seq_len,
                num_samples=int(np.ceil(self.num_samples * wi * 1.005)) + 1,
                mode=mode,
                seed=seed + 31 * i,
                build_cache=build_cache,
            )
            for i, (p, wi) in enumerate(zip(data_prefixes, w))
        ]
        self.ds_index, self.ds_sample = build_blending_indices(w, self.num_samples)
        logger.info(
            f"BlendedGPTDataset[{mode}] {len(self.children)} corpora, "
            f"weights={np.round(w, 4).tolist()}, samples={self.num_samples}"
        )

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        i = idx % self.num_samples
        return self.children[int(self.ds_index[i])][int(self.ds_sample[i])]


@DATASETS.register("LM_Eval_Dataset")
class LMEvalDataset:
    """Overlapping-window LM perplexity eval (reference gpt_dataset.py:484):
    windows of seq_len stride ``overlapping_eval``; only new tokens counted
    in the loss mask."""

    def __init__(
        self, tokens: np.ndarray, seq_len: int = 1024, overlapping_eval: int = 32, **_
    ):
        self.tokens = np.asarray(tokens, dtype=np.int64)
        self.seq_len = seq_len
        self.stride = overlapping_eval
        total = len(self.tokens)
        self.num = max(1, 1 + max(0, (total - seq_len - 1 + self.stride - 1) // self.stride))

    def __len__(self):
        return self.num

    def __getitem__(self, i: int):
        start = i * self.stride
        seq = self.tokens[start : start + self.seq_len + 1]
        pad = self.seq_len + 1 - len(seq)
        if pad:
            seq = np.concatenate([seq, np.zeros(pad, np.int64)])
        mask = np.ones(self.seq_len, np.float32)
        if pad:
            mask[-pad:] = 0.0
        if i > 0:  # only the non-overlapping tail counts
            mask[: self.seq_len - self.stride] = 0.0
        return {
            "tokens": seq[:-1],
            "labels": seq[1:],
            "loss_mask": mask,
            "position_ids": np.arange(self.seq_len, dtype=np.int64),
        }


@DATASETS.register("Lambada_Eval_Dataset")
class LambadaEvalDataset:
    """LAMBADA last-word accuracy (reference gpt_dataset.py:589): loss mask
    covers only the target-word tokens."""

    def __init__(self, examples, seq_len: int = 1024, **_):
        # examples: list of (context_token_ids, target_token_ids)
        self.examples = examples
        self.seq_len = seq_len

    def __len__(self):
        return len(self.examples)

    def __getitem__(self, i: int):
        ctx, tgt = self.examples[i]
        seq = np.concatenate([ctx, tgt]).astype(np.int64)[: self.seq_len + 1]
        pad = self.seq_len + 1 - len(seq)
        if pad:
            seq = np.concatenate([seq, np.zeros(pad, np.int64)])
        mask = np.zeros(self.seq_len, np.float32)
        lo = max(len(ctx) - 1, 0)
        hi = min(len(ctx) - 1 + len(tgt), self.seq_len)
        mask[lo:hi] = 1.0
        return {
            "tokens": seq[:-1],
            "labels": seq[1:],
            "loss_mask": mask,
            "position_ids": np.arange(self.seq_len, dtype=np.int64),
        }


def write_synthetic_corpus(
    prefix: str, vocab_size: int = 50304, num_docs: int = 64, mean_len: int = 600, seed: int = 0
) -> str:
    """Generate a tiny corpus in the mmap format (for tests and benches)."""
    parent = os.path.dirname(os.path.abspath(prefix))
    os.makedirs(parent, exist_ok=True)
    rng = np.random.default_rng(seed)
    lens = rng.integers(mean_len // 2, mean_len * 2, num_docs).astype(np.int32)
    # Zipf-ish unigram distribution: gives the model learnable structure
    # (uniform data would make ln(vocab) the optimum — useless for loss-drop
    # tests and unrepresentative for benches)
    probs = 1.0 / (np.arange(vocab_size) + 5.0)
    probs /= probs.sum()
    tokens = rng.choice(vocab_size, size=int(lens.sum()), p=probs).astype(
        np.uint16 if vocab_size < 2**16 else np.uint32
    )
    np.save(prefix + "_ids.npy", tokens)
    np.savez(prefix + "_idx.npz", lens=lens)
    return prefix
