"""Protein folding dataset: featurized training examples.

The reference repo has no protein data pipeline (deferred to the upstream
HelixFold app); this dataset completes the training path.  Two modes:

* ``input_dir`` — load pre-featurized ``.npz`` examples (one per protein,
  AlphaFold feature naming; see FEATURES below).
* synthetic (default) — geometrically consistent random proteins: a
  self-avoiding CA random walk with ~3.8 A steps, ideal N/C/O/CB placed in
  each backbone frame, random MSA with BERT-style masking, and (optionally)
  templates derived from the noisy ground truth.  This is the smoke/parity
  path (the same role SyntheticClsDataset plays for vision).

All examples are padded/cropped to ``num_res`` residues, ``num_msa`` MSA
rows, ``num_extra_msa`` extra rows and ``num_templates`` templates so jit
shapes are static.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from paddlefleetx_tpu.utils.registry import DATASETS

FEATURES = [
    "aatype", "residue_index", "seq_mask", "target_feat", "msa_feat",
    "msa_mask", "true_msa", "bert_mask", "extra_msa", "extra_has_deletion",
    "extra_deletion_value", "extra_msa_mask", "all_atom_positions",
    "all_atom_mask", "template_aatype", "template_all_atom_positions",
    "template_all_atom_masks", "template_pseudo_beta",
    "template_pseudo_beta_mask", "template_mask",
]

# atom37 indices of the backbone atoms (residue_constants.atom_order)
_N, _CA, _C, _CB, _O = 0, 1, 2, 3, 4
_IDEAL = {
    _N: np.array([-0.525, 1.363, 0.000], np.float32),
    _C: np.array([1.526, 0.000, 0.000], np.float32),
    _CB: np.array([-0.529, -0.774, -1.205], np.float32),
    _O: np.array([2.153, -1.062, 0.000], np.float32),
}


def _random_backbone(rng: np.random.Generator, n: int) -> np.ndarray:
    """CA trace random walk with 3.8 A steps and mild direction persistence."""
    steps = rng.normal(size=(n, 3)).astype(np.float32)
    for i in range(1, n):
        steps[i] = 0.6 * steps[i - 1] + 0.8 * steps[i]
    steps /= np.linalg.norm(steps, axis=-1, keepdims=True) + 1e-8
    ca = np.cumsum(3.8 * steps, axis=0)
    return ca - ca.mean(0)


def _frames_from_ca(ca: np.ndarray) -> np.ndarray:
    """Orthonormal frame per residue from the CA trace tangents."""
    n = len(ca)
    e0 = np.zeros((n, 3), np.float32)
    e0[:-1] = ca[1:] - ca[:-1]
    e0[-1] = e0[-2]
    e0 /= np.linalg.norm(e0, axis=-1, keepdims=True) + 1e-8
    up = np.tile(np.array([0.0, 0.0, 1.0], np.float32), (n, 1))
    e1 = up - np.sum(up * e0, -1, keepdims=True) * e0
    # degenerate when the tangent is near +-z
    bad = np.linalg.norm(e1, axis=-1) < 1e-3
    e1[bad] = np.array([0.0, 1.0, 0.0], np.float32)
    e1 /= np.linalg.norm(e1, axis=-1, keepdims=True) + 1e-8
    e2 = np.cross(e0, e1)
    return np.stack([e0, e1, e2], axis=-1)  # [n, 3, 3] columns


def synthesize_protein(
    rng: np.random.Generator,
    num_res: int,
    num_msa: int,
    num_extra_msa: int,
    num_templates: int,
) -> Dict[str, np.ndarray]:
    aatype = rng.integers(0, 20, num_res).astype(np.int32)
    ca = _random_backbone(rng, num_res)
    rot = _frames_from_ca(ca)

    pos = np.zeros((num_res, 37, 3), np.float32)
    mask = np.zeros((num_res, 37), np.float32)
    pos[:, _CA] = ca
    mask[:, [_N, _CA, _C, _O]] = 1.0
    for a, local in _IDEAL.items():
        pos[:, a] = ca + rot @ local
    # glycine (aatype 7) has no CB
    has_cb = aatype != 7
    mask[:, _CB] = has_cb.astype(np.float32)

    target_feat = np.zeros((num_res, 22), np.float32)
    target_feat[np.arange(num_res), aatype + 1] = 1.0  # slot 0 = between-seg

    true_msa = np.concatenate(
        [aatype[None], rng.integers(0, 21, (num_msa - 1, num_res))], 0
    ).astype(np.int32)
    bert_mask = (rng.random((num_msa, num_res)) < 0.15).astype(np.float32)
    shown = np.where(bert_mask > 0, 22, true_msa)  # masked token = 22
    msa_feat = np.zeros((num_msa, num_res, 49), np.float32)
    msa_feat[..., :23] = np.eye(23, dtype=np.float32)[shown]
    msa_feat[..., 25:48] = np.eye(23, dtype=np.float32)[true_msa]  # profile slot

    extra_msa = rng.integers(0, 21, (num_extra_msa, num_res)).astype(np.int32)

    ex: Dict[str, np.ndarray] = {
        "aatype": aatype,
        "residue_index": np.arange(num_res, dtype=np.int32),
        "seq_mask": np.ones(num_res, np.float32),
        "target_feat": target_feat,
        "msa_feat": msa_feat,
        "msa_mask": np.ones((num_msa, num_res), np.float32),
        "true_msa": true_msa,
        "bert_mask": bert_mask,
        "extra_msa": extra_msa,
        "extra_has_deletion": np.zeros((num_extra_msa, num_res), np.float32),
        "extra_deletion_value": np.zeros((num_extra_msa, num_res), np.float32),
        "extra_msa_mask": np.ones((num_extra_msa, num_res), np.float32),
        "all_atom_positions": pos,
        "all_atom_mask": mask,
    }
    if num_templates > 0:
        tpos = pos[None] + rng.normal(0, 0.5, (num_templates,) + pos.shape).astype(
            np.float32
        )
        beta = np.where((aatype == 7)[:, None], tpos[..., _CA, :], tpos[..., _CB, :])
        ex.update(
            {
                "template_aatype": np.tile(aatype, (num_templates, 1)),
                "template_all_atom_positions": tpos,
                "template_all_atom_masks": np.tile(mask, (num_templates, 1, 1)),
                "template_pseudo_beta": beta.astype(np.float32),
                "template_pseudo_beta_mask": np.tile(
                    mask[:, _CB][None], (num_templates, 1)
                ),
                "template_mask": np.ones(num_templates, np.float32),
            }
        )
    return ex


@DATASETS.register("ProteinDataset")
class ProteinDataset:
    def __init__(
        self,
        input_dir: Optional[str] = None,
        num_res: int = 64,
        num_msa: int = 16,
        num_extra_msa: int = 16,
        num_templates: int = 2,
        num_samples: int = 64,
        mode: str = "Train",
        seed: int = 0,
        **_unused: Any,
    ):
        self.num_res = num_res
        self.dims = (num_res, num_msa, num_extra_msa, num_templates)
        self.records: List[Dict[str, np.ndarray]] = []
        if input_dir:
            for f in sorted(os.listdir(input_dir)):
                if f.endswith(".npz"):
                    with np.load(os.path.join(input_dir, f)) as z:
                        self.records.append(
                            self._pad_crop({k: z[k] for k in z.files})
                        )
        else:
            rng = np.random.default_rng(seed + (0 if mode == "Train" else 10_000))
            for _ in range(num_samples):
                self.records.append(
                    synthesize_protein(rng, num_res, num_msa, num_extra_msa, num_templates)
                )

    # per-feature (msa-rows-dim, residue-dim) axis positions for pad/crop
    _AXES = {
        "aatype": (None, 0), "residue_index": (None, 0), "seq_mask": (None, 0),
        "target_feat": (None, 0), "msa_feat": (0, 1), "msa_mask": (0, 1),
        "true_msa": (0, 1), "bert_mask": (0, 1), "extra_msa": (0, 1),
        "extra_has_deletion": (0, 1), "extra_deletion_value": (0, 1),
        "extra_msa_mask": (0, 1), "all_atom_positions": (None, 0),
        "all_atom_mask": (None, 0), "template_aatype": (0, 1),
        "template_all_atom_positions": (0, 1), "template_all_atom_masks": (0, 1),
        "template_pseudo_beta": (0, 1), "template_pseudo_beta_mask": (0, 1),
        "template_mask": (0, None),
    }

    def _pad_crop(self, rec: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Pad (zeros) / crop each loaded feature to the configured static
        shapes so jitted losses never retrace on protein length."""
        num_res, num_msa, num_extra, num_templates = self.dims
        out: Dict[str, np.ndarray] = {}
        for k, v in rec.items():
            if k not in self._AXES:
                out[k] = v
                continue
            rows_ax, res_ax = self._AXES[k]
            if rows_ax is not None:
                rows = num_templates if k.startswith("template_") else (
                    num_extra if k.startswith("extra_") else num_msa
                )
                v = self._fit(v, rows_ax, rows)
            if res_ax is not None:
                v = self._fit(v, res_ax, num_res)
            out[k] = v
        return out

    @staticmethod
    def _fit(v: np.ndarray, axis: int, size: int) -> np.ndarray:
        if v.shape[axis] > size:
            sl = [slice(None)] * v.ndim
            sl[axis] = slice(0, size)
            return v[tuple(sl)]
        if v.shape[axis] < size:
            pad = [(0, 0)] * v.ndim
            pad[axis] = (0, size - v.shape[axis])
            return np.pad(v, pad)
        return v

    def __len__(self) -> int:
        return len(self.records)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        return self.records[idx % len(self.records)]
