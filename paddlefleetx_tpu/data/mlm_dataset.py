"""Generic masked-LM dataset over the Megatron-style mmap corpus.

BERT-style dynamic masking for encoder pretraining (DebertaV2): sample
fixed-length windows from the token stream and mask `mask_prob` of the
positions with the standard 80/10/10 [MASK]/random/keep split.  Emits the
{input_ids, labels, attention_mask} contract of
``models/debertav2/model.py::mlm_loss`` (labels == -1 ignored).

The reference ships DebertaV2 as modeling-only (consumed as an Imagen text
encoder, SURVEY §2.3); this dataset is what makes the repo's
``configs/debertav2/pretrain_debertav2_base.yaml`` genuinely trainable
end-to-end rather than a modeling stub.

Corpus format: ``<prefix>_ids.npy`` + ``<prefix>_idx.npz`` — the same
files GPTDataset mmaps (``write_synthetic_corpus`` generates them).
"""

from __future__ import annotations

import glob
import os

import numpy as np

from paddlefleetx_tpu.utils.registry import DATASETS


@DATASETS.register("MaskedLmDataset")
class MaskedLmDataset:
    def __init__(
        self,
        input_dir: str,
        max_seq_len: int = 512,
        vocab_size: int = 128100,
        mask_prob: float = 0.15,
        mask_token_id: int = 128000,
        seed: int = 1234,
        num_samples: int = 0,
        mode: str = "Train",
        split=(949, 50, 1),
        **_unused,
    ):
        prefix = input_dir
        if not os.path.exists(prefix + "_ids.npy"):
            hits = sorted(glob.glob(os.path.join(input_dir, "*_ids.npy")))
            if not hits:
                raise FileNotFoundError(
                    f"no <prefix>_ids.npy under {input_dir!r} "
                    "(write_synthetic_corpus / preprocess_data format)"
                )
            prefix = hits[0][: -len("_ids.npy")]
        self.tokens = np.load(prefix + "_ids.npy", mmap_mode="r")
        self.seq_len = int(max_seq_len)
        self.vocab_size = int(vocab_size)
        self.mask_prob = float(mask_prob)
        self.mask_id = int(mask_token_id)
        self.seed = int(seed)
        total = max(len(self.tokens) // self.seq_len, 1)
        # mode-disjoint window ranges (GPTDataset's (949, 50, 1) split
        # semantics): eval must never score windows the model trains on
        w = np.asarray(split, np.float64)
        bounds = np.concatenate([[0.0], np.cumsum(w / w.sum())])
        i = {"Train": 0, "Eval": 1, "Test": 2}.get(mode, 0)
        self._win0 = int(round(bounds[i] * total))
        n_windows = max(int(round(bounds[i + 1] * total)) - self._win0, 1)
        # epoch-loop past the range end like GPTDataset (train wants
        # max_steps * batch samples; windows repeat deterministically)
        self._len = int(num_samples) if num_samples else n_windows
        self._n_windows = n_windows

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx: int) -> dict:
        w = self._win0 + idx % self._n_windows
        start = w * self.seq_len
        ids = np.asarray(self.tokens[start:start + self.seq_len], dtype=np.int64)
        if ids.size and int(ids.max()) >= self.vocab_size:
            # a corpus tokenized with a larger vocab than the config
            # declares must fail loudly, not silently scramble token ids
            raise ValueError(
                f"corpus token id {int(ids.max())} >= configured "
                f"vocab_size {self.vocab_size} (wrong corpus or config?)"
            )
        pad = self.seq_len - len(ids)
        if pad:
            ids = np.concatenate([ids, np.zeros(pad, np.int64)])
        attn = np.ones(self.seq_len, np.float32)
        if pad:
            attn[-pad:] = 0.0

        rng = np.random.default_rng((self.seed, idx))
        labels = np.full(self.seq_len, -1, np.int64)
        input_ids = ids.copy()
        maskable = attn > 0
        draw = rng.random(self.seq_len)
        chosen = maskable & (draw < self.mask_prob)
        labels[chosen] = ids[chosen]
        # 80% -> [MASK], 10% -> random token, 10% -> keep original
        action = rng.random(self.seq_len)
        input_ids[chosen & (action < 0.8)] = self.mask_id
        rand = chosen & (action >= 0.8) & (action < 0.9)
        input_ids[rand] = rng.integers(0, self.vocab_size, int(rand.sum()))
        return {"input_ids": input_ids, "labels": labels, "attention_mask": attn}
