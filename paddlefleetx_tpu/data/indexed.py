"""Index-map builders for Megatron-style token datasets.

Same four entry points as the reference's native helper module
(``fast_index_map_helpers`` — build_sample_idx / build_mapping /
build_blocks_mapping / build_blending_indices,
ppfleetx/data/data_tools/cpp/fast_index_map_helpers.cpp:693-697), provided
as (a) a C++ shared library loaded via ctypes (built by
``paddlefleetx_tpu/data/cpp``) and (b) pure-numpy fallbacks with identical
outputs (mirroring the reference's Python fallback, gpt_dataset.py:274-465).
The C++ implementations here are written from scratch against the observed
behavior — O(tokens) two-pointer walks.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from paddlefleetx_tpu.utils.log import logger

_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _load_lib() -> Optional[ctypes.CDLL]:
    """Load (building on first use) the C++ helper shared library."""
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    try:
        from paddlefleetx_tpu.data.cpp.build import build_and_load

        _LIB = build_and_load()
    except Exception as e:  # toolchain missing: numpy fallback
        logger.warning(f"C++ index helpers unavailable ({e}); using numpy fallback")
        _LIB_FAILED = True
    return _LIB


def build_sample_idx(
    sizes: np.ndarray,
    doc_idx: np.ndarray,
    seq_length: int,
    num_epochs: int,
    tokens_per_epoch: int,
    use_cpp: bool = True,
) -> np.ndarray:
    """Map each training sample to (doc_idx position, in-doc offset).

    Returns int32 [num_samples+1, 2]; sample i spans tokens from boundary i
    to boundary i+1 (seq_length+1 tokens, +1 for the shifted label).
    Reference: fast_index_map_helpers.cpp:92-178 / gpt_dataset.py fallback.
    """
    sizes = np.asarray(sizes, dtype=np.int32)
    doc_idx = np.asarray(doc_idx, dtype=np.int32)
    num_samples = (num_epochs * tokens_per_epoch - 1) // seq_length

    lib = _load_lib() if use_cpp else None
    if lib is not None:
        out = np.zeros((num_samples + 1, 2), dtype=np.int32)
        lib.build_sample_idx(
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            doc_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(seq_length),
            ctypes.c_int64(num_samples),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out

    sample_idx = np.zeros((num_samples + 1, 2), dtype=np.int32)
    di, offset = 0, 0
    sample_idx[0] = (0, 0)
    for i in range(1, num_samples + 1):
        remaining = seq_length
        # advance through docs until the sample (seq_length tokens + 1 label
        # overlap) is filled
        while remaining > 0:
            doc_len = sizes[doc_idx[di]] - offset
            if doc_len > remaining:
                offset += remaining
                remaining = 0
            else:
                remaining -= doc_len
                di += 1
                offset = 0
        sample_idx[i] = (di, offset)
    return sample_idx


def build_shuffle_idx(num_samples: int, total_size: int, rng: np.random.Generator):
    """Two-part shuffle (reference gpt_dataset.py:436-465): samples inside
    the requested range shuffled separately from the epoch tail."""
    dtype = np.int64 if total_size >= 2**31 else np.int32
    first = np.arange(num_samples, dtype=dtype)
    rng.shuffle(first)
    last = np.arange(num_samples, total_size, dtype=dtype)
    rng.shuffle(last)
    return np.concatenate([first, last])


def build_doc_idx(
    num_docs: int, num_epochs: int, rng: np.random.Generator, separate_last: bool = True
):
    """Shuffled doc order over epochs (reference gpt_dataset.py:407-433);
    the final partial epoch is shuffled separately for exact sample counts."""
    if num_epochs <= 1 or not separate_last:
        idx = np.tile(np.arange(num_docs, dtype=np.int32), max(num_epochs, 1))
        rng.shuffle(idx)
        return idx
    head = np.tile(np.arange(num_docs, dtype=np.int32), num_epochs - 1)
    rng.shuffle(head)
    tail = np.arange(num_docs, dtype=np.int32)
    rng.shuffle(tail)
    return np.concatenate([head, tail])


def build_blending_indices(
    weights: np.ndarray, num_samples: int, use_cpp: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    """Interleave multiple datasets by weight (reference
    fast_index_map_helpers.cpp build_blending_indices): greedily pick the
    dataset whose emitted fraction lags its weight most."""
    weights = np.asarray(weights, dtype=np.float64)
    weights = weights / weights.sum()
    n = len(weights)

    lib = _load_lib() if use_cpp else None
    if lib is not None:
        ds_index = np.zeros(num_samples, dtype=np.int8)
        ds_sample = np.zeros(num_samples, dtype=np.int64)
        lib.build_blending_indices(
            weights.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_int32(n),
            ctypes.c_int64(num_samples),
            ds_index.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            ds_sample.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return ds_index, ds_sample

    ds_index = np.zeros(num_samples, dtype=np.int8)
    ds_sample = np.zeros(num_samples, dtype=np.int64)
    counts = np.zeros(n, dtype=np.int64)
    for i in range(num_samples):
        errors = weights * (i + 1) - counts
        d = int(np.argmax(errors))
        ds_index[i] = d
        ds_sample[i] = counts[d]
        counts[d] += 1
    return ds_index, ds_sample


def build_mapping(
    docs: np.ndarray,
    sizes: np.ndarray,
    max_seq_length: int,
    short_seq_prob: float = 0.1,
    seed: int = 1,
    min_num_sent: int = 2,
    use_cpp: bool = True,
) -> np.ndarray:
    """BERT/ERNIE sentence-pair sample map (reference build_mapping,
    fast_index_map_helpers.cpp:693): greedily packs consecutive sentences of
    each document into samples of up to max_seq_length-3 tokens (room for
    [CLS] a [SEP] b [SEP]); a short_seq_prob fraction get random shorter
    targets.  Returns int64 [n, 3] rows (sent_begin, sent_end, target_len).

    docs:  int64 [num_docs+1] sentence-index boundary per doc.
    sizes: int32 [num_sentences] token length per sentence.
    """
    docs = np.asarray(docs, dtype=np.int64)
    sizes = np.asarray(sizes, dtype=np.int32)
    num_docs = len(docs) - 1

    lib = _load_lib() if use_cpp else None
    if lib is not None:
        max_out = len(sizes) + num_docs + 1
        out = np.zeros((max_out, 3), dtype=np.int64)
        n = lib.build_mapping(
            docs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(num_docs),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(max_seq_length),
            ctypes.c_double(short_seq_prob),
            ctypes.c_uint64(seed),
            ctypes.c_int64(max_out),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int32(min_num_sent),
        )
        return out[:n]

    # numpy fallback: same walk, same RNG *semantics* (not bit-identical to
    # the C++ mt19937 stream — callers must pick one path per index cache)
    rng = np.random.default_rng(seed)
    max_tokens = max_seq_length - 3
    rows = []

    def target():
        if short_seq_prob > 0.0 and rng.random() < short_seq_prob:
            return 2 + int(rng.random() * (max_tokens - 1))
        return max_tokens

    for doc in range(num_docs):
        begin, end = docs[doc], docs[doc + 1]
        t = target()
        start, tok_count, num_sent = begin, 0, 0
        for s in range(begin, end):
            tok_count += int(sizes[s])
            num_sent += 1
            last = s == end - 1
            if (tok_count >= t and num_sent >= min_num_sent) or last:
                if num_sent >= min_num_sent and tok_count > 1:
                    rows.append((start, s + 1, t))
                start, tok_count, num_sent = s + 1, 0, 0
                t = target()
    return np.asarray(rows, dtype=np.int64).reshape(-1, 3)


def build_blocks_mapping(
    docs: np.ndarray,
    sizes: np.ndarray,
    max_seq_length: int,
    seed: int = 1,
    use_cpp: bool = True,
) -> np.ndarray:
    """Fixed-block sample map (reference build_blocks_mapping): consecutive
    sentences packed into blocks of max_seq_length-2 tokens.  Returns int64
    [n, 4] rows (sent_begin, sent_end, doc_index, block_len)."""
    docs = np.asarray(docs, dtype=np.int64)
    sizes = np.asarray(sizes, dtype=np.int32)
    num_docs = len(docs) - 1

    lib = _load_lib() if use_cpp else None
    if lib is not None:
        max_out = len(sizes) + num_docs + 1
        out = np.zeros((max_out, 4), dtype=np.int64)
        n = lib.build_blocks_mapping(
            docs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(num_docs),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(max_seq_length),
            ctypes.c_uint64(seed),
            ctypes.c_int64(max_out),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return out[:n]

    max_tokens = max_seq_length - 2
    rows = []
    for doc in range(num_docs):
        begin, end = docs[doc], docs[doc + 1]
        start, tok_count = begin, 0
        for s in range(begin, end):
            tok_count += int(sizes[s])
            last = s == end - 1
            if tok_count >= max_tokens or last:
                if tok_count > 1:
                    rows.append((start, s + 1, doc, min(tok_count, max_tokens)))
                start, tok_count = s + 1, 0
    return np.asarray(rows, dtype=np.int64).reshape(-1, 4)
