"""ERNIE pretraining dataset: sentence-pair construction + ngram masking.

Behavior modeled on the reference's ERNIE data pipeline
(ppfleetx/data/dataset/ernie/ernie_dataset.py:46-129 +
dataset_utils.py:254-470 ``create_masked_lm_predictions``): documents of
tokenized sentences -> sentence-pair samples (C++ ``build_mapping`` index,
data/indexed.py) -> per-sample ngram span masking (80% [MASK] / 10% random
/ 10% keep) + NSP label by random segment swap.

Corpus format (created by :func:`write_synthetic_sentence_corpus` or the
preprocessing tools): ``prefix_ids.npy`` flat token stream plus
``prefix_idx.npz`` with ``sent_lens`` (int32 per-sentence token counts) and
``doc_sent_counts`` (int32 sentences per document).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from paddlefleetx_tpu.data.indexed import build_mapping
from paddlefleetx_tpu.utils.registry import DATASETS


@DATASETS.register("ErnieDataset")
class ErnieDataset:
    def __init__(
        self,
        input_dir: Optional[str] = None,
        tokens: Optional[np.ndarray] = None,
        sent_lens: Optional[np.ndarray] = None,
        doc_sent_counts: Optional[np.ndarray] = None,
        max_seq_len: int = 512,
        masked_lm_prob: float = 0.15,
        max_predictions_per_seq: Optional[int] = None,
        short_seq_prob: float = 0.1,
        max_ngrams: int = 3,
        vocab_size: int = 40000,
        cls_id: int = 1,
        sep_id: int = 2,
        mask_id: int = 3,
        pad_id: int = 0,
        binary_head: bool = True,
        seed: int = 1234,
        num_samples: Optional[int] = None,
        mode: str = "Train",
        **_,
    ):
        if input_dir is not None:
            tokens = np.load(input_dir + "_ids.npy", mmap_mode="r")
            idx = np.load(input_dir + "_idx.npz")
            sent_lens = idx["sent_lens"]
            doc_sent_counts = idx["doc_sent_counts"]
        assert tokens is not None and sent_lens is not None and doc_sent_counts is not None
        self.tokens = tokens
        self.sent_lens = np.asarray(sent_lens, dtype=np.int32)
        # token-stream offset of each sentence
        self.sent_offsets = np.concatenate(
            [[0], np.cumsum(self.sent_lens)]
        ).astype(np.int64)
        docs = np.concatenate([[0], np.cumsum(doc_sent_counts)]).astype(np.int64)

        self.max_seq_len = int(max_seq_len)
        self.masked_lm_prob = float(masked_lm_prob)
        self.max_predictions = int(
            max_predictions_per_seq
            if max_predictions_per_seq is not None
            else round(masked_lm_prob * max_seq_len)
        )
        self.max_ngrams = int(max_ngrams)
        self.vocab_size = int(vocab_size)
        self.cls_id, self.sep_id, self.mask_id, self.pad_id = cls_id, sep_id, mask_id, pad_id
        self.binary_head = bool(binary_head)
        self.seed = int(seed)

        self.samples = build_mapping(
            docs,
            self.sent_lens,
            self.max_seq_len,
            short_seq_prob=short_seq_prob,
            seed=self.seed,
            min_num_sent=2 if self.binary_head else 1,
        )
        self._epoch_len = len(self.samples)
        self.num_samples = int(num_samples) if num_samples else self._epoch_len
        self._visits: Dict[int, int] = {}

    def __len__(self) -> int:
        return self.num_samples

    def _sentence(self, s: int) -> np.ndarray:
        a, b = self.sent_offsets[s], self.sent_offsets[s + 1]
        return np.asarray(self.tokens[a:b], dtype=np.int64)

    def __getitem__(self, idx: int, visit: Optional[int] = None) -> Dict[str, np.ndarray]:
        row = self.samples[idx % self._epoch_len]
        sent_begin, sent_end, target_len = int(row[0]), int(row[1]), int(row[2])
        # fresh masking each epoch (visit counter), deterministic per visit
        # (the reference re-masks per epoch the same way, via epoch seeds);
        # loader workers pass the visit explicitly
        if visit is None:
            visit = self._visits.get(idx, 0)
            self._visits[idx] = visit + 1
        rng = np.random.default_rng((self.seed, idx, visit))
        sents = [self._sentence(s) for s in range(sent_begin, sent_end)]

        # --- segment split + NSP label (random A/B swap, BERT-style) ------
        if self.binary_head and len(sents) > 1:
            split = int(rng.integers(1, len(sents)))
            a = np.concatenate(sents[:split])
            b = np.concatenate(sents[split:])
            if rng.random() < 0.5:
                a, b = b, a
                nsp_label = 1  # swapped / "random next"
            else:
                nsp_label = 0
        else:
            a = np.concatenate(sents)
            b = np.zeros(0, dtype=np.int64)
            nsp_label = 0

        # truncate longest-first to target_len
        budget = min(target_len, self.max_seq_len - 3)
        while len(a) + len(b) > budget:
            if len(a) >= len(b):
                a = a[:-1] if rng.random() < 0.5 else a[1:]
            else:
                b = b[:-1] if rng.random() < 0.5 else b[1:]

        ids = np.concatenate(
            [[self.cls_id], a, [self.sep_id], b, [self.sep_id]]
        ).astype(np.int64)
        token_type = np.concatenate(
            [np.zeros(len(a) + 2, np.int64), np.ones(len(b) + 1, np.int64)]
        )
        special = np.zeros(len(ids), dtype=bool)
        special[0] = special[len(a) + 1] = special[-1] = True

        input_ids, mlm_labels = self._mask_tokens(ids, special, rng)

        # pad to max_seq_len
        L = self.max_seq_len
        pad = L - len(input_ids)
        attn = np.concatenate([np.ones(len(input_ids), np.float32), np.zeros(pad, np.float32)])
        input_ids = np.concatenate([input_ids, np.full(pad, self.pad_id, np.int64)])
        token_type = np.concatenate([token_type, np.zeros(pad, np.int64)])
        mlm_labels = np.concatenate([mlm_labels, np.full(pad, -1, np.int64)])
        return {
            "input_ids": input_ids,
            "token_type_ids": token_type,
            "attention_mask": attn,
            "masked_lm_labels": mlm_labels,
            "next_sentence_label": np.int64(nsp_label),
        }

    def _mask_tokens(self, ids: np.ndarray, special: np.ndarray, rng) -> tuple:
        """Ngram span masking (reference create_masked_lm_predictions
        dataset_utils.py:254-470): candidate positions get ngram spans with
        pvals ~ 1/n; each masked token is 80% [MASK], 10% random, 10% kept."""
        ids = ids.copy()
        labels = np.full(len(ids), -1, dtype=np.int64)
        num_to_predict = min(
            self.max_predictions,
            max(1, int(round(len(ids) * self.masked_lm_prob))),
        )
        candidates = np.flatnonzero(~special)
        rng.shuffle(candidates)
        pvals = 1.0 / np.arange(1, self.max_ngrams + 1)
        pvals = pvals / pvals.sum()
        covered = np.zeros(len(ids), dtype=bool)
        n_masked = 0
        for start in candidates:
            if n_masked >= num_to_predict:
                break
            n = int(rng.choice(np.arange(1, self.max_ngrams + 1), p=pvals))
            span = range(start, min(start + n, len(ids)))
            if any(covered[i] or special[i] for i in span):
                continue
            for i in span:
                if n_masked >= num_to_predict:
                    break
                covered[i] = True
                labels[i] = ids[i]
                r = rng.random()
                if r < 0.8:
                    ids[i] = self.mask_id
                elif r < 0.9:
                    ids[i] = int(rng.integers(4, self.vocab_size))
                n_masked += 1
        return ids, labels


def write_synthetic_sentence_corpus(
    prefix: str,
    vocab_size: int = 40000,
    num_docs: int = 32,
    sents_per_doc: int = 8,
    mean_sent_len: int = 24,
    seed: int = 0,
) -> str:
    """Tiny sentence-structured corpus in the ERNIE mmap format (tests)."""
    rng = np.random.default_rng(seed)
    doc_sent_counts = rng.integers(
        max(2, sents_per_doc // 2), sents_per_doc * 2, num_docs
    ).astype(np.int32)
    total_sents = int(doc_sent_counts.sum())
    sent_lens = rng.integers(
        max(4, mean_sent_len // 2), mean_sent_len * 2, total_sents
    ).astype(np.int32)
    probs = 1.0 / (np.arange(vocab_size) + 5.0)
    probs[:4] = 0.0  # special tokens never appear in raw text
    probs /= probs.sum()
    tokens = rng.choice(vocab_size, size=int(sent_lens.sum()), p=probs).astype(
        np.uint16 if vocab_size < 2**16 else np.uint32
    )
    np.save(prefix + "_ids.npy", tokens)
    np.savez(prefix + "_idx.npz", sent_lens=sent_lens, doc_sent_counts=doc_sent_counts)
    return prefix
