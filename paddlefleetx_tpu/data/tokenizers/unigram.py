"""Shared sentencepiece-style unigram segmentation core.

Viterbi best-segmentation over a piece->logprob vocabulary with the "▁"
whitespace marker — the algorithm both the T5 and DebertaV2 tokenizers
wrap (the reference vendors two separate sentencepiece-backed stacks,
t5_tokenizer.py and debertav2_tokenizer.py; the segmentation math is one
function here).
"""

from __future__ import annotations

import math
from typing import Dict, List

SPIECE_UNDERLINE = "▁"


def viterbi_segment(
    text: str, scores: Dict[str, float], max_piece_len: int
) -> List[str]:
    """Best segmentation of one pre-tokenized chunk (▁-prefixed word).
    Unknown single characters get a below-vocab penalty score."""
    n = len(text)
    best: List[float] = [0.0] + [-math.inf] * n
    back: List[int] = [0] * (n + 1)
    unk_pen = min(scores.values(), default=-10.0) - 10.0
    for end in range(1, n + 1):
        for start in range(max(0, end - max_piece_len), end):
            piece = text[start:end]
            score = scores.get(piece)
            if score is None:
                if end - start == 1:
                    score = unk_pen  # single-char fallback -> maybe <unk>
                else:
                    continue
            cand = best[start] + score
            if cand > best[end]:
                best[end] = cand
                back[end] = start
    out: List[str] = []
    end = n
    while end > 0:
        start = back[end]
        out.append(text[start:end])
        end = start
    return out[::-1]


def tokenize_words(
    text: str, scores: Dict[str, float], max_piece_len: int
) -> List[str]:
    toks: List[str] = []
    for word in text.strip().split():
        toks.extend(viterbi_segment(SPIECE_UNDERLINE + word, scores, max_piece_len))
    return toks
