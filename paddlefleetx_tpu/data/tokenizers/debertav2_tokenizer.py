"""DebertaV2 sentencepiece-style tokenizer (pure Python).

The reference vendors a 2,163-LoC HF-style ``DebertaV2Tokenizer`` wrapping
the sentencepiece C library (ppfleetx/data/tokenizers/debertav2_tokenizer.py:
``SPMTokenizer`` :1899 + ``DebertaV2Tokenizer`` :113 with the full
pad/truncate/special-token machinery).  This is a dependency-free
re-implementation of the behavior the framework needs: Viterbi unigram
segmentation over a piece->logprob vocab with the "▁" whitespace marker,
DeBERTa special-token conventions ([PAD]=0, [CLS]=1, [SEP]=2, [UNK]=3,
[MASK] appended at the top of the vocab, matching the reference's
``add_special_token`` layout), single- and pair-sequence encoding with
token_type_ids, padding/truncation, and decode.

Vocab format: JSON {"pieces": [[piece, logprob], ...]} with the four
specials at ids 0-3 (id = index).  ``from_tiny_corpus`` builds a toy vocab
for tests; real deployments convert a trained sentencepiece vocab with
``tools/preprocess_data.py`` conventions.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SPIECE_UNDERLINE = "▁"


class DebertaV2Tokenizer:
    def __init__(
        self,
        pieces: Sequence[Tuple[str, float]],
        *,
        do_lower_case: bool = False,
        pad_token: str = "[PAD]",
        cls_token: str = "[CLS]",
        sep_token: str = "[SEP]",
        unk_token: str = "[UNK]",
        mask_token: str = "[MASK]",
    ):
        self.pieces = list(pieces)
        self.do_lower_case = do_lower_case
        self.pad_token, self.cls_token = pad_token, cls_token
        self.sep_token, self.unk_token, self.mask_token = sep_token, unk_token, mask_token
        specials = [pad_token, cls_token, sep_token, unk_token]
        have = {p for p, _ in self.pieces}
        missing = [s for s in specials if s not in have]
        if missing:
            # prepend ONLY the missing specials (a vocab that already
            # contains some of them must keep its existing ids intact);
            # a fully-special-free vocab gets the DeBERTa spm layout
            # [PAD]=0 [CLS]=1 [SEP]=2 [UNK]=3
            self.pieces = [(s, 0.0) for s in missing] + self.pieces
        self.vocab: Dict[str, int] = {p: i for i, (p, _) in enumerate(self.pieces)}
        if mask_token not in self.vocab:
            # reference SPMTokenizer.add_special_token appends at the end
            self.vocab[mask_token] = len(self.vocab)
        self.inv_vocab = {i: p for p, i in self.vocab.items()}
        self.scores = {p: s for p, s in self.pieces}
        self.max_piece_len = max((len(p) for p, _ in self.pieces), default=1)

    # -- construction -------------------------------------------------------

    @classmethod
    def from_file(cls, path: str, **kw) -> "DebertaV2Tokenizer":
        with open(path) as f:
            data = json.load(f)
        return cls([(p, s) for p, s in data["pieces"]], **kw)

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"pieces": self.pieces}, f, ensure_ascii=False)

    @classmethod
    def from_tiny_corpus(
        cls, texts: Iterable[str], max_pieces: int = 1000, **kw
    ) -> "DebertaV2Tokenizer":
        from collections import Counter

        counts: Counter = Counter()
        chars: Counter = Counter()
        lower = kw.get("do_lower_case", False)
        for t in texts:
            if lower:
                t = t.lower()
            for w in t.split():
                counts[SPIECE_UNDERLINE + w] += 1
                for c in w:
                    chars[c] += 1
        pieces: List[Tuple[str, float]] = []
        total = sum(counts.values()) + sum(chars.values()) + 1
        seen = set()
        for c, n in chars.most_common():
            pieces.append((c, math.log(n / total)))
            pieces.append((SPIECE_UNDERLINE + c, math.log(n / total) - 1.0))
            seen.update((c, SPIECE_UNDERLINE + c))
        for w, n in counts.most_common(max_pieces - len(pieces)):
            if w not in seen:
                pieces.append((w, math.log(n / total)))
                seen.add(w)
        return cls(pieces, **kw)

    # -- unigram segmentation (shared core: tokenizers/unigram.py) ----------

    def tokenize(self, text: str) -> List[str]:
        from paddlefleetx_tpu.data.tokenizers.unigram import tokenize_words

        if self.do_lower_case:
            text = text.lower()
        return tokenize_words(text, self.scores, self.max_piece_len)

    # -- encode / decode ----------------------------------------------------

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        unk = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk) for t in tokens]

    def build_inputs_with_special_tokens(
        self, ids_a: List[int], ids_b: Optional[List[int]] = None
    ) -> List[int]:
        """[CLS] A [SEP] (+ B [SEP]) — reference :650-672."""
        out = [self.cls_id] + list(ids_a) + [self.sep_id]
        if ids_b is not None:
            out += list(ids_b) + [self.sep_id]
        return out

    def create_token_type_ids(
        self, ids_a: List[int], ids_b: Optional[List[int]] = None
    ) -> List[int]:
        """0s over [CLS] A [SEP], 1s over B [SEP] — reference :705-733."""
        t = [0] * (len(ids_a) + 2)
        if ids_b is not None:
            t += [1] * (len(ids_b) + 1)
        return t

    def encode(
        self,
        text: str,
        text_pair: Optional[str] = None,
        *,
        max_length: Optional[int] = None,
        padding: bool = False,
        add_special_tokens: bool = True,
    ) -> Dict[str, List[int]]:
        ids_a = self.convert_tokens_to_ids(self.tokenize(text))
        ids_b = (
            self.convert_tokens_to_ids(self.tokenize(text_pair))
            if text_pair is not None
            else None
        )
        if add_special_tokens:
            if max_length is not None:
                # truncate the longer sequence first (reference
                # truncate_sequences 'longest_first', :1195)
                n_special = 3 if ids_b is not None else 2
                if max_length < n_special + 1:
                    raise ValueError(
                        f"max_length={max_length} cannot fit {n_special} special "
                        f"tokens plus content"
                    )
                while len(ids_a) + len(ids_b or []) + n_special > max_length and (
                    ids_a or ids_b
                ):
                    if ids_b and len(ids_b) > len(ids_a):
                        ids_b.pop()
                    else:
                        ids_a.pop()
            input_ids = self.build_inputs_with_special_tokens(ids_a, ids_b)
            type_ids = self.create_token_type_ids(ids_a, ids_b)
        else:
            input_ids = ids_a + (ids_b or [])
            type_ids = [0] * len(ids_a) + [1] * len(ids_b or [])
            if max_length is not None:
                input_ids, type_ids = input_ids[:max_length], type_ids[:max_length]
        mask = [1] * len(input_ids)
        if padding and max_length is not None and len(input_ids) < max_length:
            pad_n = max_length - len(input_ids)
            input_ids += [self.pad_id] * pad_n
            type_ids += [0] * pad_n
            mask += [0] * pad_n
        return {
            "input_ids": input_ids,
            "token_type_ids": type_ids,
            "attention_mask": mask,
        }

    def decode(self, ids: Iterable[int], skip_special_tokens: bool = True) -> str:
        specials = {
            self.pad_token,
            self.cls_token,
            self.sep_token,
            self.unk_token,
            self.mask_token,
        }
        parts: List[str] = []
        for i in ids:
            p = self.inv_vocab.get(int(i), self.unk_token)
            if skip_special_tokens and p in specials:
                continue
            parts.append(p)
        return "".join(parts).replace(SPIECE_UNDERLINE, " ").strip()

    # -- properties ---------------------------------------------------------

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def pad_id(self) -> int:
        return self.vocab[self.pad_token]

    @property
    def cls_id(self) -> int:
        return self.vocab[self.cls_token]

    @property
    def sep_id(self) -> int:
        return self.vocab[self.sep_token]

    @property
    def mask_id(self) -> int:
        return self.vocab[self.mask_token]

    # T5-compatible surface so datasets can treat any tokenizer uniformly
    @property
    def eos_id(self) -> int:
        return self.sep_id

    def encode_ids(self, text: str, add_eos: bool = False) -> List[int]:
        """Flat id list without specials (Imagen caption path parity with
        T5Tokenizer.encode)."""
        ids = self.convert_tokens_to_ids(self.tokenize(text))
        if add_eos:
            ids.append(self.sep_id)
        return ids
