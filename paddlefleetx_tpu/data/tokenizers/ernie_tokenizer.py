"""ERNIE / BERT-style WordPiece tokenizer (pure Python).

Re-implementation of the tokenizer the reference wraps
(ppfleetx/data/tokenizers/ernie_tokenizer.py, a thin shim over the
paddlenlp ErnieTokenizer — BERT basic-tokenize + greedy-longest-match
WordPiece with '##' continuation, [CLS]/[SEP]/[MASK]/[PAD]/[UNK]
specials).

Vocab format: one token per line (id = line number), the BERT convention.
`from_tiny_corpus` builds a toy vocab for tests.
"""

from __future__ import annotations

import os
import unicodedata
from typing import Dict, Iterable, List, Optional, Sequence


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_chinese_char(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF
        or 0xF900 <= cp <= 0xFAFF
    )


class ErnieTokenizer:
    def __init__(
        self,
        vocab: Dict[str, int],
        *,
        do_lower_case: bool = True,
        unk_token: str = "[UNK]",
        pad_token: str = "[PAD]",
        cls_token: str = "[CLS]",
        sep_token: str = "[SEP]",
        mask_token: str = "[MASK]",
        max_input_chars_per_word: int = 100,
    ):
        self.vocab = dict(vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.do_lower_case = do_lower_case
        self.unk_token, self.pad_token = unk_token, pad_token
        self.cls_token, self.sep_token, self.mask_token = cls_token, sep_token, mask_token
        self.max_input_chars_per_word = max_input_chars_per_word

    # -- construction -------------------------------------------------------

    @classmethod
    def from_file(cls, vocab_file: str, **kw) -> "ErnieTokenizer":
        vocab: Dict[str, int] = {}
        with open(vocab_file, encoding="utf-8") as f:
            for i, line in enumerate(f):
                tok = line.rstrip("\n")
                if tok:
                    vocab[tok] = i
        return cls(vocab, **kw)

    def save(self, vocab_file: str) -> None:
        os.makedirs(os.path.dirname(vocab_file) or ".", exist_ok=True)
        with open(vocab_file, "w", encoding="utf-8") as f:
            for tok, _ in sorted(self.vocab.items(), key=lambda kv: kv[1]):
                f.write(tok + "\n")

    @classmethod
    def from_tiny_corpus(cls, texts: Iterable[str], **kw) -> "ErnieTokenizer":
        specials = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
        words, chars = set(), set()
        for t in texts:
            for w in t.lower().split():
                words.add(w)
                chars.update(w)
        vocab = {t: i for i, t in enumerate(specials)}
        for c in sorted(chars):
            vocab.setdefault(c, len(vocab))
            vocab.setdefault("##" + c, len(vocab))
        for w in sorted(words):
            vocab.setdefault(w, len(vocab))
        return cls(vocab, **kw)

    # -- basic tokenization --------------------------------------------------

    def _basic_tokenize(self, text: str) -> List[str]:
        if self.do_lower_case:
            # BERT BasicTokenizer: lowercase + strip accents (NFD then drop
            # combining marks) so 'café' -> 'cafe' like uncased vocabs expect
            text = unicodedata.normalize("NFD", text.lower())
            text = "".join(c for c in text if unicodedata.category(c) != "Mn")
        else:
            text = unicodedata.normalize("NFC", text)
        out: List[str] = []
        word: List[str] = []

        def flush():
            if word:
                out.append("".join(word))
                word.clear()

        for ch in text:
            if ch.isspace():
                flush()
            elif _is_punctuation(ch) or _is_chinese_char(ord(ch)):
                flush()
                out.append(ch)
            else:
                word.append(ch)
        flush()
        return out

    # -- wordpiece -----------------------------------------------------------

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_input_chars_per_word:
            return [self.unk_token]
        pieces: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            pieces.append(cur)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for word in self._basic_tokenize(text):
            out.extend(self._wordpiece(word))
        return out

    # -- encode / decode -----------------------------------------------------

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        unk = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk) for t in tokens]

    def encode(
        self,
        text: str,
        text_pair: Optional[str] = None,
        max_seq_len: Optional[int] = None,
    ) -> Dict[str, List[int]]:
        """[CLS] a [SEP] (b [SEP]) with token_type_ids, BERT layout."""
        a = self.convert_tokens_to_ids(self.tokenize(text))
        b = self.convert_tokens_to_ids(self.tokenize(text_pair)) if text_pair else []
        if max_seq_len:
            budget = max_seq_len - 2 - (1 if b else 0)
            if budget < 1:
                raise ValueError(
                    f"max_seq_len={max_seq_len} leaves no room for content "
                    f"after special tokens"
                )
            # longest-first truncation across the pair
            while len(a) + len(b) > budget:
                (a if len(a) >= len(b) else b).pop()
        cls_id, sep_id = self.vocab[self.cls_token], self.vocab[self.sep_token]
        ids = [cls_id] + a + [sep_id]
        type_ids = [0] * len(ids)
        if b:
            ids += b + [sep_id]
            type_ids += [1] * (len(b) + 1)
        return {"input_ids": ids, "token_type_ids": type_ids}

    def decode(self, ids: Iterable[int], skip_special_tokens: bool = True) -> str:
        specials = {self.pad_token, self.cls_token, self.sep_token, self.mask_token}
        parts: List[str] = []
        for i in ids:
            tok = self.inv_vocab.get(int(i), self.unk_token)
            if skip_special_tokens and tok in specials:
                continue
            if tok.startswith("##") and parts:
                parts[-1] += tok[2:]
            else:
                parts.append(tok)
        return " ".join(parts)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def pad_token_id(self) -> int:
        return self.vocab[self.pad_token]

    @property
    def mask_token_id(self) -> int:
        return self.vocab[self.mask_token]

    @property
    def cls_token_id(self) -> int:
        return self.vocab[self.cls_token]

    @property
    def sep_token_id(self) -> int:
        return self.vocab[self.sep_token]
