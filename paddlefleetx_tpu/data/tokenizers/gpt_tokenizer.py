"""GPT byte-level BPE tokenizer (reference ppfleetx/data/tokenizers/
gpt_tokenizer.py, 819 LoC wrapping the standard GPT-2 BPE).

From-scratch implementation of the standard algorithm: reversible
byte->unicode mapping, greedy pair merging by learned rank, GPT-2 word
pattern.  Loads the usual ``vocab.json`` + ``merges.txt`` artifacts.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

import regex as re

from paddlefleetx_tpu.utils.registry import TOKENIZERS

_WORD_PAT = re.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
)

# word-level memoization caps: natural-language traffic saturates well under
# this (Zipf), while adversarial/high-entropy input stays memory-bounded
_ENCODE_CACHE_MAX = 1 << 18


@functools.lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """Reversible byte->printable-unicode map (standard GPT-2 construction:
    printable ASCII/latin bytes map to themselves, the rest to 256+n)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _get_pairs(word: Tuple[str, ...]) -> set:
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class _NativeBpe:
    """ctypes wrapper over data/cpp/bpe.cpp — raw-byte vocab + merge ranks
    (tokens containing non-byte-mappable chars, i.e. special tokens, are
    excluded; the caller falls back to Python for them)."""

    def __init__(self, encoder: Dict[str, int], bpe_ranks, byte_decoder):
        import ctypes
        import struct

        from paddlefleetx_tpu.data.cpp.build import build_and_load

        self._lib = build_and_load()

        def to_bytes(mapped: str) -> Optional[bytes]:
            try:
                return bytes(byte_decoder[c] for c in mapped)
            except KeyError:
                return None

        # vocab blob: ids must be the token's real id -> emit a dense list.
        # Non-mappable tokens (specials) get a placeholder longer than the
        # 4096-byte word limit, so no queryable symbol can ever collide.
        placeholder = b"\x00" * 5000
        n = max(encoder.values()) + 1
        toks = [placeholder] * n
        for t, i in encoder.items():
            raw = to_bytes(t)
            if raw is not None:
                toks[i] = raw
        parts = [struct.pack("<i", n)]
        parts += [struct.pack("<i", len(t)) + t for t in toks]
        vocab_blob = b"".join(parts)

        merges = sorted(bpe_ranks.items(), key=lambda kv: kv[1])
        mparts = [struct.pack("<i", len(merges))]
        for (a, b), _rank in merges:
            ra, rb = to_bytes(a), to_bytes(b)
            if ra is None or rb is None:  # keep rank indices aligned
                ra, rb = placeholder, placeholder
            mparts.append(struct.pack("<i", len(ra)) + ra)
            mparts.append(struct.pack("<i", len(rb)) + rb)
        merge_blob = b"".join(mparts)

        self._handle = self._lib.bpe_new(
            vocab_blob, len(vocab_blob), merge_blob, len(merge_blob)
        )
        if not self._handle:
            raise RuntimeError("bpe_new failed")
        self._ctypes = ctypes
        self._buf = (ctypes.c_int32 * 4096)()

    def encode_word(self, raw: bytes) -> Optional[List[int]]:
        if len(raw) > 4096:
            return None
        n = self._lib.bpe_encode_word(
            self._handle, raw, len(raw), self._buf, len(self._buf)
        )
        if n < 0:
            return None
        return list(self._buf[:n])

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.bpe_free(self._handle)
        except Exception:
            pass


@TOKENIZERS.register("GPTTokenizer")
class GPTTokenizer:
    def __init__(self, vocab_file: str, merges_file: str, eos_token: str = "<|endoftext|>"):
        with open(vocab_file) as f:
            self.encoder: Dict[str, int] = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        with open(merges_file, encoding="utf-8") as f:
            merges = [
                tuple(line.split())
                for line in f.read().split("\n")
                if line and not line.startswith("#version")
            ]
        self.bpe_ranks = {m: i for i, m in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.cache: Dict[str, str] = {}
        self.eos_token = eos_token
        self.eos_token_id = self.encoder.get(eos_token)
        self.pad_token_id = self.eos_token_id
        # native merge engine (data/cpp/bpe.cpp): byte-level BPE is
        # isomorphic under the byte->unicode map, so the C++ side works on
        # raw bytes; special tokens (not byte-mappable) stay Python-side
        self._native = None
        self._id_cache: Dict[bytes, List[int]] = {}
        try:
            self._native = _NativeBpe(self.encoder, self.bpe_ranks, self.byte_decoder)
        except Exception as e:  # no compiler / build failure: pure-Python path
            from paddlefleetx_tpu.utils.log import logger

            logger.warning(f"native BPE unavailable ({e!r}); using Python merge loop")
            self._native = None

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    def _bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word: Tuple[str, ...] = tuple(token)
        pairs = _get_pairs(word)
        if not pairs:
            return token
        while True:
            pair = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if pair not in self.bpe_ranks:
                break
            a, b = pair
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(a, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    new_word.append(a + b)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = " ".join(word)
        if len(self.cache) >= _ENCODE_CACHE_MAX:
            self.cache.pop(next(iter(self.cache)))
        self.cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        if self._native is not None:
            for tok in re.findall(_WORD_PAT, text):
                raw = tok.encode("utf-8")
                got = self._id_cache.get(raw)
                if got is None:
                    got = self._native.encode_word(raw)
                    if got is None:  # symbol outside the byte vocab
                        mapped = "".join(self.byte_encoder[b] for b in raw)
                        got = [self.encoder[t] for t in self._bpe(mapped).split(" ")]
                    # bounded FIFO eviction: encode() sits on the serving
                    # path, and high-entropy client text would otherwise
                    # grow the cache without limit over a long-lived server
                    if len(self._id_cache) >= _ENCODE_CACHE_MAX:
                        self._id_cache.pop(next(iter(self._id_cache)))
                    self._id_cache[raw] = got
                ids.extend(got)
            return ids
        for tok in re.findall(_WORD_PAT, text):
            mapped = "".join(self.byte_encoder[b] for b in tok.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self._bpe(mapped).split(" "))
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        text = "".join(self.decoder[int(i)] for i in ids if int(i) in self.decoder)
        return bytearray(self.byte_decoder[c] for c in text).decode("utf-8", errors="replace")

    @classmethod
    def from_pretrained(cls, path: str) -> "GPTTokenizer":
        """Load from a directory with vocab.json + merges.txt."""
        return cls(os.path.join(path, "vocab.json"), os.path.join(path, "merges.txt"))
