"""GPT byte-level BPE tokenizer (reference ppfleetx/data/tokenizers/
gpt_tokenizer.py, 819 LoC wrapping the standard GPT-2 BPE).

From-scratch implementation of the standard algorithm: reversible
byte->unicode mapping, greedy pair merging by learned rank, GPT-2 word
pattern.  Loads the usual ``vocab.json`` + ``merges.txt`` artifacts.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

import regex as re

from paddlefleetx_tpu.utils.registry import TOKENIZERS

_WORD_PAT = re.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
)


@functools.lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """Reversible byte->printable-unicode map (standard GPT-2 construction:
    printable ASCII/latin bytes map to themselves, the rest to 256+n)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _get_pairs(word: Tuple[str, ...]) -> set:
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


@TOKENIZERS.register("GPTTokenizer")
class GPTTokenizer:
    def __init__(self, vocab_file: str, merges_file: str, eos_token: str = "<|endoftext|>"):
        with open(vocab_file) as f:
            self.encoder: Dict[str, int] = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        with open(merges_file, encoding="utf-8") as f:
            merges = [
                tuple(line.split())
                for line in f.read().split("\n")
                if line and not line.startswith("#version")
            ]
        self.bpe_ranks = {m: i for i, m in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.cache: Dict[str, str] = {}
        self.eos_token = eos_token
        self.eos_token_id = self.encoder.get(eos_token)
        self.pad_token_id = self.eos_token_id

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    def _bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word: Tuple[str, ...] = tuple(token)
        pairs = _get_pairs(word)
        if not pairs:
            return token
        while True:
            pair = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if pair not in self.bpe_ranks:
                break
            a, b = pair
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(a, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    new_word.append(a + b)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = " ".join(word)
        self.cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for tok in re.findall(_WORD_PAT, text):
            mapped = "".join(self.byte_encoder[b] for b in tok.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self._bpe(mapped).split(" "))
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        text = "".join(self.decoder[int(i)] for i in ids if int(i) in self.decoder)
        return bytearray(self.byte_decoder[c] for c in text).decode("utf-8", errors="replace")

    @classmethod
    def from_pretrained(cls, path: str) -> "GPTTokenizer":
        """Load from a directory with vocab.json + merges.txt."""
        return cls(os.path.join(path, "vocab.json"), os.path.join(path, "merges.txt"))
