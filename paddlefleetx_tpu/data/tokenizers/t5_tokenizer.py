"""T5 sentencepiece-style unigram tokenizer (pure Python).

The reference vendors a full sentencepiece-backed T5Tokenizer
(ppfleetx/data/tokenizers/t5_tokenizer.py + tokenizer_base, ~2.9k LoC
wrapping the sentencepiece C library).  This is a dependency-free
re-implementation of the inference side: Viterbi unigram segmentation over
a piece->logprob vocabulary with the "▁" whitespace marker, byte-level
<unk> fallback, and the T5 special tokens (</s>=1, <pad>=0, <unk>=2,
<extra_id_0..99> sentinel ids at the top of the vocab).

Vocab format: JSON {"pieces": [[piece, logprob], ...]} in sentencepiece
order (id = index).  `from_tiny_corpus` builds a toy vocab for tests.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, Iterable, List, Sequence, Tuple

SPIECE_UNDERLINE = "▁"  # ▁


class T5Tokenizer:
    def __init__(
        self,
        pieces: Sequence[Tuple[str, float]],
        *,
        num_extra_ids: int = 100,
        pad_token: str = "<pad>",
        eos_token: str = "</s>",
        unk_token: str = "<unk>",
    ):
        self.pieces = list(pieces)
        self.extra_tokens = [f"<extra_id_{i}>" for i in range(num_extra_ids)]
        self.vocab: Dict[str, int] = {p: i for i, (p, _) in enumerate(self.pieces)}
        # sentinels occupy the ids above the base vocab in DESCENDING order:
        # extra_id_0 is the highest id (reference/HF T5 convention), so
        # corpora tokenized with a reference tokenizer keep matching ids
        base = len(self.pieces)
        for i, t in enumerate(self.extra_tokens):
            self.vocab[t] = base + num_extra_ids - 1 - i
        self.inv_vocab = {i: p for p, i in self.vocab.items()}
        self.scores = {p: s for p, s in self.pieces}
        self.pad_token, self.eos_token, self.unk_token = pad_token, eos_token, unk_token
        self.max_piece_len = max((len(p) for p, _ in self.pieces), default=1)

    # -- construction -------------------------------------------------------

    @classmethod
    def from_file(cls, path: str, **kw) -> "T5Tokenizer":
        with open(path) as f:
            data = json.load(f)
        return cls([(p, s) for p, s in data["pieces"]], **kw)

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"pieces": self.pieces}, f, ensure_ascii=False)

    @classmethod
    def from_tiny_corpus(cls, texts: Iterable[str], max_pieces: int = 1000, **kw) -> "T5Tokenizer":
        """Toy vocab: specials + chars + frequent words (unigram scores from
        counts). Good enough for tests and demos; real deployments load a
        trained sentencepiece vocab via from_file."""
        from collections import Counter

        counts: Counter = Counter()
        chars: Counter = Counter()
        for t in texts:
            for w in t.split():
                counts[SPIECE_UNDERLINE + w] += 1
                for c in w:
                    chars[c] += 1
        pieces: List[Tuple[str, float]] = [("<pad>", 0.0), ("</s>", 0.0), ("<unk>", 0.0)]
        total = sum(counts.values()) + sum(chars.values()) + 1
        seen = {p for p, _ in pieces}
        for c, n in chars.most_common():
            pieces.append((c, math.log(n / total)))
            pieces.append((SPIECE_UNDERLINE + c, math.log(n / total) - 1.0))
            seen.update((c, SPIECE_UNDERLINE + c))
        for w, n in counts.most_common(max_pieces - len(pieces)):
            if w not in seen:
                pieces.append((w, math.log(n / total)))
                seen.add(w)
        return cls(pieces, **kw)

    # -- core unigram segmentation -----------------------------------------

    def tokenize(self, text: str) -> List[str]:
        from paddlefleetx_tpu.data.tokenizers.unigram import tokenize_words

        return tokenize_words(text, self.scores, self.max_piece_len)

    # -- encode / decode ----------------------------------------------------

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        unk = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk) for t in tokens]

    def encode(self, text: str, add_eos: bool = True) -> List[int]:
        ids = self.convert_tokens_to_ids(self.tokenize(text))
        if add_eos:
            ids.append(self.vocab[self.eos_token])
        return ids

    def decode(self, ids: Iterable[int], skip_special_tokens: bool = True) -> str:
        specials = {self.pad_token, self.eos_token, self.unk_token, *self.extra_tokens}
        parts: List[str] = []
        for i in ids:
            p = self.inv_vocab.get(int(i), self.unk_token)
            if skip_special_tokens and p in specials:
                continue
            parts.append(p)
        return "".join(parts).replace(SPIECE_UNDERLINE, " ").strip()

    def extra_id(self, i: int) -> int:
        return self.vocab[f"<extra_id_{i}>"]

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def pad_id(self) -> int:
        return self.vocab[self.pad_token]

    @property
    def eos_id(self) -> int:
        return self.vocab[self.eos_token]
