"""GLUE task datasets (reference ppfleetx/data/dataset/glue_dataset.py:48-841:
CoLA / SST2 / MRPC / STSB / QQP / MNLI / QNLI / RTE / WNLI).

Reads the standard GLUE TSV layout from a local directory (``root/train.tsv``
/ ``dev.tsv``); column positions and label maps per task follow the public
GLUE release (same as the reference's processors).  Features come in two
styles:

  - ``gpt``: single token stream ``text_a [sep] text_b``, last-token
    classification (GPTForSequenceClassification path)
  - ``bert``: ``[CLS] a [SEP] b [SEP]`` with token-type ids (Ernie path)

Labels: int64 class index, or float32 for the STS-B regression task.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddlefleetx_tpu.utils.registry import DATASETS

# task -> (sentence columns (train), label column (train), label map, num_classes)
# column layouts of the public GLUE TSVs
_TASKS = {
    "cola": {"cols": (3,), "label": 1, "labels": ["0", "1"], "skip_header": False},
    "sst2": {"cols": (0,), "label": 1, "labels": ["0", "1"], "skip_header": True},
    "mrpc": {"cols": (3, 4), "label": 0, "labels": ["0", "1"], "skip_header": True},
    "stsb": {"cols": (7, 8), "label": 9, "labels": None, "skip_header": True},
    "qqp": {"cols": (3, 4), "label": 5, "labels": ["0", "1"], "skip_header": True},
    "mnli": {
        "cols": (8, 9),
        "label": -1,
        "labels": ["contradiction", "entailment", "neutral"],
        "skip_header": True,
    },
    "qnli": {
        "cols": (1, 2),
        "label": -1,
        "labels": ["entailment", "not_entailment"],
        "skip_header": True,
    },
    "rte": {
        "cols": (1, 2),
        "label": -1,
        "labels": ["entailment", "not_entailment"],
        "skip_header": True,
    },
    "wnli": {"cols": (1, 2), "label": -1, "labels": ["0", "1"], "skip_header": True},
}

# default eval metric per task (reference finetune yamls)
TASK_METRICS = {
    "cola": {"name": "Mcc"},
    "sst2": {"name": "Accuracy"},
    "mrpc": {"name": "AccuracyAndF1"},
    "stsb": {"name": "PearsonAndSpearman"},
    "qqp": {"name": "AccuracyAndF1"},
    "mnli": {"name": "Accuracy"},
    "qnli": {"name": "Accuracy"},
    "rte": {"name": "Accuracy"},
    "wnli": {"name": "Accuracy"},
}


def _read_tsv(path: str, skip_header: bool) -> List[List[str]]:
    with open(path, encoding="utf-8") as f:
        reader = csv.reader(f, delimiter="\t", quotechar=None)
        rows = list(reader)
    return rows[1:] if skip_header else rows


@DATASETS.register("GLUEDataset")
class GLUEDataset:
    def __init__(
        self,
        task: str,
        root: Optional[str] = None,
        tokenizer=None,
        examples: Optional[List[Tuple[List[str], Optional[str]]]] = None,
        max_seq_len: int = 128,
        style: str = "gpt",
        mode: str = "Train",
        pad_id: int = 0,
        cls_id: int = 1,
        sep_id: int = 2,
        **_,
    ):
        task = task.lower().replace("-", "")
        if task not in _TASKS:
            raise ValueError(f"unknown GLUE task {task!r}; known {sorted(_TASKS)}")
        self.task = task
        spec = _TASKS[task]
        self.is_regression = spec["labels"] is None
        self.num_classes = 1 if self.is_regression else len(spec["labels"])
        self.max_seq_len = int(max_seq_len)
        self.style = style
        self.tokenizer = tokenizer
        self.pad_id, self.cls_id, self.sep_id = pad_id, cls_id, sep_id

        if examples is None:
            fname = "train.tsv" if mode == "Train" else "dev.tsv"
            if task == "mnli" and mode != "Train":
                fname = "dev_matched.tsv"
            rows = _read_tsv(os.path.join(root, fname), spec["skip_header"])
            examples = []
            for row in rows:
                try:
                    texts = [row[c] for c in spec["cols"]]
                    label = row[spec["label"]]
                except IndexError:
                    continue  # malformed line
                examples.append((texts, label))
        self.examples = examples
        label_map = (
            None
            if self.is_regression
            else {name: i for i, name in enumerate(spec["labels"])}
        )
        self._features = [
            self._featurize(texts, label, label_map) for texts, label in self.examples
        ]

    def _encode(self, text) -> List[int]:
        if self.tokenizer is not None:
            return self.tokenizer.encode(text)
        if isinstance(text, str):  # no tokenizer: hashed-word fallback (tests)
            return [hash(w) % 30000 + 10 for w in text.split()]
        return list(text)  # already token ids

    def _featurize(self, texts, label, label_map) -> Dict[str, np.ndarray]:
        encoded = [self._encode(t) for t in texts]
        L = self.max_seq_len
        if self.style == "bert":
            a = encoded[0]
            b = encoded[1] if len(encoded) > 1 else []
            budget = L - (3 if b else 2)
            while len(a) + len(b) > budget:  # truncate longest-first
                if len(a) >= len(b):
                    a = a[:-1]
                else:
                    b = b[:-1]
            ids = [self.cls_id] + a + [self.sep_id] + (b + [self.sep_id] if b else [])
            token_type = [0] * (len(a) + 2) + [1] * (len(b) + 1 if b else 0)
            n = len(ids)
            feats = {
                "input_ids": np.asarray(ids + [self.pad_id] * (L - n), np.int64),
                "token_type_ids": np.asarray(token_type + [0] * (L - n), np.int64),
                "attention_mask": np.asarray([1.0] * n + [0.0] * (L - n), np.float32),
            }
        else:  # gpt: plain concatenated stream, right-padded
            ids: List[int] = []
            for i, e in enumerate(encoded):
                if i > 0:
                    ids.append(self.sep_id)
                ids.extend(e)
            ids = ids[: L - 1] if len(ids) >= L else ids
            n = len(ids)
            feats = {
                "tokens": np.asarray(ids + [self.pad_id] * (L - n), np.int64),
                "position_ids": np.arange(L, dtype=np.int64),
                # index of the last real token: its hidden state classifies
                "cls_position": np.int64(max(n - 1, 0)),
            }
        if self.is_regression:
            feats["labels"] = np.float32(float(label))
        else:
            feats["labels"] = np.int64(
                label_map[label.strip()] if isinstance(label, str) else int(label)
            )
        return feats

    def __len__(self) -> int:
        return len(self._features)

    def __getitem__(self, i: int) -> Dict[str, np.ndarray]:
        return self._features[i]


def write_synthetic_glue_task(
    root: str, task: str = "sst2", n: int = 64, seed: int = 0
) -> str:
    """Write a tiny fake GLUE TSV pair (train/dev) for tests: label-correlated
    token patterns so finetuning is learnable."""
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    spec = _TASKS[task.lower().replace("-", "")]
    pos_words = ["good", "great", "excellent", "happy"]
    neg_words = ["bad", "awful", "terrible", "sad"]
    for fname in ("train.tsv", "dev.tsv"):
        with open(os.path.join(root, fname), "w", encoding="utf-8") as f:
            if spec["skip_header"]:
                f.write("header\t" * 10 + "\n")
            for _ in range(n):
                y = int(rng.integers(0, 2))
                words = [
                    str(rng.choice(pos_words if y else neg_words))
                    for _ in range(int(rng.integers(3, 8)))
                ]
                text = " ".join(words)
                if task == "sst2":
                    f.write(f"{text}\t{y}\n")
                elif task == "cola":
                    f.write(f"x\t{y}\tx\t{text}\n")
                else:
                    raise NotImplementedError(f"synthetic writer for {task}")
    return root
