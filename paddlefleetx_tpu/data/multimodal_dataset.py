"""Imagen image-text dataset.

Re-design of the reference ImagenDataset
(ppfleetx/data/dataset/multimodal_dataset.py:62-202): a file list of
json lines, each with a base64-encoded image + caption; images decoded,
resized, scaled to [0, 1]; captions tokenized to fixed length.

Line format (either key set works):
  {"image_base64": "<b64 png/jpeg>", "caption": "..."}
  {"image_npy_base64": "<b64 of np.save bytes>", "caption": "..."}
"""

from __future__ import annotations

import base64
import io
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from paddlefleetx_tpu.utils.registry import DATASETS


@DATASETS.register("ImagenDataset")
class ImagenDataset:
    def __init__(
        self,
        input_path: str,
        image_size: int = 64,
        max_seq_len: int = 128,
        tokenizer: Optional[Any] = None,
        tokenizer_vocab: Optional[str] = None,
        tokenizer_name: str = "t5",
        filter_image_size: int = 0,
        mode: str = "Train",
        num_samples: Optional[int] = None,
    ):
        self.image_size = image_size
        self.max_seq_len = max_seq_len
        if tokenizer is None and tokenizer_vocab:
            # config path: Data.Train.dataset.tokenizer_vocab points at a
            # saved vocab json; tokenizer_name picks the family (the Imagen
            # DebertaV2 text-encoder option needs its matching tokenizer)
            if tokenizer_name.lower() in ("debertav2", "deberta_v2", "deberta"):
                from paddlefleetx_tpu.data.tokenizers.debertav2_tokenizer import (
                    DebertaV2Tokenizer,
                )

                tokenizer = DebertaV2Tokenizer.from_file(tokenizer_vocab)
            else:
                from paddlefleetx_tpu.data.tokenizers.t5_tokenizer import T5Tokenizer

                tokenizer = T5Tokenizer.from_file(tokenizer_vocab)
        self.tokenizer = tokenizer
        self.mode = mode
        self.records: List[Dict[str, Any]] = []
        with open(input_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    self.records.append(json.loads(line))
        if filter_image_size > 0:
            # drop records whose stored size metadata is below threshold
            # (reference ImagenDataset filters small source images)
            self.records = [
                r for r in self.records
                if min(r.get("width", filter_image_size), r.get("height", filter_image_size))
                >= filter_image_size
            ]
        if num_samples is not None and num_samples < len(self.records):
            self.records = self.records[:num_samples]

    def __len__(self) -> int:
        return len(self.records)

    def _decode_image(self, rec: Dict[str, Any]) -> np.ndarray:
        if "image_npy_base64" in rec:
            arr = np.load(io.BytesIO(base64.b64decode(rec["image_npy_base64"])))
        else:
            from PIL import Image

            img = Image.open(io.BytesIO(base64.b64decode(rec["image_base64"])))
            arr = np.asarray(img.convert("RGB"))
        return arr

    def _resize(self, arr: np.ndarray) -> np.ndarray:
        h, w = arr.shape[:2]
        s = self.image_size
        if (h, w) == (s, s):
            return arr
        try:
            from PIL import Image

            if np.issubdtype(arr.dtype, np.integer):
                return np.asarray(
                    Image.fromarray(arr.astype(np.uint8)).resize((s, s), Image.BILINEAR)
                )
            # float images: PIL 'F' mode per channel (uint8 cast would
            # truncate [0,1] floats to 0); grayscale handled as one channel
            if arr.ndim == 2:
                return np.asarray(
                    Image.fromarray(arr.astype(np.float32), mode="F").resize(
                        (s, s), Image.BILINEAR
                    )
                )
            chans = [
                np.asarray(
                    Image.fromarray(arr[..., c].astype(np.float32), mode="F").resize(
                        (s, s), Image.BILINEAR
                    )
                )
                for c in range(arr.shape[-1])
            ]
            return np.stack(chans, axis=-1)
        except Exception as e:
            # nearest-neighbor numpy fallback (PIL missing or exotic shape);
            # log it — silent quality degradation is worse than noise
            import warnings

            warnings.warn(f"PIL resize failed ({e!r}); using nearest-neighbor", stacklevel=2)
            yi = (np.arange(s) * h // s).clip(0, h - 1)
            xi = (np.arange(s) * w // s).clip(0, w - 1)
            return arr[yi][:, xi]

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        rec = self.records[idx]
        raw = self._decode_image(rec)
        to_unit = np.issubdtype(raw.dtype, np.integer)
        arr = self._resize(raw).astype(np.float32)
        if to_unit:
            arr = arr / 255.0
        out: Dict[str, np.ndarray] = {"images": arr}
        caption = rec.get("caption", "")
        if self.tokenizer is not None:
            # encode_ids: flat id list without specials (DebertaV2Tokenizer);
            # T5Tokenizer.encode already returns a flat list
            enc = getattr(self.tokenizer, "encode_ids", self.tokenizer.encode)
            ids = enc(caption)[: self.max_seq_len]
            pad = getattr(self.tokenizer, "pad_id", 0)
            ids = ids + [pad] * (self.max_seq_len - len(ids))
            out["input_ids"] = np.asarray(ids, np.int64)
        if "text_embed" in rec:
            out["text_embeds"] = np.asarray(rec["text_embed"], np.float32)
        return out


def write_synthetic_image_text_corpus(
    path: str, n: int = 8, image_size: int = 32, seed: int = 0
) -> str:
    """Tiny synthetic jsonl corpus (tests/demos)."""
    rng = np.random.default_rng(seed)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    words = ["red", "green", "cat", "dog", "sky", "tree", "sun", "sea"]
    with open(path, "w") as f:
        for i in range(n):
            img = (rng.uniform(size=(image_size, image_size, 3)) * 255).astype(np.uint8)
            buf = io.BytesIO()
            np.save(buf, img)
            rec = {
                "image_npy_base64": base64.b64encode(buf.getvalue()).decode(),
                "caption": " ".join(rng.choice(words, 3)),
            }
            f.write(json.dumps(rec) + "\n")
    return path
