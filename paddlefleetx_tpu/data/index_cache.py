"""Index-map cache integrity: atomic ``.npy`` writes, a cross-process
build lock, and validated loads with loud quarantine-on-corruption.

The GPT index maps (doc/sample/shuffle, data/gpt_dataset.py) are built once
and cached beside the corpus.  Three failure modes this module closes:

  - **torn writes**: a crash mid-``np.save`` leaves a half-written ``.npy``
    that a later run np.loads into garbage (or a parse error) — every write
    here goes tmp + ``os.replace`` so a cache file is either absent or
    complete (the same discipline utils/checkpoint.py applies to meta.json);
  - **multi-host build races**: N processes starting on a fresh corpus all
    build and write the same maps; without exclusion their writes can
    interleave on shared storage.  ``index_map_lock`` serializes builders
    per cache prefix via an ``fcntl`` file lock (advisory, shared-FS-safe
    for single-host and NFSv4+; builders re-check the cache after acquiring
    so exactly one process pays the build);
  - **bit-rot / wrong maps**: cached arrays are validated against the
    expected shape and dtype on load; a file that fails to parse or
    validate is QUARANTINED (renamed ``*.corrupt``, the PR-2 convention —
    loud, never silently reused) and the caller rebuilds.
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, Optional, Tuple

import numpy as np

from paddlefleetx_tpu.utils.checkpoint import corrupt_rename
from paddlefleetx_tpu.utils.log import logger


def atomic_save_npy(path: str, arr: np.ndarray) -> None:
    """Write ``path`` (must end in ``.npy``) atomically: tmp + rename, so a
    crash can never leave a torn array file behind."""
    if not path.endswith(".npy"):
        raise ValueError(f"atomic_save_npy expects a .npy path, got {path}")
    # tmp keeps the .npy suffix so np.save does not append a second one;
    # pid-suffix inside the name keeps concurrent writers from colliding
    tmp = f"{path[:-4]}.tmp{os.getpid()}.npy"
    try:
        np.save(tmp, arr)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def quarantine_cache_file(path: str) -> Optional[str]:
    """Rename a corrupt cache file to ``*.corrupt`` (the shared
    utils/checkpoint.corrupt_rename convention); returns the new path, or
    None when another process already renamed/removed it (shared-storage
    race — the goal is achieved either way)."""
    dst = corrupt_rename(path)
    if dst is not None:
        logger.error(
            f"QUARANTINED corrupt index-map cache: {path} -> {dst} "
            "(rebuilding from the corpus; inspect or delete the .corrupt "
            "file)"
        )
    return dst


@contextlib.contextmanager
def index_map_lock(cache_prefix: str):
    """Cross-process advisory lock for building the maps of one cache
    prefix.  Lock file: ``<prefix>.lock`` (left in place — deleting it
    would race a waiter locking the dead inode).  Falls back to unlocked
    on platforms without fcntl or on unwritable cache dirs (read-only
    data mounts build in memory anyway)."""
    lock_path = cache_prefix + ".lock"
    try:
        import fcntl
    except ImportError:  # non-POSIX: no cross-process exclusion available
        logger.warning("fcntl unavailable: index-map build lock disabled")
        yield
        return
    try:
        f = open(lock_path, "a")
    except OSError as e:  # read-only data dir: caller keeps maps in memory
        logger.warning(f"index-map lock {lock_path} unavailable ({e})")
        yield
        return
    try:
        fcntl.flock(f, fcntl.LOCK_EX)
        yield
    finally:
        with contextlib.suppress(OSError):
            fcntl.flock(f, fcntl.LOCK_UN)
        f.close()


def load_index_cache(
    cache_prefix: str,
    expect: Dict[str, Tuple[Tuple[int, ...], type]],
) -> Optional[Dict[str, np.ndarray]]:
    """Load + validate the cached maps for ``cache_prefix``.

    ``expect`` maps suffix name (e.g. ``doc_idx``) to (shape, dtype).
    Returns the dict of arrays when every file is present AND valid; None
    when any is missing; on a file that fails to parse or validate, every
    present cache file is quarantined (one torn writer means the set is
    not trustworthy as a unit) and None is returned so the caller rebuilds
    loudly."""
    paths = {name: f"{cache_prefix}_{name}.npy" for name in expect}
    if not all(os.path.exists(p) for p in paths.values()):
        return None
    out: Dict[str, np.ndarray] = {}
    for name, path in paths.items():
        shape, dtype = expect[name]
        try:
            arr = np.load(path, allow_pickle=False)
        except Exception as e:  # torn/rotten file: ValueError, EOFError...
            logger.error(f"index-map cache {path} unreadable: {e}")
            _quarantine_set(paths)
            return None
        if tuple(arr.shape) != tuple(shape) or arr.dtype != np.dtype(dtype):
            logger.error(
                f"index-map cache {path} shape/dtype mismatch: got "
                f"{arr.shape}/{arr.dtype}, expected {tuple(shape)}/"
                f"{np.dtype(dtype)}"
            )
            _quarantine_set(paths)
            return None
        out[name] = arr
    return out


def _quarantine_set(paths: Dict[str, str]) -> None:
    for p in paths.values():
        if os.path.exists(p):
            quarantine_cache_file(p)


def save_index_cache(cache_prefix: str, maps: Dict[str, np.ndarray]) -> bool:
    """Atomically write every map; returns False (warn, maps stay in
    memory) on unwritable storage."""
    try:
        for name, arr in maps.items():
            atomic_save_npy(f"{cache_prefix}_{name}.npy", arr)
        return True
    except OSError as e:  # read-only data dir: keep in memory
        logger.warning(f"index cache not written: {e}")
        return False
