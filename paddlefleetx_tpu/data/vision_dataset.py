"""Vision datasets (reference ppfleetx/data/dataset/vision_dataset.py:33-426:
GeneralClsDataset / ImageFolder / CIFAR10 / ContrastiveLearningDataset).

Host-side numpy pipelines; images flow to devices as [b, H, W, C] float32
batches (normalisation folded in here, augmentation kept minimal and
composable)."""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from paddlefleetx_tpu.utils.registry import DATASETS

IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


def normalize(img: np.ndarray) -> np.ndarray:
    return (img.astype(np.float32) / 255.0 - IMAGENET_MEAN) / IMAGENET_STD


def random_flip(img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    return img[:, ::-1] if rng.random() < 0.5 else img


@DATASETS.register("GeneralClsDataset")
class GeneralClsDataset:
    """Image-list file dataset (reference :33): each line
    ``relative/path.jpg<sep>label``."""

    def __init__(
        self,
        image_root: str,
        cls_label_path: str,
        mode: str = "Train",
        transform_ops=None,
        delimiter: str = " ",
        **_unused,
    ):
        self.root = image_root
        self.train = mode == "Train"
        self.samples: List[Tuple[str, int]] = []
        with open(cls_label_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                path, label = line.rsplit(delimiter, 1)
                self.samples.append((path, int(label)))
        self.rng = np.random.default_rng(0)

    def __len__(self):
        return len(self.samples)

    def _load(self, path: str) -> np.ndarray:
        full = os.path.join(self.root, path)
        if full.endswith(".npy"):
            return np.load(full)
        from PIL import Image  # lazy: PIL only needed for real image files

        return np.asarray(Image.open(full).convert("RGB"))

    def __getitem__(self, idx: int):
        path, label = self.samples[idx]
        img = self._load(path)
        if self.train:
            img = random_flip(img, self.rng)
        return {"images": normalize(img), "labels": np.int64(label)}


@DATASETS.register("SyntheticClsDataset")
class SyntheticClsDataset:
    """Class-conditional synthetic images (tests/benches): each class is a
    distinct mean pattern + noise, so accuracy is learnable."""

    def __init__(
        self,
        num_samples: int = 512,
        image_size: int = 32,
        num_classes: int = 8,
        seed: int = 0,
        mode: str = "Train",
        **_unused,
    ):
        self.n = num_samples
        self.size = image_size
        self.classes = num_classes
        rng = np.random.default_rng(seed)
        self.patterns = rng.normal(0, 1, (num_classes, image_size, image_size, 3)).astype(
            np.float32
        )
        self.labels = rng.integers(0, num_classes, num_samples)
        self.seed = seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx: int):
        rng = np.random.default_rng(self.seed * 100003 + idx)
        label = int(self.labels[idx])
        img = self.patterns[label] + 0.5 * rng.normal(0, 1, self.patterns[label].shape)
        return {"images": img.astype(np.float32), "labels": np.int64(label)}
