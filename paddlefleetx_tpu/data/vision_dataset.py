"""Vision datasets (reference ppfleetx/data/dataset/vision_dataset.py:33-426:
GeneralClsDataset / ImageFolder / CIFAR10 / ContrastiveLearningDataset).

Host-side numpy pipelines; images flow to devices as [b, H, W, C] float32
batches.  Transforms are name-dispatched from config ``transform_ops`` lists
(the reference builds paddle.vision transforms the same way)."""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlefleetx_tpu.utils.registry import DATASETS

IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


def normalize(img: np.ndarray, mean=IMAGENET_MEAN, std=IMAGENET_STD) -> np.ndarray:
    return (img.astype(np.float32) / 255.0 - mean) / std


def _resize(img: np.ndarray, size: int) -> np.ndarray:
    """Bilinear resize shorter side to ``size``, keeping aspect ratio."""
    h, w = img.shape[:2]
    if h < w:
        nh, nw = size, max(1, round(w * size / h))
    else:
        nh, nw = max(1, round(h * size / w)), size
    return _resize_exact(img, nh, nw)


def _resize_exact(img: np.ndarray, nh: int, nw: int) -> np.ndarray:
    """Bilinear resize to exactly [nh, nw] (numpy; no PIL dependency)."""
    h, w = img.shape[:2]
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def _center_crop(img: np.ndarray, size: int) -> np.ndarray:
    h, w = img.shape[:2]
    top = max(0, (h - size) // 2)
    left = max(0, (w - size) // 2)
    return img[top : top + size, left : left + size]


def _random_crop(img: np.ndarray, size: int, rng: np.random.Generator) -> np.ndarray:
    h, w = img.shape[:2]
    top = int(rng.integers(0, max(1, h - size + 1)))
    left = int(rng.integers(0, max(1, w - size + 1)))
    return img[top : top + size, left : left + size]


def build_transforms(ops: Optional[Sequence[Dict]]):
    """Compose a transform pipeline from config (reference transform_ops
    yaml lists: RandCropImage/RandFlipImage/ResizeImage/CropImage/
    NormalizeImage...).  Each op: {Name: {kwargs}}.  Returns a picklable
    callable (img, rng, train) -> img float32 — picklable so datasets can
    cross into spawn-started loader worker processes (batch_sampler.
    WorkerLoader)."""
    specs = []
    for op in ops or []:
        (name, kwargs), = op.items() if isinstance(op, dict) else [(op, {})]
        specs.append((name, dict(kwargs or {})))
    return _TransformPipeline(specs)


class _TransformPipeline:
    def __init__(self, specs):
        self.specs = specs

    def __call__(self, img: np.ndarray, rng: np.random.Generator, train: bool) -> np.ndarray:
        normalized = False
        for name, kw in self.specs:
            if name in ("ResizeImage", "Resize"):
                if "resize_short" in kw:
                    img = _resize(img, int(kw["resize_short"]))
                else:  # 'size' = exact HxW resize (reference semantics)
                    size = int(kw.get("size", 256))
                    img = _resize_exact(img, size, size)
            elif name in ("RandCropImage", "RandomResizedCrop"):
                size = int(kw.get("size", 224))
                if train:
                    img = _random_crop(_resize(img, max(size, int(size * 1.15))), size, rng)
                else:
                    img = _center_crop(_resize(img, max(size, int(size * 1.15))), size)
            elif name in ("CropImage", "CenterCrop"):
                img = _center_crop(img, int(kw.get("size", 224)))
            elif name in ("RandFlipImage", "RandomHorizontalFlip"):
                if train and rng.random() < 0.5:
                    img = img[:, ::-1]
            elif name in ("NormalizeImage", "Normalize"):
                mean = np.asarray(kw.get("mean", IMAGENET_MEAN), np.float32)
                std = np.asarray(kw.get("std", IMAGENET_STD), np.float32)
                scale = float(kw.get("scale", 1.0 / 255.0))
                img = (img.astype(np.float32) * scale - mean) / std
                normalized = True
            # unknown ops raise: silent skips would change training inputs
            elif name != "ToCHWImage":  # layout handled at batch level (NHWC native)
                raise ValueError(f"unknown transform op {name!r}")
        if not normalized:
            img = normalize(img)
        return np.ascontiguousarray(img, np.float32)


@DATASETS.register("GeneralClsDataset")
class GeneralClsDataset:
    """Image-list file dataset (reference :33): each line
    ``relative/path.jpg<sep>label``."""

    def __init__(
        self,
        image_root: str,
        cls_label_path: str,
        mode: str = "Train",
        transform_ops=None,
        delimiter: str = " ",
        seed: int = 1024,
        **_unused,
    ):
        self.root = image_root
        self.train = mode == "Train"
        self.samples: List[Tuple[str, int]] = []
        with open(cls_label_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                path, label = line.rsplit(delimiter, 1)
                self.samples.append((path, int(label)))
        self.transform = build_transforms(transform_ops)
        self.seed = int(seed)
        self._visits: Dict[int, int] = {}

    def __len__(self):
        return len(self.samples)

    def _load(self, path: str) -> np.ndarray:
        full = os.path.join(self.root, path)
        if full.lower().endswith(".npy"):
            return np.load(full)
        from PIL import Image  # lazy: PIL only needed for real image files

        return np.asarray(Image.open(full).convert("RGB"))

    def __getitem__(self, idx: int, visit: Optional[int] = None):
        path, label = self.samples[idx]
        img = self._load(path)
        # per-(seed, idx, visit) stream: reproducible under shuffling, yet a
        # fresh augmentation draw each epoch (visit = how many times this
        # sample has been served); loader workers pass the visit explicitly
        # so draws stay deterministic across worker scheduling
        if visit is None:
            visit = self._visits.get(idx, 0)
            self._visits[idx] = visit + 1
        rng = np.random.default_rng((self.seed, idx, visit))
        img = self.transform(img, rng, self.train)
        return {"images": img, "labels": np.int64(label)}


@DATASETS.register("SyntheticClsDataset")
class SyntheticClsDataset:
    """Class-conditional synthetic images (tests/benches): each class is a
    distinct mean pattern + noise, so accuracy is learnable."""

    def __init__(
        self,
        num_samples: int = 512,
        image_size: int = 32,
        num_classes: int = 8,
        seed: int = 0,
        mode: str = "Train",
        **_unused,
    ):
        self.n = num_samples
        self.size = image_size
        self.classes = num_classes
        rng = np.random.default_rng(seed)
        self.patterns = rng.normal(0, 1, (num_classes, image_size, image_size, 3)).astype(
            np.float32
        )
        self.labels = rng.integers(0, num_classes, num_samples)
        self.seed = seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx: int):
        rng = np.random.default_rng(self.seed * 100003 + idx)
        label = int(self.labels[idx])
        img = self.patterns[label] + 0.5 * rng.normal(0, 1, self.patterns[label].shape)
        return {"images": img.astype(np.float32), "labels": np.int64(label)}


@DATASETS.register("ImageFolder")
class ImageFolder(GeneralClsDataset):
    """Directory-per-class layout (reference ImageFolder vision_dataset.py:112:
    ``root/<class>/<image>`` with classes sorted alphabetically).  Shares
    loading/augmentation with GeneralClsDataset; only sample discovery differs."""

    IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp", ".npy")

    def __init__(self, root: str, mode: str = "Train", transform_ops=None,
                 seed: int = 1024, **_unused):
        self.root = root
        self.train = mode == "Train"
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise FileNotFoundError(f"no class folders under {root}")
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(self.IMG_EXTS):
                    self.samples.append((os.path.join(c, fname), self.class_to_idx[c]))
        self.transform = build_transforms(transform_ops)
        self.seed = int(seed)
        self._visits = {}


@DATASETS.register("CIFAR10")
class CIFAR10:
    """CIFAR-10 from the standard python pickle batches (reference
    vision_dataset.py:302).  Expects ``data_batch_{1..5}`` / ``test_batch``
    under ``root`` (the reference auto-downloads; this environment has no
    egress, so a missing root raises with the expected layout spelled out).
    Images are decoded once into memory as [32, 32, 3] uint8."""

    def __init__(self, root: str, mode: str = "train", transform_ops=None,
                 seed: int = 1024, **_unused):
        import pickle

        self.train = mode.lower() == "train"
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"CIFAR10 mode must be train|test, got {mode!r}")
        names = (
            [f"data_batch_{i}" for i in range(1, 6)] if self.train else ["test_batch"]
        )
        images, labels = [], []
        for name in names:
            path = os.path.join(root, name)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"{path} not found; CIFAR10 expects the extracted "
                    "cifar-10-batches-py layout (data_batch_1..5, test_batch)"
                )
            with open(path, "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            data = np.asarray(batch[b"data"], np.uint8)
            images.append(data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            labels.extend(batch[b"labels"])
        self.images = np.concatenate(images, axis=0)
        self.labels = np.asarray(labels, np.int64)
        self.transform = build_transforms(transform_ops)
        self.seed = int(seed)
        self._visits: Dict[int, int] = {}

    def __len__(self):
        return len(self.images)

    @property
    def class_num(self):
        return int(len(np.unique(self.labels)))

    def __getitem__(self, idx: int, visit: Optional[int] = None):
        if visit is None:
            visit = self._visits.get(idx, 0)
            self._visits[idx] = visit + 1
        rng = np.random.default_rng((self.seed, idx, visit))
        img = self.transform(self.images[idx], rng, self.train)
        return {"images": img, "labels": self.labels[idx]}


@DATASETS.register("ContrastiveLearningDataset")
@DATASETS.register("ContrativeLearningDataset")  # reference spelling (:29)
class ContrastiveLearningDataset:
    """Two independently-augmented views per image for MoCo
    (reference vision_dataset.py ContrativeLearningDataset): returns
    ``img_q`` / ``img_k`` drawn from the same underlying sample."""

    def __init__(self, base: Optional[Dict] = None, root: Optional[str] = None,
                 cls_label_path: Optional[str] = None, mode: str = "Train",
                 transform_ops=None, seed: int = 1024, **kw):
        if base is not None:
            base = dict(base)
            name = base.pop("name")
            base.setdefault("mode", mode)
            self.base = DATASETS.get(name)(**base)
        elif cls_label_path is not None:
            self.base = GeneralClsDataset(
                image_root=root or ".", cls_label_path=cls_label_path, mode=mode,
                transform_ops=transform_ops, seed=seed, **kw)
        else:
            self.base = SyntheticClsDataset(mode=mode, seed=seed, **kw)

        self.seed = int(seed)
        self._visits: Dict[int, int] = {}

    def __len__(self):
        return len(self.base)

    def _augment(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        # view-specific augmentation on top of the base transform (MoCo's two
        # views must differ even when the base pipeline is deterministic)
        if rng.random() < 0.5:
            img = img[:, ::-1]
        return img + rng.normal(0, 0.05, img.shape).astype(np.float32)

    def __getitem__(self, idx: int, visit: Optional[int] = None):
        if visit is None:
            visit = self._visits.get(idx, 0)
            self._visits[idx] = visit + 1
        img = self.base[idx]["images"]  # load once, augment twice
        q = self._augment(img, np.random.default_rng((self.seed, idx, visit, 0)))
        k = self._augment(img, np.random.default_rng((self.seed, idx, visit, 1)))
        return {"img_q": np.ascontiguousarray(q), "img_k": np.ascontiguousarray(k)}
