// Byte-level BPE merge engine — C-ABI module consumed via ctypes.
//
// TPU-native analogue of the reference's native data tooling
// (fast_index_map_helpers.cpp): the per-word greedy merge loop is the hot
// path when tokenizing pretraining corpora (tools/preprocess_data.py); the
// GPT-2 regex word split and caching stay in Python.  Byte-level BPE is
// isomorphic under the byte->unicode display map, so this module works on
// RAW UTF-8 BYTES and never touches unicode: a vocab token and a merge
// side are byte strings.
//
// Wire format (all length-prefixed, little-endian int32):
//   vocab blob:  n, then n x { len, bytes }            (index == token id)
//   merge blob:  m, then m x { lenA, bytesA, lenB, bytesB }  (index == rank)
//
// Entry points:
//   bpe_new(vocab, vocab_len, merges, merges_len) -> handle (0 on error)
//   bpe_encode_word(handle, word, len, out_ids, max_out) -> n ids (-1 err)
//   bpe_free(handle)

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
  size_t operator()(const std::pair<std::string, std::string>& p) const {
    std::hash<std::string> h;
    size_t a = h(p.first), b = h(p.second);
    return a ^ (b + 0x9e3779b97f4a7c15ULL + (a << 6) + (a >> 2));
  }
};

struct Bpe {
  std::unordered_map<std::string, int32_t> vocab;
  std::unordered_map<std::pair<std::string, std::string>, int32_t, PairHash> ranks;
};

const uint8_t* read_i32(const uint8_t* p, const uint8_t* end, int32_t* out) {
  if (p + 4 > end) return nullptr;
  std::memcpy(out, p, 4);
  return p + 4;
}

const uint8_t* read_str(const uint8_t* p, const uint8_t* end, std::string* out) {
  int32_t n;
  p = read_i32(p, end, &n);
  if (!p || n < 0 || p + n > end) return nullptr;
  out->assign(reinterpret_cast<const char*>(p), n);
  return p + n;
}

}  // namespace

extern "C" {

void* bpe_new(const uint8_t* vocab_blob, int64_t vocab_len,
              const uint8_t* merge_blob, int64_t merge_len) {
  auto* bpe = new (std::nothrow) Bpe();
  if (!bpe) return nullptr;
  {
    const uint8_t* p = vocab_blob;
    const uint8_t* end = vocab_blob + vocab_len;
    int32_t n;
    p = read_i32(p, end, &n);
    if (!p || n < 0) { delete bpe; return nullptr; }
    bpe->vocab.reserve(n * 2);
    std::string tok;
    for (int32_t i = 0; i < n; ++i) {
      p = read_str(p, end, &tok);
      if (!p) { delete bpe; return nullptr; }
      bpe->vocab.emplace(tok, i);
    }
  }
  {
    const uint8_t* p = merge_blob;
    const uint8_t* end = merge_blob + merge_len;
    int32_t m;
    p = read_i32(p, end, &m);
    if (!p || m < 0) { delete bpe; return nullptr; }
    bpe->ranks.reserve(m * 2);
    std::string a, b;
    for (int32_t i = 0; i < m; ++i) {
      p = read_str(p, end, &a);
      if (p) p = read_str(p, end, &b);
      if (!p) { delete bpe; return nullptr; }
      bpe->ranks.emplace(std::make_pair(a, b), i);
    }
  }
  return bpe;
}

void bpe_free(void* handle) { delete static_cast<Bpe*>(handle); }

// Greedy lowest-rank pair merging (the standard GPT-2 loop), then vocab
// lookup per final symbol.  Returns the id count, or -1 on unknown symbol /
// overflow / bad handle.
int32_t bpe_encode_word(void* handle, const uint8_t* word, int32_t len,
                        int32_t* out_ids, int32_t max_out) {
  if (!handle || len < 0) return -1;
  const Bpe& bpe = *static_cast<Bpe*>(handle);

  std::vector<std::string> syms;
  syms.reserve(len);
  for (int32_t i = 0; i < len; ++i)
    syms.emplace_back(reinterpret_cast<const char*>(word) + i, 1);

  while (syms.size() > 1) {
    int32_t best_rank = INT32_MAX;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < syms.size(); ++i) {
      auto it = bpe.ranks.find({syms[i], syms[i + 1]});
      if (it != bpe.ranks.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == INT32_MAX) break;
    // merge every non-overlapping occurrence of the best pair (left-to-
    // right), matching the Python reference loop
    const std::string a = syms[best_i], b = syms[best_i + 1];
    std::vector<std::string> merged;
    merged.reserve(syms.size());
    for (size_t i = 0; i < syms.size();) {
      if (i + 1 < syms.size() && syms[i] == a && syms[i + 1] == b) {
        merged.emplace_back(a + b);
        i += 2;
      } else {
        merged.emplace_back(syms[i]);
        i += 1;
      }
    }
    syms.swap(merged);
  }

  if (static_cast<int32_t>(syms.size()) > max_out) return -1;
  for (size_t i = 0; i < syms.size(); ++i) {
    auto it = bpe.vocab.find(syms[i]);
    if (it == bpe.vocab.end()) return -1;
    out_ids[i] = it->second;
  }
  return static_cast<int32_t>(syms.size());
}

}  // extern "C"
