"""JIT build of the C++ index helpers (reference compiles its pybind11 module
at first use via data_tools/cpp/compile.py + Makefile; we shell out to g++
once and cache the .so next to the source)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "helpers.cpp"), os.path.join(_DIR, "bpe.cpp")]
_SO = os.path.join(_DIR, "libpfx_helpers.so")


def build(force: bool = False) -> str:
    # tolerate a partial checkout: the index helpers must keep working even
    # if an optional source (bpe.cpp) is missing
    srcs = [s for s in _SRCS if os.path.exists(s)]
    if not srcs:
        raise FileNotFoundError(f"no C++ sources found in {_DIR}")
    src_mtime = max(os.path.getmtime(s) for s in srcs)
    if force or not os.path.exists(_SO) or os.path.getmtime(_SO) < src_mtime:
        # build to a temp name then rename: concurrent ranks racing the build
        # each produce a complete .so (reference rank0-builds + others poll;
        # atomic rename is simpler and lock-free)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        try:
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", *srcs, "-o", tmp],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, _SO)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return _SO


def build_and_load() -> ctypes.CDLL:
    lib = ctypes.CDLL(build())
    lib.build_sample_idx.restype = None
    lib.build_blending_indices.restype = None
    lib.build_mapping.restype = ctypes.c_int64
    lib.build_blocks_mapping.restype = ctypes.c_int64
    if hasattr(lib, "bpe_new"):  # optional module (bpe.cpp)
        lib.bpe_new.restype = ctypes.c_void_p
        lib.bpe_new.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.bpe_free.restype = None
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_encode_word.restype = ctypes.c_int32
        lib.bpe_encode_word.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
    return lib
