"""JIT build of the C++ index helpers (reference compiles its pybind11 module
at first use via data_tools/cpp/compile.py + Makefile; we shell out to g++
once and cache the .so next to the source)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "helpers.cpp")
_SO = os.path.join(_DIR, "libpfx_helpers.so")


def build(force: bool = False) -> str:
    if force or not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        # build to a temp name then rename: concurrent ranks racing the build
        # each produce a complete .so (reference rank0-builds + others poll;
        # atomic rename is simpler and lock-free)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        try:
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, _SO)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return _SO


def build_and_load() -> ctypes.CDLL:
    lib = ctypes.CDLL(build())
    lib.build_sample_idx.restype = None
    lib.build_blending_indices.restype = None
    lib.build_mapping.restype = ctypes.c_int64
    lib.build_blocks_mapping.restype = ctypes.c_int64
    return lib
