// Native index-map helpers for Megatron-style token datasets.
//
// TPU-native framework equivalent of the reference's pybind11 module
// ppfleetx/data/data_tools/cpp/fast_index_map_helpers.cpp (written from
// scratch; exported with a plain C ABI and loaded via ctypes — pybind11 is
// not part of this image).  Hot host-side data-prep: O(tokens) two-pointer
// walks that the Python fallbacks in data/indexed.py mirror exactly.

#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

extern "C" {

// Map each fixed-length training sample to (doc_idx position, in-doc offset).
// out: int32 [(num_samples+1) * 2].  A sample spans seq_length tokens plus a
// one-token overlap for the shifted language-modeling label.
void build_sample_idx(const int32_t* sizes, const int32_t* doc_idx,
                      int32_t seq_length, int64_t num_samples, int32_t* out) {
  int64_t di = 0;
  int32_t offset = 0;
  out[0] = 0;
  out[1] = 0;
  for (int64_t i = 1; i <= num_samples; ++i) {
    int32_t remaining = seq_length;
    while (remaining > 0) {
      int32_t doc_len = sizes[doc_idx[di]] - offset;
      if (doc_len > remaining) {
        offset += remaining;
        remaining = 0;
      } else {
        remaining -= doc_len;
        ++di;
        offset = 0;
      }
    }
    out[2 * i] = static_cast<int32_t>(di);
    out[2 * i + 1] = offset;
  }
}

// Greedy weighted interleaving of multiple datasets: at every step emit from
// the dataset whose emitted fraction lags its target weight the most.
void build_blending_indices(const double* weights, int32_t num_datasets,
                            int64_t num_samples, int8_t* ds_index,
                            int64_t* ds_sample) {
  std::vector<int64_t> counts(num_datasets, 0);
  for (int64_t i = 0; i < num_samples; ++i) {
    int32_t best = 0;
    double best_err = -1e300;
    for (int32_t d = 0; d < num_datasets; ++d) {
      double err = weights[d] * static_cast<double>(i + 1) -
                   static_cast<double>(counts[d]);
      if (err > best_err) {
        best_err = err;
        best = d;
      }
    }
    ds_index[i] = static_cast<int8_t>(best);
    ds_sample[i] = counts[best];
    ++counts[best];
  }
}

// BERT/ERNIE-style sentence-pair sample map (reference build_mapping):
// emits (start_doc_sentence_index, end_doc_sentence_index, target_seq_len)
// triples for masked-LM training over documents of sentences.
//
// docs:   int64 [num_docs+1] sentence-index boundaries per document
// sizes:  int32 [num_sentences] token length per sentence
// out:    int64 [max_out * 3]; returns number of triples written.
int64_t build_mapping(const int64_t* docs, int64_t num_docs,
                      const int32_t* sizes, int32_t max_seq_length,
                      double short_seq_prob, uint64_t seed, int64_t max_out,
                      int64_t* out, int32_t min_num_sent) {
  std::mt19937_64 rng(seed);
  std::uniform_real_distribution<double> unif(0.0, 1.0);
  int64_t written = 0;
  const int32_t max_tokens = max_seq_length - 3;  // [CLS] a [SEP] b [SEP]
  for (int64_t doc = 0; doc < num_docs; ++doc) {
    const int64_t sent_begin = docs[doc];
    const int64_t sent_end = docs[doc + 1];
    int32_t target = max_tokens;
    if (short_seq_prob > 0.0 && unif(rng) < short_seq_prob) {
      target = 2 + static_cast<int32_t>(unif(rng) * (max_tokens - 1));
    }
    int64_t start = sent_begin;
    int32_t tok_count = 0;
    int64_t num_sent = 0;
    for (int64_t s = sent_begin; s < sent_end; ++s) {
      tok_count += sizes[s];
      ++num_sent;
      const bool last = (s == sent_end - 1);
      if ((tok_count >= target && num_sent >= min_num_sent) || last) {
        if (num_sent >= min_num_sent && tok_count > 1) {
          if (written < max_out) {
            out[3 * written] = start;
            out[3 * written + 1] = s + 1;
            out[3 * written + 2] = target;
          }
          ++written;
        }
        start = s + 1;
        tok_count = 0;
        num_sent = 0;
        if (short_seq_prob > 0.0 && unif(rng) < short_seq_prob) {
          target = 2 + static_cast<int32_t>(unif(rng) * (max_tokens - 1));
        } else {
          target = max_tokens;
        }
      }
    }
  }
  return written;
}

// Block-based sample map (reference build_blocks_mapping): fixed token
// blocks for span-masking pretrain; emits (sentence_start, sentence_end,
// doc_index, block_len) quadruples.
int64_t build_blocks_mapping(const int64_t* docs, int64_t num_docs,
                             const int32_t* sizes, int32_t max_seq_length,
                             uint64_t seed, int64_t max_out, int64_t* out) {
  std::mt19937_64 rng(seed);
  int64_t written = 0;
  const int32_t max_tokens = max_seq_length - 2;  // [CLS] ... [SEP]
  for (int64_t doc = 0; doc < num_docs; ++doc) {
    const int64_t sent_begin = docs[doc];
    const int64_t sent_end = docs[doc + 1];
    int64_t start = sent_begin;
    int32_t tok_count = 0;
    for (int64_t s = sent_begin; s < sent_end; ++s) {
      tok_count += sizes[s];
      const bool last = (s == sent_end - 1);
      if (tok_count >= max_tokens || last) {
        if (tok_count > 1) {
          if (written < max_out) {
            out[4 * written] = start;
            out[4 * written + 1] = s + 1;
            out[4 * written + 2] = doc;
            out[4 * written + 3] = tok_count < max_tokens ? tok_count : max_tokens;
          }
          ++written;
        }
        start = s + 1;
        tok_count = 0;
      }
    }
  }
  return written;
}

}  // extern "C"
