"""Distributed batch sampling + host batch assembly.

Reference: GPTBatchSampler (ppfleetx/data/sampler/batch_sampler.py:31-192) —
slices the global batch across the data-parallel world (dp × sharding ranks,
env.py:158-178) with ``consumed_samples`` resume support.

TPU-native difference: with pjit we assemble the *global* batch on host and
let ``jax.make_array_from_process_local_data`` scatter it; on a single host
the "rank slicing" is purely logical.  The sampler therefore yields global
batches of indices, and resume is a sample counter — the same contract the
reference's checkpoint meta carries.

Iterator-state contract (docs/data_pipeline.md): every loader in this module
exposes ``state_dict()`` / ``load_state(state)`` / ``rewind(consumed_samples)``
— the engine saves the stream position in checkpoint meta, and anomaly
rollback rewinds the stream to the checkpoint position so the replayed data
is token-for-token identical to what an uninterrupted run would have served.
Rewinding invalidates any LIVE iteration (the position is read at
``iter()`` time): callers must re-``iter()`` after a rewind; the loaders'
``rewind`` tears down their background machinery (prefetch thread, worker
pool) so the stale lookahead cannot leak into the replay.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Sequence

import numpy as np

from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.registry import SAMPLERS


@SAMPLERS.register("GPTBatchSampler")
class DistributedBatchSampler:
    def __init__(
        self,
        dataset_len: int,
        batch_size: int,
        shuffle: bool = False,
        drop_last: bool = True,
        seed: int = 1234,
        consumed_samples: int = 0,
    ):
        self.n = int(dataset_len)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.consumed_samples = int(consumed_samples)
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {self.batch_size}")
        if self.drop_last and self.n < self.batch_size:
            # the epoch loop would otherwise spin forever yielding nothing
            # (observed as a silent eval hang on a 4-sample eval split)
            raise ValueError(
                f"dataset has {self.n} samples < batch_size {self.batch_size} "
                "with drop_last: no batch can ever be formed — lower the "
                "batch size (Global.eval_batch_size for eval) or grow the data"
            )

    def __iter__(self) -> Iterator[np.ndarray]:
        epoch = self.consumed_samples // self.n
        offset = self.consumed_samples % self.n
        while True:
            if self.shuffle:
                order = np.random.default_rng(self.seed + epoch).permutation(self.n)
            else:
                order = np.arange(self.n)
            for i in range(offset, self.n - self.batch_size + 1, self.batch_size):
                batch = order[i : i + self.batch_size]
                self.consumed_samples += len(batch)
                yield batch
            if not self.drop_last and (self.n - offset) % self.batch_size:
                tail = order[self.n - (self.n - offset) % self.batch_size :]
                self.consumed_samples += len(tail)
                yield tail
            epoch += 1
            offset = 0

    # -- iterator-state contract ---------------------------------------
    def state_dict(self) -> Dict[str, int]:
        return {"consumed_samples": self.consumed_samples}

    def load_state(self, state: Dict[str, int]) -> None:
        self.rewind(int(state["consumed_samples"]))

    def rewind(self, consumed_samples: int) -> None:
        """Reposition the stream at ``consumed_samples``.  The position is
        read at ``iter()`` time, so a LIVE iterator is unaffected — callers
        must re-``iter()`` (the loaders' ``rewind`` handles this)."""
        cs = int(consumed_samples)
        if cs < 0:
            raise ValueError(f"consumed_samples must be >= 0, got {cs}")
        self.consumed_samples = cs


def collate_stack(items: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """gpt_collate_fn analogue (reference batch_collate_fn.py:95: Tuple of
    Stack over tokens/position_ids/labels/loss_mask) — dict-of-stacked-arrays."""
    keys = items[0].keys()
    return {k: np.stack([it[k] for it in items]) for k in keys}


class DataLoader:
    """Minimal host data loader: sampler indices -> collated numpy batches.

    (The reference uses paddle.io.DataLoader worker processes; token datasets
    here are mmap reads + concatenation — cheap enough to do inline, and the
    engine overlaps host assembly with device steps via async dispatch.)

    Corrupt-sample quarantine: a sample whose fetch/decode raises is skipped
    under a bounded ``max_skips`` budget (``Data.<mode>.loader.max_skips``,
    default 0 = fail on the first bad sample).  The skip substitutes the
    next dataset index deterministically — so a rewound/resumed replay that
    hits the same corrupt record serves the same substitute and the stream
    stays reproducible — records a structured ``data_skip`` event (drained
    into the metrics stream by the engine), and fails loudly naming the
    budget once it is exhausted.  PFX_FAULT sites ``corrupt_sample`` and
    ``io_stall`` fire inside the fetch, keyed by a monotonic per-loader
    fetch counter.
    """

    def __init__(self, dataset, sampler: DistributedBatchSampler,
                 collate_fn=collate_stack, max_skips: int = 0):
        self.dataset = dataset
        self.sampler = sampler
        self.collate_fn = collate_fn
        self.max_skips = int(max_skips)
        self.skips = 0
        # structured data_skip events, appended here and drained by the
        # engine into the metrics stream (decoupled: the loader knows
        # nothing about metrics files)
        self.skip_events: List[Dict] = []
        self._fetch_count = 0
        # (stream_pos, cumulative_skips) per skip, on top of _skip_base
        # (skips restored from a checkpoint).  Lets ``skips_at(pos)`` report
        # the budget spent on TRAINED data only: with prefetch the live
        # ``skips`` counter runs ahead by the lookahead, and saving it
        # would double-charge the budget when the resumed replay re-hits a
        # corrupt sample in the buffered-but-untrained window.
        self._skip_base = 0
        self._skip_log: List[tuple] = []

    def _fetch(self, idx: int):
        from paddlefleetx_tpu.utils import resilience

        self._fetch_count += 1
        resilience.maybe_fire("io_stall", self._fetch_count)
        resilience.maybe_fire("corrupt_sample", self._fetch_count)
        return self.dataset[int(idx)]

    def _get(self, idx: int):
        try:
            return self._fetch(idx)
        except Exception as e:  # noqa: BLE001 — budgeted + re-raised below
            return self._skip_and_substitute(int(idx), e)

    def _budget_error(self, idx: int, err: Exception) -> RuntimeError:
        return RuntimeError(
            f"data.max_skips budget exhausted: sample {idx} failed "
            f"({type(err).__name__}: {err}) after {self.skips} "
            f"skip(s) already spent (data.max_skips={self.max_skips}) — "
            "the data is rotten beyond the configured tolerance; fix the "
            "shard or raise Data.<mode>.loader.max_skips"
        )

    def _skip_and_substitute(self, idx: int, err: Exception):
        if self.skips >= self.max_skips:
            # checked before len(): the budget error must fire even for
            # datasets that cannot offer a substitute
            raise self._budget_error(idx, err) from err
        n = len(self.dataset)
        bad = idx
        for attempt in range(1, max(n, 2)):
            if self.skips >= self.max_skips:
                raise self._budget_error(bad, err) from err
            self.skips += 1
            # the sampler increments consumed_samples BEFORE yielding the
            # batch, so its live counter is this batch's END position
            pos = self.sampler.consumed_samples
            self._skip_log.append((pos, self.skips))
            sub = (idx + attempt) % n  # deterministic: replays substitute
            event = {
                "event": "data_skip",
                "index": bad,
                "substitute": sub,
                "pos": pos,
                "error": f"{type(err).__name__}: {err}",
                "skips": self.skips,
                "max_skips": self.max_skips,
            }
            self.skip_events.append(event)
            logger.error(
                f"DATA SKIP {self.skips}/{self.max_skips}: sample {bad} "
                f"failed ({type(err).__name__}: {err}); substituting "
                f"sample {sub}"
            )
            try:
                return self._fetch(sub)
            except Exception as e:  # noqa: PERF203 — bounded by the budget
                bad, err = sub, e
        raise RuntimeError(
            f"every substitute sample failed after {self.skips} skip(s); "
            f"last error on sample {bad}: {err}"
        ) from err

    def __iter__(self):
        for batch_idx in self.sampler:
            yield self.collate_fn([self._get(int(i)) for i in batch_idx])

    # -- iterator-state contract ---------------------------------------
    def state_dict(self) -> Dict[str, int]:
        state = dict(self.sampler.state_dict())
        state["skips"] = self.skips
        return state

    def load_state(self, state: Dict[str, int]) -> None:
        self.sampler.load_state(state)
        self.skips = int(state.get("skips", self.skips))
        # the restored count is pre-history; the replayed window re-logs
        # its own skips from here
        self._skip_base = self.skips
        self._skip_log = []

    def rewind(self, consumed_samples: int) -> None:
        self.sampler.rewind(consumed_samples)

    def skips_at(self, consumed_samples: int) -> int:
        """Cumulative skips charged by batches at stream positions <=
        ``consumed_samples`` — the value a checkpoint at that position
        must record (the live ``skips`` counter includes prefetched-but-
        untrained batches whose replay will re-spend the budget)."""
        cs = int(consumed_samples)
        out = self._skip_base
        for pos, cum in self._skip_log:
            if pos <= cs:
                out = cum
        return out

    def close(self) -> None:
        """No background machinery to reclaim; present so callers can close
        any loader uniformly."""

    def stats(self) -> Dict[str, float]:
        return {"skips": self.skips}


class WorkerLoader:
    """Worker-process loader: the reference paddle.io.DataLoader
    ``num_workers`` analogue for decode-heavy datasets (image resize /
    augmentation dominate host time for the vision families).

    Workers use the ``spawn`` start method: the training process has live
    XLA/jax threads, and forking a threaded process can deadlock the
    child.  The dataset is pickled once into each worker at pool start
    (datasets and their transform pipelines are plain picklable objects),
    after which only indices and samples cross the pipe.  Worker startup
    costs a fresh interpreter (plus whatever sitecustomize preloads —
    the axon image preloads jax, ~3 s/worker); the pool lives for the
    whole epoch-looping iteration, so this is paid once per fit, not per
    batch.  Sample RNG streams stay deterministic per (seed, idx, visit)
    — but visit counters live per worker, so augmentation draws across
    epochs differ from the single-process order (same guarantee the
    reference's worker processes give).

    Worker exceptions PROPAGATE to the training loop (pool.map re-raises
    in the parent) instead of wedging it; ``close()`` tears down the pool
    so exits are clean.  The corrupt-sample skip budget is an inline
    DataLoader feature — a bad sample here fails loudly (the visit
    counters make silent substitution nondeterministic across worker
    scheduling).  ``rewind`` repositions the sampler but does NOT rewind
    the per-sample visit counters: replayed augmenting samples draw their
    next augmentation, not a byte-identical repeat.
    """

    def __init__(self, dataset, sampler: DistributedBatchSampler,
                 collate_fn=collate_stack, num_workers: int = 2):
        import inspect

        self.dataset = dataset
        self.sampler = sampler
        self.collate_fn = collate_fn
        self.num_workers = max(1, int(num_workers))
        # augmenting datasets key their RNG on (seed, idx, visit); the
        # visit counter must live HERE in the parent — per-worker counters
        # would make draws depend on which worker happened to serve a
        # sample (nondeterministic run-to-run, and epoch 2 frequently
        # replays epoch 1's draw when the sample lands on a fresh worker)
        self._visit_aware = "visit" in inspect.signature(
            dataset.__getitem__
        ).parameters
        self._visits: dict = {}
        self._gen = None

    def _visit(self, idx: int) -> int:
        v = self._visits.get(idx, 0)
        self._visits[idx] = v + 1
        return v

    def _iterate(self):
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        with ctx.Pool(
            self.num_workers, initializer=_worker_init, initargs=(self.dataset,)
        ) as pool:
            for batch_idx in self.sampler:
                if self._visit_aware:
                    work = [(int(i), self._visit(int(i))) for i in batch_idx]
                    items = pool.starmap(
                        _worker_get_visit, work,
                        chunksize=max(1, len(work) // self.num_workers),
                    )
                else:
                    items = pool.map(
                        _worker_get, [int(i) for i in batch_idx],
                        chunksize=max(1, len(batch_idx) // self.num_workers),
                    )
                yield self.collate_fn(items)

    def __iter__(self):
        self.close()  # at most one live pool per loader
        self._gen = self._iterate()
        return self._gen

    # -- iterator-state contract ---------------------------------------
    def state_dict(self) -> Dict[str, int]:
        return self.sampler.state_dict()

    def load_state(self, state: Dict[str, int]) -> None:
        self.close()
        self.sampler.load_state(state)

    def rewind(self, consumed_samples: int) -> None:
        self.close()
        self.sampler.rewind(consumed_samples)

    def close(self) -> None:
        """Terminate the worker pool (GeneratorExit unwinds the ``with
        ctx.Pool`` block) so no worker processes outlive the loader."""
        gen, self._gen = self._gen, None
        if gen is not None:
            gen.close()

    def stats(self) -> Dict[str, float]:
        return {}


_WORKER_DATASET = None


def _worker_init(dataset):
    global _WORKER_DATASET
    _WORKER_DATASET = dataset


def _worker_get(idx: int):
    return _WORKER_DATASET[idx]


def _worker_get_visit(idx: int, visit: int):
    return _WORKER_DATASET.__getitem__(idx, visit)


class _PrefetchIterator:
    """One live prefetch stream: a background thread fills a bounded queue
    from the wrapped loader; the consumer pops with starvation accounting.
    Owned by PrefetchLoader — ``close()`` stops and JOINS the thread."""

    def __init__(self, parent: "PrefetchLoader"):
        import queue
        import threading

        self.parent = parent
        self.q: "queue.Queue" = queue.Queue(maxsize=max(1, parent.depth))
        self.stop = threading.Event()
        self.err: List[BaseException] = []
        self.done = False
        self.thread = threading.Thread(
            target=self._producer, daemon=True, name="pfx-prefetch"
        )
        self.thread.start()

    def _put(self, item) -> bool:
        import queue

        while not self.stop.is_set():
            try:
                self.q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self):
        try:
            for item in self.parent.loader:
                if not self._put(item):
                    return  # consumer gone: drop buffers, exit thread
        except BaseException as e:  # surface in consumer thread
            self.err.append(e)
        finally:
            self._put(PrefetchLoader._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        import queue

        if self.done:
            raise StopIteration
        t0 = time.monotonic()
        warned = False
        while True:
            try:
                item = self.q.get(timeout=0.5)
                break
            except queue.Empty:
                waited = time.monotonic() - t0
                warn_s = self.parent.stall_warn_s
                if not warned and warn_s > 0 and waited >= warn_s:
                    # step-starvation watchdog: the device is idle waiting
                    # for host data — an I/O stall, slow storage, or an
                    # underpowered host pipeline; warn ONCE per batch
                    warned = True
                    self.parent.stall_warnings += 1
                    logger.warning(
                        f"prefetch starved: training step has waited "
                        f"{waited:.1f}s for the next batch (warn threshold "
                        f"{warn_s:.1f}s) — I/O stall or the host data "
                        "pipeline cannot keep up with the device step"
                    )
        self.parent.data_wait_s += time.monotonic() - t0
        if item is PrefetchLoader._DONE:
            self.done = True
            self._join()
            if self.err:
                raise self.err[0]
            raise StopIteration
        return item

    def depth(self) -> int:
        return self.q.qsize()

    def close(self) -> None:
        self.stop.set()
        self._join()

    def _join(self) -> None:
        self.thread.join(self.parent.join_timeout_s)
        if self.thread.is_alive():
            # blocked inside a dataset fetch (hung storage read): the
            # thread is daemon so the interpreter can still exit, but say
            # so loudly — a clean close should never hit this
            logger.warning(
                f"prefetch thread did not exit within "
                f"{self.parent.join_timeout_s:.1f}s (blocked in a sample "
                "fetch?); leaving the daemon thread behind"
            )


class PrefetchLoader:
    """Background-thread prefetch over any batch iterable (reference
    paddle.io.DataLoader worker analogue): host batch assembly overlaps the
    device step instead of serializing after it.  ``depth`` bounds buffered
    batches (memory = depth x batch bytes).

    Robustness contract: producer exceptions re-raise in the consumer;
    ``stats()`` reports the live queue depth and cumulative ``data_wait_s``
    (consumer seconds spent starved); waits past ``stall_warn_s`` trip a
    loud step-starvation warning; ``close()`` stops AND JOINS the thread so
    exits are clean; ``rewind``/``load_state`` tear down the live stream
    first (its buffered lookahead belongs to the abandoned position).
    """

    _DONE = object()

    def __init__(self, loader, depth: int = 2, stall_warn_s: float = 30.0,
                 join_timeout_s: float = 5.0):
        self.loader = loader
        self.depth = int(depth)
        self.stall_warn_s = float(stall_warn_s)
        self.join_timeout_s = float(join_timeout_s)
        self.data_wait_s = 0.0
        self.stall_warnings = 0
        self._it: "_PrefetchIterator | None" = None

    def __iter__(self):
        self._stop_stream()  # at most one live prefetch thread per loader
        self._it = _PrefetchIterator(self)
        return self._it

    def _stop_stream(self) -> None:
        """Stop and join the live prefetch iterator WITHOUT touching the
        wrapped loader (re-``iter()`` and rewind/load_state restart the
        stream; a plain-generator loader must survive the reset)."""
        it, self._it = self._it, None
        if it is not None:
            it.close()

    def close(self) -> None:
        self._stop_stream()
        # cascade: a wrapped WorkerLoader's spawn pool must not outlive
        # this loader (the producer thread is joined first so it cannot
        # race a live pool.map against the teardown)
        inner = getattr(self.loader, "close", None)
        if callable(inner):
            inner()

    def skips_at(self, consumed_samples: int):
        inner = getattr(self.loader, "skips_at", None)
        return inner(consumed_samples) if callable(inner) else None

    def stats(self) -> Dict[str, float]:
        inner = getattr(self.loader, "stats", None)
        out: Dict[str, float] = dict(inner()) if callable(inner) else {}
        out["data_wait_s"] = round(self.data_wait_s, 3)
        out["prefetch_depth"] = self._it.depth() if self._it is not None else 0
        out["stall_warnings"] = self.stall_warnings
        # mirror onto the process-wide telemetry registry (/metrics):
        # stats() runs at the engine's logging cadence, never per batch,
        # so this is off the hot path; cumulative values are exporter-set
        from paddlefleetx_tpu.utils.telemetry import get_registry

        reg = get_registry()
        reg.counter("pfx_data_wait_seconds_total").set(out["data_wait_s"])
        reg.gauge("pfx_data_prefetch_depth").set(out["prefetch_depth"])
        reg.counter("pfx_data_stall_warnings_total").set(out["stall_warnings"])
        if "skips" in out:
            reg.counter("pfx_data_skips_total").set(out["skips"])
        return out

    # -- iterator-state contract (delegates to the wrapped loader) ------
    def state_dict(self) -> Dict[str, int]:
        return self.loader.state_dict()

    def load_state(self, state: Dict[str, int]) -> None:
        self._stop_stream()
        self.loader.load_state(state)

    def rewind(self, consumed_samples: int) -> None:
        self._stop_stream()
        self.loader.rewind(consumed_samples)

    # skip accounting surfaces through the wrapper so the engine sees one
    # uniform loader interface regardless of the prefetch layer
    @property
    def skips(self) -> int:
        return getattr(self.loader, "skips", 0)

    @property
    def skip_events(self) -> List[Dict]:
        return getattr(self.loader, "skip_events", [])
