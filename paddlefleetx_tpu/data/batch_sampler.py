"""Distributed batch sampling + host batch assembly.

Reference: GPTBatchSampler (ppfleetx/data/sampler/batch_sampler.py:31-192) —
slices the global batch across the data-parallel world (dp × sharding ranks,
env.py:158-178) with ``consumed_samples`` resume support.

TPU-native difference: with pjit we assemble the *global* batch on host and
let ``jax.make_array_from_process_local_data`` scatter it; on a single host
the "rank slicing" is purely logical.  The sampler therefore yields global
batches of indices, and resume is a sample counter — the same contract the
reference's checkpoint meta carries.
"""

from __future__ import annotations

from typing import Dict, Iterator, Sequence

import numpy as np

from paddlefleetx_tpu.utils.registry import SAMPLERS


@SAMPLERS.register("GPTBatchSampler")
class DistributedBatchSampler:
    def __init__(
        self,
        dataset_len: int,
        batch_size: int,
        shuffle: bool = False,
        drop_last: bool = True,
        seed: int = 1234,
        consumed_samples: int = 0,
    ):
        self.n = int(dataset_len)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.consumed_samples = int(consumed_samples)
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {self.batch_size}")
        if self.drop_last and self.n < self.batch_size:
            # the epoch loop would otherwise spin forever yielding nothing
            # (observed as a silent eval hang on a 4-sample eval split)
            raise ValueError(
                f"dataset has {self.n} samples < batch_size {self.batch_size} "
                "with drop_last: no batch can ever be formed — lower the "
                "batch size (Global.eval_batch_size for eval) or grow the data"
            )

    def __iter__(self) -> Iterator[np.ndarray]:
        epoch = self.consumed_samples // self.n
        offset = self.consumed_samples % self.n
        while True:
            if self.shuffle:
                order = np.random.default_rng(self.seed + epoch).permutation(self.n)
            else:
                order = np.arange(self.n)
            for i in range(offset, self.n - self.batch_size + 1, self.batch_size):
                batch = order[i : i + self.batch_size]
                self.consumed_samples += len(batch)
                yield batch
            if not self.drop_last and (self.n - offset) % self.batch_size:
                tail = order[self.n - (self.n - offset) % self.batch_size :]
                self.consumed_samples += len(tail)
                yield tail
            epoch += 1
            offset = 0

    def state_dict(self) -> Dict[str, int]:
        return {"consumed_samples": self.consumed_samples}


def collate_stack(items: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """gpt_collate_fn analogue (reference batch_collate_fn.py:95: Tuple of
    Stack over tokens/position_ids/labels/loss_mask) — dict-of-stacked-arrays."""
    keys = items[0].keys()
    return {k: np.stack([it[k] for it in items]) for k in keys}


class DataLoader:
    """Minimal host data loader: sampler indices -> collated numpy batches.

    (The reference uses paddle.io.DataLoader worker processes; token datasets
    here are mmap reads + concatenation — cheap enough to do inline, and the
    engine overlaps host assembly with device steps via async dispatch.)
    """

    def __init__(self, dataset, sampler: DistributedBatchSampler, collate_fn=collate_stack):
        self.dataset = dataset
        self.sampler = sampler
        self.collate_fn = collate_fn

    def __iter__(self):
        for batch_idx in self.sampler:
            yield self.collate_fn([self.dataset[int(i)] for i in batch_idx])


class WorkerLoader:
    """Worker-process loader: the reference paddle.io.DataLoader
    ``num_workers`` analogue for decode-heavy datasets (image resize /
    augmentation dominate host time for the vision families).

    Workers use the ``spawn`` start method: the training process has live
    XLA/jax threads, and forking a threaded process can deadlock the
    child.  The dataset is pickled once into each worker at pool start
    (datasets and their transform pipelines are plain picklable objects),
    after which only indices and samples cross the pipe.  Worker startup
    costs a fresh interpreter (plus whatever sitecustomize preloads —
    the axon image preloads jax, ~3 s/worker); the pool lives for the
    whole epoch-looping iteration, so this is paid once per fit, not per
    batch.  Sample RNG streams stay deterministic per (seed, idx, visit)
    — but visit counters live per worker, so augmentation draws across
    epochs differ from the single-process order (same guarantee the
    reference's worker processes give).
    """

    def __init__(self, dataset, sampler: DistributedBatchSampler,
                 collate_fn=collate_stack, num_workers: int = 2):
        import inspect

        self.dataset = dataset
        self.sampler = sampler
        self.collate_fn = collate_fn
        self.num_workers = max(1, int(num_workers))
        # augmenting datasets key their RNG on (seed, idx, visit); the
        # visit counter must live HERE in the parent — per-worker counters
        # would make draws depend on which worker happened to serve a
        # sample (nondeterministic run-to-run, and epoch 2 frequently
        # replays epoch 1's draw when the sample lands on a fresh worker)
        self._visit_aware = "visit" in inspect.signature(
            dataset.__getitem__
        ).parameters
        self._visits: dict = {}

    def _visit(self, idx: int) -> int:
        v = self._visits.get(idx, 0)
        self._visits[idx] = v + 1
        return v

    def __iter__(self):
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        with ctx.Pool(
            self.num_workers, initializer=_worker_init, initargs=(self.dataset,)
        ) as pool:
            for batch_idx in self.sampler:
                if self._visit_aware:
                    work = [(int(i), self._visit(int(i))) for i in batch_idx]
                    items = pool.starmap(
                        _worker_get_visit, work,
                        chunksize=max(1, len(work) // self.num_workers),
                    )
                else:
                    items = pool.map(
                        _worker_get, [int(i) for i in batch_idx],
                        chunksize=max(1, len(batch_idx) // self.num_workers),
                    )
                yield self.collate_fn(items)


_WORKER_DATASET = None


def _worker_init(dataset):
    global _WORKER_DATASET
    _WORKER_DATASET = dataset


def _worker_get(idx: int):
    return _WORKER_DATASET[idx]


def _worker_get_visit(idx: int, visit: int):
    return _WORKER_DATASET.__getitem__(idx, visit)


class PrefetchLoader:
    """Background-thread prefetch over any batch iterable (reference
    paddle.io.DataLoader worker analogue): host batch assembly overlaps the
    device step instead of serializing after it.  ``depth`` bounds buffered
    batches (memory = depth x batch bytes)."""

    _DONE = object()

    def __init__(self, loader, depth: int = 2):
        self.loader = loader
        self.depth = int(depth)

    def __iter__(self):
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        err: list = []

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in self.loader:
                    if not put(item):
                        return  # consumer gone: drop buffers, exit thread
            except BaseException as e:  # surface in consumer thread
                err.append(e)
            finally:
                put(self._DONE)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._DONE:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # early consumer exit (max_steps break, exception, GC): unblock
            # and terminate the worker so buffers + thread are reclaimed
            stop.set()
