"""Dataset/dataloader builders from config (reference data/__init__.py:28-119)."""

from __future__ import annotations


from paddlefleetx_tpu.data.batch_sampler import DataLoader, DistributedBatchSampler, collate_stack
from paddlefleetx_tpu.parallel.seed import get_seed_tracker
from paddlefleetx_tpu.utils.registry import DATASETS


def build_dataset(cfg, mode: str, **extra):
    ds_cfg = dict(cfg.Data[mode].dataset)
    name = ds_cfg.pop("name")
    ds_cfg.setdefault("mode", mode)
    ds_cfg.update(extra)
    return DATASETS.get(name)(**ds_cfg)


def build_dataloader(cfg, mode: str, dataset=None, consumed_samples: int = 0) -> DataLoader:
    """Build dataset + sampler + loader for a config mode (Train/Eval/Test).

    The sampler yields *global* batches; dp-rank slicing is done by the
    device_put sharding, not the sampler (see batch_sampler.py docstring).
    ``consumed_samples`` (from a restored checkpoint's meta) resumes the
    data order mid-epoch (reference GPTBatchSampler batch_sampler.py:87,118).
    """
    if dataset is None:
        num_samples = None
        if mode == "Train":
            num_samples = int(cfg.Engine.max_steps) * int(cfg.Global.global_batch_size)
        dataset = build_dataset(cfg, mode, **({"num_samples": num_samples} if num_samples else {}))
    sampler_cfg = dict(cfg.Data[mode].get("sampler", {}))
    sampler = DistributedBatchSampler(
        dataset_len=len(dataset),
        batch_size=int(cfg.Global.global_batch_size)
        if mode == "Train"
        else int(cfg.Global.get("eval_batch_size", cfg.Global.global_batch_size)),
        shuffle=bool(sampler_cfg.get("shuffle", mode == "Train")),
        drop_last=bool(sampler_cfg.get("drop_last", True)),
        seed=get_seed_tracker().data_seed() if _seed_ready() else 1234,
        consumed_samples=consumed_samples,
    )
    loader_cfg = cfg.Data[mode].get("loader", {})
    num_workers = int(loader_cfg.get("num_workers", 0) or 0)
    max_skips = int(loader_cfg.get("max_skips", 0) or 0)
    if num_workers > 0:
        from paddlefleetx_tpu.data.batch_sampler import WorkerLoader

        if max_skips:
            from paddlefleetx_tpu.utils.log import logger

            logger.warning(
                "Data.%s.loader.max_skips is an inline-loader feature; "
                "WorkerLoader (num_workers>0) propagates sample errors "
                "loudly instead of substituting", mode
            )
        loader = WorkerLoader(dataset, sampler, collate_stack, num_workers)
    else:
        loader = DataLoader(dataset, sampler, collate_stack, max_skips=max_skips)
    prefetch = int(loader_cfg.get("prefetch", 0) or 0)
    if prefetch > 0:
        from paddlefleetx_tpu.data.batch_sampler import PrefetchLoader

        loader = PrefetchLoader(
            loader,
            depth=prefetch,
            stall_warn_s=float(loader_cfg.get("stall_warn_s", 30.0)),
        )
    return loader


def _seed_ready() -> bool:
    try:
        get_seed_tracker()
        return True
    except RuntimeError:
        return False
