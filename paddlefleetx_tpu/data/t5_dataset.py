"""T5 span-corruption pretraining dataset.

The reference ships T5 purely as a model library (modeling.py) with no
pretraining data path; this closes that gap so the T5 family trains
end-to-end from the CLI.  Windows come from the same mmap corpus format
(and window machinery) as GPTDataset; each window is corrupted with the
standard T5 scheme (random_spans_noise_mask, arXiv:1910.10683 §3.1.4 /
HF FlaxDataCollatorForT5MLM): ~``corruption_rate`` of tokens in spans of
mean length ``mean_span_len`` are replaced by one sentinel each in the
input; the target is each sentinel followed by the span it replaced, then
EOS.  Sentinels occupy the TOP of the vocab descending (extra_id_0 =
vocab_size-1 — the reference/HF layout the tokenizer also uses).
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

from paddlefleetx_tpu.data.gpt_dataset import GPTDataset
from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.registry import DATASETS


def random_spans_noise_mask(
    length: int,
    noise_density: float,
    mean_span_len: float,
    rng: np.random.Generator,
    max_spans: int = 100,
) -> np.ndarray:
    """Boolean [length] mask: True = noise (standard T5 partition scheme).

    ``max_spans`` caps the span count at the sentinel budget; the count is
    also bounded by what the partitions can express (each span needs >= 1
    noise token, the gaps need num_spans+1 >= 1 non-noise tokens)."""
    num_noise = int(round(length * noise_density))
    num_noise = min(max(num_noise, 1), length - 1)
    num_nonnoise = length - num_noise
    num_spans = int(round(num_noise / mean_span_len))
    num_spans = max(min(num_spans, num_noise, num_nonnoise - 1, max_spans), 1)

    def partition(total: int, parts: int) -> np.ndarray:
        # random composition of `total` into `parts` positive integers
        cuts = np.sort(rng.choice(total - 1, parts - 1, replace=False)) if parts > 1 else np.array([], np.int64)
        bounds = np.concatenate([[0], cuts + 1, [total]])
        return np.diff(bounds)

    noise_spans = partition(num_noise, num_spans)
    nonnoise_spans = partition(num_nonnoise, num_spans + 1)

    mask = np.zeros(length, bool)
    pos = nonnoise_spans[0]
    for i in range(num_spans):
        mask[pos : pos + noise_spans[i]] = True
        pos += noise_spans[i] + nonnoise_spans[i + 1]
    return mask


@DATASETS.register("T5PretrainDataset")
class T5PretrainDataset:
    """Yields input_ids [max_seq_len] and labels [max_target_len]."""

    def __init__(
        self,
        input_dir: str = None,
        data_prefix: str = None,
        split: Sequence[float] = (949, 50, 1),
        max_seq_len: int = 512,
        max_target_len: int = 128,
        corruption_rate: float = 0.15,
        mean_span_len: float = 3.0,
        vocab_size: int = 32128,
        num_sentinels: int = 100,
        pad_token_id: int = 0,
        eos_token_id: int = 1,
        num_samples: int = None,
        mode: str = "Train",
        seed: int = 1234,
        build_cache: bool = True,
        **_unused,
    ):
        self.base = GPTDataset(
            input_dir=input_dir,
            data_prefix=data_prefix,
            split=split,
            max_seq_len=max_seq_len,
            num_samples=num_samples,
            mode=mode,
            seed=seed,
            build_cache=build_cache,
        )
        self.enc_len = int(max_seq_len)
        self.dec_len = int(max_target_len)
        self.rate = float(corruption_rate)
        self.mean_span = float(mean_span_len)
        self.vocab_size = int(vocab_size)
        self.num_sentinels = int(num_sentinels)
        self.pad_id = int(pad_token_id)
        self.eos_id = int(eos_token_id)
        self.seed = int(seed)
        self.truncation_count = 0
        # expected target length must fit: each example carries ~rate*L
        # noise tokens + one sentinel per span + EOS (rare tails truncate)
        exp_noise = int(round(self.enc_len * self.rate))
        exp_spans = max(min(int(round(exp_noise / self.mean_span)), self.num_sentinels), 1)
        if exp_noise + exp_spans + 1 > self.dec_len:
            raise ValueError(
                f"max_target_len {self.dec_len} too small for "
                f"~{exp_noise} noise tokens + {exp_spans} sentinels + EOS at "
                f"corruption_rate {self.rate}, mean_span_len {self.mean_span} "
                f"(need >= {exp_noise + exp_spans + 1})"
            )

    def __len__(self) -> int:
        return len(self.base)

    def _sentinel(self, k: int) -> int:
        return self.vocab_size - 1 - k  # extra_id_k, descending layout

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        tokens = self.base[idx]["tokens"]  # [enc_len] raw window
        L = len(tokens)
        # per-sample span draws can exceed dec_len even when the expected
        # length fits (constructor check): re-draw the noise mask a few
        # times rather than silently dropping EOS and mid-span tokens
        for attempt in range(4):
            # attempt 0 keeps the historical (seed, idx) key so mid-epoch
            # resumes from pre-redraw-loop checkpoints see the identical
            # data stream; only actual redraws mix in the attempt term
            key = (self.seed, idx) if attempt == 0 else (self.seed, idx, attempt)
            rng = np.random.default_rng(key)
            mask = random_spans_noise_mask(
                L, self.rate, self.mean_span, rng, max_spans=self.num_sentinels
            )

            inputs, targets = [], []
            k = 0
            i = 0
            while i < L:
                if mask[i]:
                    sent = self._sentinel(k)
                    k += 1
                    inputs.append(sent)
                    targets.append(sent)
                    while i < L and mask[i]:
                        targets.append(int(tokens[i]))
                        i += 1
                else:
                    inputs.append(int(tokens[i]))
                    i += 1
            targets.append(self.eos_id)
            if len(targets) <= self.dec_len:
                break
        else:
            # pathological window: truncate but keep the EOS the decoder
            # trains to emit, and count it so the anomaly is observable
            targets = targets[: self.dec_len - 1] + [self.eos_id]
            self.truncation_count += 1
            if self.truncation_count in (1, 100, 10000):
                logger.warning(
                    f"t5 span-corruption target overflowed max_target_len "
                    f"{self.dec_len} after 4 redraws (sample {idx}; "
                    f"{self.truncation_count} total) — truncated, EOS kept"
                )

        inp = np.full(self.enc_len, self.pad_id, np.int64)
        inp[: min(len(inputs), self.enc_len)] = inputs[: self.enc_len]
        lab = np.full(self.dec_len, self.pad_id, np.int64)
        lab[: len(targets)] = targets
        return {"input_ids": inp, "labels": lab}
