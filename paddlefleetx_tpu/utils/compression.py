"""Model compression: magnitude pruning + int8 quantization.

TPU-native re-design of the reference compression helpers
(ppfleetx/utils/compression_helper.py:19-79: ``prune_model`` via PaddleSlim
GlobalMagnitude/L1/L2 pruning, ``quant_model`` via QAT).  PaddleSlim's
graph-rewriting machinery is replaced by pure pytree transforms:

  - prune_params:  per-tensor or global magnitude masks at a target ratio
    (criteria l1 / l2 / global-magnitude), applied to the matmul weights
    (ndim >= 2 leaves), returning (pruned_params, masks).  Masks can be
    re-applied after each optimizer step to keep sparsity during finetune.
  - quantize_params / dequantize_params: symmetric per-channel int8 PTQ
    for matmul weights; activations stay in bf16/fp32 (XLA has no int8
    activation kernels worth using off-TPU-int8 hardware here).
  - fake_quant: straight-through int8 fake-quantization for QAT-style
    finetuning (quant error in the forward, identity gradient).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def _is_weight(x: jax.Array) -> bool:
    return hasattr(x, "ndim") and x.ndim >= 2 and jnp.issubdtype(x.dtype, jnp.floating)


# ---------------------------------------------------------------------------
# Pruning
# ---------------------------------------------------------------------------


def prune_params(
    params: Any,
    ratio: float,
    criterion: str = "l1",
    global_ranking: bool = False,
) -> Tuple[Any, Any]:
    """Zero out the smallest-magnitude fraction ``ratio`` of weight entries.

    criterion 'l1' |weights| or 'l2' weights^2 (same ordering); ranking per
    tensor by default, or across ALL weight tensors when global_ranking
    (reference GlobalMagnitudePruner).  Returns (pruned, masks) where masks
    has a boolean leaf per weight tensor (None-like ones for non-weights).
    """
    assert 0.0 <= ratio < 1.0
    score_fn = jnp.abs if criterion == "l1" else jnp.square

    leaves, treedef = jax.tree.flatten(params)
    weight_idx = [i for i, x in enumerate(leaves) if _is_weight(x)]

    if global_ranking and weight_idx:
        all_scores = jnp.concatenate([score_fn(leaves[i]).ravel() for i in weight_idx])
        k = int(ratio * all_scores.size)
        thresh = jnp.sort(all_scores)[k] if k > 0 else -jnp.inf
        masks_w = {i: score_fn(leaves[i]) >= thresh for i in weight_idx}
    else:
        masks_w = {}
        for i in weight_idx:
            s = score_fn(leaves[i]).ravel()
            k = int(ratio * s.size)
            thresh = jnp.sort(s)[k] if k > 0 else -jnp.inf
            masks_w[i] = score_fn(leaves[i]) >= thresh

    new_leaves = list(leaves)
    mask_leaves = [jnp.ones_like(x, bool) if hasattr(x, "shape") else x for x in leaves]
    for i in weight_idx:
        new_leaves[i] = jnp.where(masks_w[i], leaves[i], 0.0)
        mask_leaves[i] = masks_w[i]
    return jax.tree.unflatten(treedef, new_leaves), jax.tree.unflatten(treedef, mask_leaves)


def apply_masks(params: Any, masks: Any) -> Any:
    """Re-apply pruning masks (after an optimizer step, sparse finetune)."""
    return jax.tree.map(
        lambda p, m: jnp.where(m, p, 0.0) if _is_weight(p) else p, params, masks
    )


def sparsity(params: Any) -> float:
    """Fraction of exactly-zero entries across weight tensors."""
    total, zeros = 0, 0
    for x in jax.tree.leaves(params):
        if _is_weight(x):
            total += x.size
            zeros += int(jnp.sum(x == 0.0))
    return zeros / max(total, 1)


# ---------------------------------------------------------------------------
# Quantization
# ---------------------------------------------------------------------------


def _chan_scale(w: jax.Array) -> jax.Array:
    """Symmetric absmax scale per output channel (last dim)."""
    reduce_axes = tuple(range(w.ndim - 1))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    return jnp.maximum(absmax, 1e-8) / 127.0


def quantize_params(params: Any) -> Tuple[Any, Any]:
    """Weights -> int8 + fp32 per-channel scales; non-weights untouched.

    Returns (q_params, scales): q leaf is int8 where quantized; scale leaf
    is the multiplier to recover floats (None marker = not quantized)."""

    def q(x):
        if not _is_weight(x):
            return x, None
        s = _chan_scale(x)
        return jnp.clip(jnp.round(x / s), -127, 127).astype(jnp.int8), s

    leaves, treedef = jax.tree.flatten(params)
    pairs = [q(x) for x in leaves]
    return (
        jax.tree.unflatten(treedef, [p[0] for p in pairs]),
        jax.tree.unflatten(treedef, [p[1] if p[1] is not None else () for p in pairs]),
    )


def dequantize_params(q_params: Any, scales: Any, dtype=jnp.float32) -> Any:
    def dq(x, s):
        if isinstance(s, tuple):  # () marker: not quantized
            return x
        return (x.astype(dtype)) * s.astype(dtype)

    return jax.tree.map(dq, q_params, scales, is_leaf=lambda x: isinstance(x, tuple) and x == ())


def quant_error(params: Any) -> float:
    """Max relative reconstruction error over weight tensors (sanity)."""
    qp, sc = quantize_params(params)
    deq = dequantize_params(qp, sc)
    err = 0.0
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(deq)):
        if _is_weight(a):
            denom = float(jnp.max(jnp.abs(a))) + 1e-12
            err = max(err, float(jnp.max(jnp.abs(a - b))) / denom)
    return err


@jax.custom_vjp
def fake_quant(w: jax.Array) -> jax.Array:
    """QAT fake-quantization: int8 rounding in the forward, straight-through
    gradient (reference quant_model QAT semantics)."""
    s = _chan_scale(w)
    return jnp.clip(jnp.round(w / s), -127, 127) * s


def _fq_fwd(w):
    return fake_quant(w), None


def _fq_bwd(_, g):
    return (g,)


fake_quant.defvjp(_fq_fwd, _fq_bwd)


def build_qat_transform(compress_cfg) -> Optional[Callable[[Any], Any]]:
    """Config-gated QAT param transform (reference ``Compress.Quantization``
    blocks, e.g. qat_gpt_345M_mp8.yaml:37-52 — PaddleSlim's graph rewrite
    becomes a pytree transform applied to params inside the loss).

    Returns None when QAT is disabled; otherwise a function mapping the
    param tree to one with matmul weights fake-quantized in the forward
    (straight-through gradients, so the optimizer still updates the
    full-precision master weights — the definition of QAT).

    Config keys honored: ``enable``, ``weight_bits`` (must be 8),
    ``freeze_embedding`` (skip embedding tables, default True),
    ``skip_tensors`` (path-substring excludes, the reference
    ``skip_tensor_map`` analogue)."""
    if not compress_cfg:
        return None
    q = compress_cfg.get("Quantization", {})
    if not q or not bool(q.get("enable", False)):
        return None
    bits = int(q.get("weight_bits", 8))
    if bits != 8:
        raise ValueError(f"QAT supports weight_bits=8, got {bits}")
    freeze_embedding = bool(q.get("freeze_embedding", True))
    skip = tuple(q.get("skip_tensors", []) or [])

    def transform(params: Any) -> Any:
        def fq(path, x):
            if not _is_weight(x):
                return x
            name = jax.tree_util.keystr(path)
            if freeze_embedding and any(
                k in name for k in ("embedding", "word", "position", "token_type")
            ):
                return x
            if any(s in name for s in skip):
                return x
            return fake_quant(x)

        return jax.tree_util.tree_map_with_path(fq, params)

    return transform


def quantize_tree_for_export(params: Any) -> Dict[str, Any]:
    """Package for the export path: {'q': int8 tree, 'scales': tree}."""
    q, s = quantize_params(params)
    return {"q": q, "scales": s}
