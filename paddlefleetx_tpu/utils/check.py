"""Environment sanity checks (reference ppfleetx/utils/check.py:27-60:
check_version gates on a compiled-with-CUDA Paddle; here the gates are the
JAX version floor and backend availability)."""

from __future__ import annotations

from paddlefleetx_tpu.utils.log import logger

MIN_JAX_VERSION = (0, 4, 30)


def check_version() -> None:
    """Fail fast on a jax too old for the shard_map schedules (0.4.30+:
    the floor of parallel/shard_map_compat.py's full-manual branch)."""
    import re

    import jax

    ver = tuple(
        int(re.match(r"\d+", x).group()) if re.match(r"\d+", x) else 0
        for x in jax.__version__.split(".")[:3]
    )
    if ver < MIN_JAX_VERSION:
        raise RuntimeError(
            f"paddlefleetx_tpu needs jax >= {'.'.join(map(str, MIN_JAX_VERSION))}, "
            f"found {jax.__version__}"
        )


def check_device(device: str = "tpu") -> None:
    """Warn (not fail) when the requested platform is absent: the same
    program runs on the virtual CPU mesh (reference check_device aborts —
    here every layout is CPU-runnable by design)."""
    import jax

    platforms = {d.platform for d in jax.devices()}
    if device not in platforms:
        logger.warning(
            f"requested device '{device}' not present (have {sorted(platforms)}); "
            "running on the available backend"
        )
