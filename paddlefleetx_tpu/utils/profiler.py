"""Profiler integration (config-gated, reference eager_engine.py:250-272,
419-420, 866-925: paddle.profiler scheduler window + chrome-trace export +
sorted Device/Kernel/Operator/Memory summary tables on finish).

TPU-native: ``jax.profiler`` writes an XPlane/TensorBoard trace for the
configured step window.  Config block::

    Profiler:
      enable: True
      scheduler: [3, 8]     # [start_step, stop_step)
      log_dir: ./profiler_log
      summary: True         # emit sorted op/memory summaries on close
      summary_top: 20       # rows in the printed op table

On trace close the hook additionally converts the captured XPlane into
the reference's printed summary views (eager_engine.py:866-925):
``summary_ops.txt`` (per-HLO-op total/self time, sorted), the raw
``hlo_stats.json``, and ``summary_memory.txt`` (live device memory stats
when the backend exposes them).  Conversion uses the xprof toolchain when
importable and degrades to trace-only with a warning otherwise.

The parsing layer is module-level (``newest_run_dir`` / ``hlo_stats_rows``
/ ``trace_event_rows`` / ``op_summary_rows`` / ``device_host_split``) so
the on-demand serving capture (``capture_profile``, behind ``POST
/admin/profile`` in tools/serve.py) reuses the exact same toolchain as
the training hook.  ``capture_profile`` enforces the two safety rules
for profiling a *production* replica: one capture at a time per process
(``ProfileBusy`` -> HTTP 409) and a hard duration cap
(``PFX_PROFILE_MAX_SECONDS``, default 30 -> HTTP 400 when exceeded).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax

from paddlefleetx_tpu.utils.log import logger

# one capture at a time per process: jax.profiler.start_trace is a global
# singleton, so a second concurrent capture would either crash or corrupt
# the first — refuse loudly instead (serve.py maps ProfileBusy to 409)
_CAPTURE_LOCK = threading.Lock()


class ProfileBusy(RuntimeError):
    """A profile capture is already active in this process."""


def profile_max_seconds() -> float:
    """Hard cap on an on-demand capture window (PFX_PROFILE_MAX_SECONDS,
    default 30): profiling stalls nothing, but traces grow with wall time
    and an unbounded window on a production replica is an outage hazard."""
    from paddlefleetx_tpu.utils.telemetry import _env_float

    return _env_float("PFX_PROFILE_MAX_SECONDS", 30.0, minimum=0.001)


def newest_run_dir(log_dir: str) -> str:
    """The newest TensorBoard profile run directory under ``log_dir``."""
    import glob

    runs = sorted(glob.glob(os.path.join(log_dir, "plugins", "profile", "*")))
    if not runs:
        raise FileNotFoundError(f"no profile runs under {log_dir}")
    return runs[-1]


def _newest_xplanes(log_dir: str):
    import glob

    run = newest_run_dir(log_dir)
    planes = sorted(glob.glob(os.path.join(run, "*.xplane.pb")))
    if not planes:
        raise FileNotFoundError(f"no xplane.pb under {run}")
    return planes


def hlo_stats_rows(log_dir: str) -> List[Dict[str, Any]]:
    """Per-HLO-op rows from xprof's hlo_stats tool (populated on real
    accelerator traces; CPU traces carry no device-op events)."""
    import json

    from xprof.convert import raw_to_tool_data  # lazy: pulls in TF

    planes = _newest_xplanes(log_dir)
    data, _ = raw_to_tool_data.xspace_to_tool_data(planes, "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    with open(os.path.join(log_dir, "hlo_stats.json"), "w") as f:
        f.write(data)

    table = json.loads(data)
    cols = [c["id"] for c in table["cols"]]
    idx = {name: cols.index(name) for name in
           ("category", "hlo_op_name", "occurrences", "total_time",
            "total_self_time")}
    rows = []
    for row in table.get("rows", []):
        vals = [cell.get("v") if isinstance(cell, dict) else cell for cell in row["c"]]
        rows.append({
            "op": vals[idx["hlo_op_name"]],
            "category": vals[idx["category"]],
            "occurrences": int(vals[idx["occurrences"]] or 0),
            "total_us": float(vals[idx["total_time"]] or 0.0),
            "self_us": float(vals[idx["total_self_time"]] or 0.0),
        })
    return rows


def _newest_trace_events(log_dir: str) -> List[Dict[str, Any]]:
    import glob
    import gzip
    import json

    run = newest_run_dir(log_dir)
    traces = sorted(glob.glob(os.path.join(run, "*.trace.json.gz")))
    if not traces:
        raise FileNotFoundError(f"no trace.json.gz under {run}")
    with gzip.open(traces[-1], "rt") as f:
        return json.load(f).get("traceEvents", [])


def trace_event_rows(log_dir: str) -> List[Dict[str, Any]]:
    """Fallback aggregation over the chrome-trace events: op name ->
    occurrences + summed duration.  Available on every backend."""
    agg: Dict[str, list] = {}
    for e in _newest_trace_events(log_dir):
        if e.get("ph") != "X" or "dur" not in e:
            continue
        entry = agg.setdefault(e.get("name", "?"), [0, 0.0])
        entry[0] += 1
        entry[1] += float(e["dur"])
    return [
        {"op": name, "category": "trace", "occurrences": n,
         "total_us": dur, "self_us": dur}
        for name, (n, dur) in agg.items()
    ]


def device_host_split(log_dir: str) -> Tuple[float, float]:
    """(device_us, host_us): summed complete-event durations split by
    whether the emitting process is a device plane.  The chrome trace
    names every pid via ``ph=="M"``/``process_name`` metadata; device
    planes are the ``/device:...`` ones (TPU/GPU streams), everything
    else (python threads, runtime) is host."""
    device_pids = set()
    events = _newest_trace_events(log_dir)
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = str((e.get("args") or {}).get("name", ""))
            if pname.startswith("/device:"):
                device_pids.add(e.get("pid"))
    device_us = host_us = 0.0
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if e.get("pid") in device_pids:
            device_us += float(e["dur"])
        else:
            host_us += float(e["dur"])
    return device_us, host_us


def op_summary_rows(log_dir: str, hlo_fn=None, trace_fn=None) -> Tuple[List[Dict[str, Any]], str]:
    """(rows sorted by self time desc, source label): hlo_stats when the
    xprof toolchain can parse the trace, chrome-trace events otherwise.
    ``hlo_fn``/``trace_fn`` override the row sources (ProfilerHook passes
    its bound methods so tests can stub a toolchain failure)."""
    try:
        rows = (hlo_fn or (lambda: hlo_stats_rows(log_dir)))()
        source = "hlo_stats"
    except Exception as e:  # noqa: BLE001 — xprof missing / schema drift
        logger.warning(f"profiler: hlo_stats unavailable ({e!r}); using trace events")
        rows = []
    if not rows:
        rows = (trace_fn or (lambda: trace_event_rows(log_dir)))()
        source = "trace events (backend emits no per-HLO device stats)"
    rows.sort(key=lambda r: -r["self_us"])
    return rows, source


def capture_profile(seconds: float, log_dir: str, top: int = 20) -> Dict[str, Any]:
    """Capture a ``jax.profiler`` trace of the LIVE process for ``seconds``
    and answer with the parsed summary — the whole ``POST /admin/profile``
    body in one call.  Raises ``ValueError`` on a bad/over-cap duration
    (-> 400) and ``ProfileBusy`` when a capture is already running
    (-> 409).  The capture adds no device sync: the profiler observes the
    running dispatch loop, it never drives it."""
    cap = profile_max_seconds()
    try:
        seconds = float(seconds)
    except (TypeError, ValueError):
        raise ValueError(f"profile seconds must be a number, got {seconds!r}") from None
    if not seconds > 0:
        raise ValueError(f"profile seconds must be > 0, got {seconds}")
    if seconds > cap:
        raise ValueError(
            f"profile seconds={seconds} exceeds PFX_PROFILE_MAX_SECONDS={cap} "
            f"(raise the cap explicitly if you really want a longer trace)"
        )
    if not _CAPTURE_LOCK.acquire(blocking=False):
        raise ProfileBusy(
            "a profile capture is already active in this process; "
            "retry after it finishes"
        )
    try:
        from paddlefleetx_tpu.utils.telemetry import get_registry

        os.makedirs(log_dir, exist_ok=True)
        t0 = time.monotonic()
        jax.profiler.start_trace(log_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        trace_s = time.monotonic() - t0
        reg = get_registry()
        reg.counter("pfx_profiler_traces_total").inc()
        reg.gauge("pfx_profiler_trace_seconds").set(round(trace_s, 3))
        rows, source = op_summary_rows(log_dir)
        try:
            device_us, host_us = device_host_split(log_dir)
        except Exception as e:  # noqa: BLE001 — split is best-effort
            logger.warning(f"profiler: device/host split unavailable ({e!r})")
            device_us = host_us = 0.0
        total_self = sum(r["self_us"] for r in rows) or 1.0
        top_ops = [
            {**r, "self_frac": round(r["self_us"] / total_self, 4)}
            for r in rows[: max(0, int(top))]
        ]
        return {
            "seconds": round(trace_s, 3),
            "trace_dir": log_dir,
            "source": source,
            "device_us": round(device_us, 1),
            "host_us": round(host_us, 1),
            "op_count": len(rows),
            "top_ops": top_ops,
        }
    finally:
        _CAPTURE_LOCK.release()


class ProfilerHook:
    """Start/stop jax.profiler.trace around a step window."""

    def __init__(self, cfg: Optional[Dict[str, Any]]):
        cfg = cfg or {}
        self.enabled = bool(cfg.get("enable", False))
        sched = cfg.get("scheduler") or [3, 8]
        try:
            ok = len(sched) == 2 and int(sched[0]) < int(sched[1])
        except (TypeError, ValueError):
            ok = False
        if not ok:
            if not self.enabled:
                # a malformed window must not abort runs that never profile
                sched = [3, 8]
            else:
                raise ValueError(
                    f"Profiler.scheduler must be [start_step, stop_step] with "
                    f"start < stop, got {sched}"
                )
        self.start_step, self.stop_step = int(sched[0]), int(sched[1])
        self.log_dir = os.path.abspath(cfg.get("log_dir", "./profiler_log"))
        self.summary = bool(cfg.get("summary", True))
        self.summary_top = int(cfg.get("summary_top", 20))
        self._active = False
        self._pending_summary = False
        self._trace_t0 = 0.0

    def step(self, step: int) -> None:
        """Call once per training step with the 1-based step counter."""
        if not self.enabled:
            return
        from paddlefleetx_tpu.utils.telemetry import (
            get_flight_recorder,
            get_registry,
        )

        if not self._active and self.start_step <= step < self.stop_step:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._trace_t0 = time.monotonic()
            get_flight_recorder().record(
                {"event": "profiler_trace_start", "step": step,
                 "log_dir": self.log_dir}
            )
            logger.info(f"profiler: trace started (steps {self.start_step}-{self.stop_step}) -> {self.log_dir}")
        elif self._active and step >= self.stop_step:
            jax.profiler.stop_trace()
            self._active = False
            # summaries lazily import the xprof/TF toolchain and parse the
            # whole trace — deferred to close() so the remaining training
            # steps (whose throughput is being measured) are not stalled
            self._pending_summary = True
            trace_s = time.monotonic() - self._trace_t0
            reg = get_registry()
            reg.counter("pfx_profiler_traces_total").inc()
            reg.gauge("pfx_profiler_trace_seconds").set(round(trace_s, 3))
            get_flight_recorder().record(
                {"event": "profiler_trace_stop", "step": step,
                 "trace_s": round(trace_s, 3)}
            )
            logger.info(f"profiler: trace written to {self.log_dir} (view with TensorBoard)")

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._pending_summary = True
        if getattr(self, "_pending_summary", False):
            self._pending_summary = False
            self._write_summary()

    # -- summary views (reference eager_engine.py:866-925) -----------------
    # thin instance seams over the module-level parsers: the on-demand
    # serving capture shares them, and tests stub toolchain failures here

    def _newest_run_dir(self) -> str:
        return newest_run_dir(self.log_dir)

    def _hlo_stats_rows(self):
        return hlo_stats_rows(self.log_dir)

    def _trace_event_rows(self):
        return trace_event_rows(self.log_dir)

    def _write_summary(self) -> None:
        if not self.summary:
            return
        try:
            self._write_op_summary()
        except Exception as e:  # noqa: BLE001 — summaries must never kill a run
            logger.warning(f"profiler: op summary unavailable ({e!r})")
        try:
            self._write_memory_summary()
        except Exception as e:  # noqa: BLE001
            logger.warning(f"profiler: memory summary unavailable ({e!r})")

    def _write_op_summary(self) -> None:
        rows, source = op_summary_rows(
            self.log_dir,
            hlo_fn=self._hlo_stats_rows,
            trace_fn=self._trace_event_rows,
        )
        total_self = sum(r["self_us"] for r in rows) or 1.0

        lines = [
            f"{'op':<56} {'category':<18} {'#':>6} "
            f"{'total us':>12} {'self us':>12} {'self %':>7}"
        ]
        for r in rows[: self.summary_top]:
            lines.append(
                f"{str(r['op'])[:56]:<56} {str(r['category'])[:18]:<18} "
                f"{r['occurrences']:>6} {r['total_us']:>12.1f} "
                f"{r['self_us']:>12.1f} {100.0 * r['self_us'] / total_self:>7.2f}"
            )
        report = "\n".join(lines)
        path = os.path.join(self.log_dir, "summary_ops.txt")
        with open(path, "w") as f:
            f.write(f"source: {source}\n" + report + "\n")
        logger.info(
            f"profiler: op summary (top {min(self.summary_top, len(rows))} of "
            f"{len(rows)} by self time, {source}) -> {path}\n{report}"
        )

    def _write_memory_summary(self) -> None:
        lines = []
        for dev in jax.local_devices():
            stats = dev.memory_stats()
            if not stats:
                continue
            lines.append(f"{dev}:")
            for key in sorted(stats):
                lines.append(f"  {key:<32} {stats[key]}")
        path = os.path.join(self.log_dir, "summary_memory.txt")
        with open(path, "w") as f:
            if lines:
                f.write("\n".join(lines) + "\n")
            else:
                f.write("backend exposes no memory_stats(); see the trace's "
                        "memory_profile tool instead\n")
        logger.info(f"profiler: memory summary -> {path}")
