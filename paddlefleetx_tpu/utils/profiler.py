"""Profiler integration (config-gated, reference eager_engine.py:250-272,
419-420, 866-925: paddle.profiler scheduler window + chrome-trace export +
sorted Device/Kernel/Operator/Memory summary tables on finish).

TPU-native: ``jax.profiler`` writes an XPlane/TensorBoard trace for the
configured step window.  Config block::

    Profiler:
      enable: True
      scheduler: [3, 8]     # [start_step, stop_step)
      log_dir: ./profiler_log
      summary: True         # emit sorted op/memory summaries on close
      summary_top: 20       # rows in the printed op table

On trace close the hook additionally converts the captured XPlane into
the reference's printed summary views (eager_engine.py:866-925):
``summary_ops.txt`` (per-HLO-op total/self time, sorted), the raw
``hlo_stats.json``, and ``summary_memory.txt`` (live device memory stats
when the backend exposes them).  Conversion uses the xprof toolchain when
importable and degrades to trace-only with a warning otherwise.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

import jax

from paddlefleetx_tpu.utils.log import logger


class ProfilerHook:
    """Start/stop jax.profiler.trace around a step window."""

    def __init__(self, cfg: Optional[Dict[str, Any]]):
        cfg = cfg or {}
        self.enabled = bool(cfg.get("enable", False))
        sched = cfg.get("scheduler") or [3, 8]
        try:
            ok = len(sched) == 2 and int(sched[0]) < int(sched[1])
        except (TypeError, ValueError):
            ok = False
        if not ok:
            if not self.enabled:
                # a malformed window must not abort runs that never profile
                sched = [3, 8]
            else:
                raise ValueError(
                    f"Profiler.scheduler must be [start_step, stop_step] with "
                    f"start < stop, got {sched}"
                )
        self.start_step, self.stop_step = int(sched[0]), int(sched[1])
        self.log_dir = os.path.abspath(cfg.get("log_dir", "./profiler_log"))
        self.summary = bool(cfg.get("summary", True))
        self.summary_top = int(cfg.get("summary_top", 20))
        self._active = False
        self._pending_summary = False
        self._trace_t0 = 0.0

    def step(self, step: int) -> None:
        """Call once per training step with the 1-based step counter."""
        if not self.enabled:
            return
        from paddlefleetx_tpu.utils.telemetry import (
            get_flight_recorder,
            get_registry,
        )

        if not self._active and self.start_step <= step < self.stop_step:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._trace_t0 = time.monotonic()
            get_flight_recorder().record(
                {"event": "profiler_trace_start", "step": step,
                 "log_dir": self.log_dir}
            )
            logger.info(f"profiler: trace started (steps {self.start_step}-{self.stop_step}) -> {self.log_dir}")
        elif self._active and step >= self.stop_step:
            jax.profiler.stop_trace()
            self._active = False
            # summaries lazily import the xprof/TF toolchain and parse the
            # whole trace — deferred to close() so the remaining training
            # steps (whose throughput is being measured) are not stalled
            self._pending_summary = True
            trace_s = time.monotonic() - self._trace_t0
            reg = get_registry()
            reg.counter("pfx_profiler_traces_total").inc()
            reg.gauge("pfx_profiler_trace_seconds").set(round(trace_s, 3))
            get_flight_recorder().record(
                {"event": "profiler_trace_stop", "step": step,
                 "trace_s": round(trace_s, 3)}
            )
            logger.info(f"profiler: trace written to {self.log_dir} (view with TensorBoard)")

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._pending_summary = True
        if getattr(self, "_pending_summary", False):
            self._pending_summary = False
            self._write_summary()

    # -- summary views (reference eager_engine.py:866-925) -----------------

    def _write_summary(self) -> None:
        if not self.summary:
            return
        try:
            self._write_op_summary()
        except Exception as e:  # noqa: BLE001 — summaries must never kill a run
            logger.warning(f"profiler: op summary unavailable ({e!r})")
        try:
            self._write_memory_summary()
        except Exception as e:  # noqa: BLE001
            logger.warning(f"profiler: memory summary unavailable ({e!r})")

    def _newest_run_dir(self) -> str:
        import glob

        runs = sorted(glob.glob(os.path.join(self.log_dir, "plugins", "profile", "*")))
        if not runs:
            raise FileNotFoundError(f"no profile runs under {self.log_dir}")
        return runs[-1]

    def _newest_xplanes(self):
        import glob

        run = self._newest_run_dir()
        planes = sorted(glob.glob(os.path.join(run, "*.xplane.pb")))
        if not planes:
            raise FileNotFoundError(f"no xplane.pb under {run}")
        return planes

    def _hlo_stats_rows(self):
        """Per-HLO-op rows from xprof's hlo_stats tool (populated on real
        accelerator traces; CPU traces carry no device-op events)."""
        import json

        from xprof.convert import raw_to_tool_data  # lazy: pulls in TF

        planes = self._newest_xplanes()
        data, _ = raw_to_tool_data.xspace_to_tool_data(planes, "hlo_stats", {})
        if isinstance(data, bytes):
            data = data.decode()
        with open(os.path.join(self.log_dir, "hlo_stats.json"), "w") as f:
            f.write(data)

        table = json.loads(data)
        cols = [c["id"] for c in table["cols"]]
        idx = {name: cols.index(name) for name in
               ("category", "hlo_op_name", "occurrences", "total_time",
                "total_self_time")}
        rows = []
        for row in table.get("rows", []):
            vals = [cell.get("v") if isinstance(cell, dict) else cell for cell in row["c"]]
            rows.append({
                "op": vals[idx["hlo_op_name"]],
                "category": vals[idx["category"]],
                "occurrences": int(vals[idx["occurrences"]] or 0),
                "total_us": float(vals[idx["total_time"]] or 0.0),
                "self_us": float(vals[idx["total_self_time"]] or 0.0),
            })
        return rows

    def _trace_event_rows(self):
        """Fallback aggregation over the chrome-trace events: op name ->
        occurrences + summed duration.  Available on every backend."""
        import glob
        import gzip
        import json

        run = self._newest_run_dir()
        traces = sorted(glob.glob(os.path.join(run, "*.trace.json.gz")))
        if not traces:
            raise FileNotFoundError(f"no trace.json.gz under {run}")
        agg: Dict[str, list] = {}
        with gzip.open(traces[-1], "rt") as f:
            events = json.load(f).get("traceEvents", [])
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            entry = agg.setdefault(e.get("name", "?"), [0, 0.0])
            entry[0] += 1
            entry[1] += float(e["dur"])
        return [
            {"op": name, "category": "trace", "occurrences": n,
             "total_us": dur, "self_us": dur}
            for name, (n, dur) in agg.items()
        ]

    def _write_op_summary(self) -> None:
        try:
            rows = self._hlo_stats_rows()
            source = "hlo_stats"
        except Exception as e:  # noqa: BLE001 — xprof missing / schema drift
            logger.warning(f"profiler: hlo_stats unavailable ({e!r}); using trace events")
            rows = []
        if not rows:
            rows = self._trace_event_rows()
            source = "trace events (backend emits no per-HLO device stats)"
        rows.sort(key=lambda r: -r["self_us"])
        total_self = sum(r["self_us"] for r in rows) or 1.0

        lines = [
            f"{'op':<56} {'category':<18} {'#':>6} "
            f"{'total us':>12} {'self us':>12} {'self %':>7}"
        ]
        for r in rows[: self.summary_top]:
            lines.append(
                f"{str(r['op'])[:56]:<56} {str(r['category'])[:18]:<18} "
                f"{r['occurrences']:>6} {r['total_us']:>12.1f} "
                f"{r['self_us']:>12.1f} {100.0 * r['self_us'] / total_self:>7.2f}"
            )
        report = "\n".join(lines)
        path = os.path.join(self.log_dir, "summary_ops.txt")
        with open(path, "w") as f:
            f.write(f"source: {source}\n" + report + "\n")
        logger.info(
            f"profiler: op summary (top {min(self.summary_top, len(rows))} of "
            f"{len(rows)} by self time, {source}) -> {path}\n{report}"
        )

    def _write_memory_summary(self) -> None:
        lines = []
        for dev in jax.local_devices():
            stats = dev.memory_stats()
            if not stats:
                continue
            lines.append(f"{dev}:")
            for key in sorted(stats):
                lines.append(f"  {key:<32} {stats[key]}")
        path = os.path.join(self.log_dir, "summary_memory.txt")
        with open(path, "w") as f:
            if lines:
                f.write("\n".join(lines) + "\n")
            else:
                f.write("backend exposes no memory_stats(); see the trace's "
                        "memory_profile tool instead\n")
        logger.info(f"profiler: memory summary -> {path}")
