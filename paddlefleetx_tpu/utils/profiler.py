"""Profiler integration (config-gated, reference eager_engine.py:250-272,
419-420, 866-925: paddle.profiler scheduler window + chrome-trace export).

TPU-native: ``jax.profiler`` writes an XPlane/TensorBoard trace for the
configured step window.  Config block::

    Profiler:
      enable: True
      scheduler: [3, 8]     # [start_step, stop_step)
      log_dir: ./profiler_log

View with TensorBoard's profile plugin (or xprof).  Per-step op/memory
summary views come from the trace viewer instead of the reference's
printed tables.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax

from paddlefleetx_tpu.utils.log import logger


class ProfilerHook:
    """Start/stop jax.profiler.trace around a step window."""

    def __init__(self, cfg: Optional[Dict[str, Any]]):
        cfg = cfg or {}
        self.enabled = bool(cfg.get("enable", False))
        sched = cfg.get("scheduler") or [3, 8]
        try:
            ok = len(sched) == 2 and int(sched[0]) < int(sched[1])
        except (TypeError, ValueError):
            ok = False
        if not ok:
            if not self.enabled:
                # a malformed window must not abort runs that never profile
                sched = [3, 8]
            else:
                raise ValueError(
                    f"Profiler.scheduler must be [start_step, stop_step] with "
                    f"start < stop, got {sched}"
                )
        self.start_step, self.stop_step = int(sched[0]), int(sched[1])
        self.log_dir = os.path.abspath(cfg.get("log_dir", "./profiler_log"))
        self._active = False

    def step(self, step: int) -> None:
        """Call once per training step with the 1-based step counter."""
        if not self.enabled:
            return
        if not self._active and self.start_step <= step < self.stop_step:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            logger.info(f"profiler: trace started (steps {self.start_step}-{self.stop_step}) -> {self.log_dir}")
        elif self._active and step >= self.stop_step:
            jax.profiler.stop_trace()
            self._active = False
            logger.info(f"profiler: trace written to {self.log_dir} (view with TensorBoard)")

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
