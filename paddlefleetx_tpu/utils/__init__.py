"""Config system, logging, registries, export, compression, profiler."""
