"""Archive/file helpers (reference ppfleetx/utils/file.py:26-80:
unzip/untar/parse_csv used by the pretrained-download path)."""

from __future__ import annotations

import csv
import os
import tarfile
import zipfile
from typing import Any, Dict, List, Optional


def unzip(zip_path: str, out_dir: Optional[str] = None, delete: bool = False) -> str:
    out_dir = out_dir or os.path.dirname(zip_path)
    with zipfile.ZipFile(zip_path, "r") as z:
        z.extractall(out_dir)
    if delete:
        os.remove(zip_path)
    return out_dir


def untar(tar_path: str, mode: str = "r:*", out_dir: Optional[str] = None,
          delete: bool = False) -> str:
    out_dir = out_dir or os.path.dirname(tar_path)
    with tarfile.open(tar_path, mode) as t:
        t.extractall(out_dir, filter="data")  # refuse path-escape members
    if delete:
        os.remove(tar_path)
    return out_dir


def parse_csv(path: str, delimiter: str = ",") -> List[Dict[str, Any]]:
    with open(path, newline="") as f:
        return list(csv.DictReader(f, delimiter=delimiter))
