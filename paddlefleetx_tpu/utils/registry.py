"""Typed registries replacing the reference's ``eval()``-based name dispatch
(e.g. models/__init__.py:30-34, data/__init__.py:69-119, optims/__init__.py:29-74)."""

from __future__ import annotations

from typing import Any, Callable, Dict


class Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, Callable[..., Any]] = {}

    def register(self, name: str = None):
        def deco(fn: Callable[..., Any]) -> Callable[..., Any]:
            key = name or fn.__name__
            if key in self._entries:
                raise KeyError(f"{self.kind} {key!r} already registered")
            self._entries[key] = fn
            return fn

        return deco

    def get(self, name: str) -> Callable[..., Any]:
        if name not in self._entries:
            raise KeyError(
                f"unknown {self.kind} {name!r}; known: {sorted(self._entries)}"
            )
        return self._entries[name]

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def names(self):
        return sorted(self._entries)


MODULES = Registry("module")
DATASETS = Registry("dataset")
SAMPLERS = Registry("sampler")
COLLATES = Registry("collate_fn")
OPTIMIZERS = Registry("optimizer")
LR_SCHEDULERS = Registry("lr_scheduler")
TOKENIZERS = Registry("tokenizer")
