"""Rank-aware colored logger (reference ppfleetx/utils/log.py:65-189)."""

from __future__ import annotations

import logging
import sys
import time
from typing import Optional

_LOGGER: Optional[logging.Logger] = None

_COLORS = {
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[35m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        color = _COLORS.get(record.levelname, "")
        prefix = f"{color}[{time.strftime('%Y-%m-%d %H:%M:%S')}] [{record.levelname:>7s}]{_RESET}"
        return f"{prefix} {record.getMessage()}"


def get_logger(name: str = "paddlefleetx_tpu") -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        lg = logging.getLogger(name)
        lg.setLevel(logging.INFO)
        lg.propagate = False
        if not lg.handlers:
            h = logging.StreamHandler(sys.stdout)
            h.setFormatter(_ColorFormatter())
            lg.addHandler(h)
        _LOGGER = lg
    return _LOGGER


logger = get_logger()


def advertise() -> None:
    """Startup banner (reference log.py:153)."""
    logger.info("=" * 60)
    logger.info("PaddleFleetX-TPU: TPU-native big model toolkit (JAX/XLA/Pallas)")
    logger.info("=" * 60)


def log_server_error(surface: str, code: int, path: str, **fields) -> None:
    """ONE structured line for every 5xx a serving surface writes
    (docs/observability.md): ``key=value`` pairs an operator can grep
    and join against the trace timeline — trace_id (when the request
    was sampled), replica_id, tenant, outcome.  None/empty fields are
    dropped so the line carries only what the handler actually knew;
    values are quoted when they contain spaces."""
    parts = [f"surface={surface}", f"code={code}", f"path={path}"]
    for key in sorted(fields):
        val = fields[key]
        if val is None or val == "":
            continue
        sval = str(val)
        if " " in sval:
            sval = '"' + sval.replace('"', "'") + '"'
        parts.append(f"{key}={sval}")
    logger.error("http_5xx " + " ".join(parts))
