"""Config system: YAML + ``_base_`` inheritance + dot-path CLI overrides.

Re-creates the user-facing config UX of the reference's
``ppfleetx/utils/config.py`` (AttrDict :192-223, ``parse_config`` with
``_base_`` includes :242-281, ``-o key.sub=val`` override grammar :333-395,
semantic passes ``process_dist_config`` :33-101 / ``process_global_configs``
:104-148 / ``process_engine_config`` :151-189) — with explicit validation
instead of ``eval()``-based dispatch.

Config sections (same vocabulary as the reference YAML trees):

    Global:       device, seed, batch sizes (global/local/micro)
    Engine:       max_steps, eval_freq, save/load, mix_precision, accumulate
    Distributed:  dp_degree, mp_degree, pp_degree, sharding, moe, sequence_parallel
    Model:        model family + hyperparams
    Data:         Train/Eval dataset+loader specs
    Optimizer:    name, lr schedule, grad clip
    Profiler:     optional jax.profiler trace window
"""

from __future__ import annotations

import argparse
import copy
import os
from typing import Any, Dict, List, Optional

import yaml


class AttrDict(dict):
    """Recursive attribute-style dict (reference utils/config.py:192-223)."""

    def __getattr__(self, key: str) -> Any:
        try:
            return self[key]
        except KeyError as e:
            raise AttributeError(key) from e

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def __deepcopy__(self, memo: Dict[int, Any]) -> "AttrDict":
        return AttrDict({copy.deepcopy(k, memo): copy.deepcopy(v, memo) for k, v in self.items()})

    @staticmethod
    def from_nested(d: Any) -> Any:
        if isinstance(d, dict):
            return AttrDict({k: AttrDict.from_nested(v) for k, v in d.items()})
        if isinstance(d, (list, tuple)):
            return type(d)(AttrDict.from_nested(v) for v in d)
        return d

    def to_dict(self) -> Dict[str, Any]:
        def conv(v: Any) -> Any:
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [conv(x) for x in v]
            return v

        return conv(self)


def _deep_merge(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Merge ``override`` into ``base`` recursively (override wins)."""
    out = dict(base)
    for k, v in override.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def parse_config(path: str) -> AttrDict:
    """Load a YAML config, resolving ``_base_`` includes relative to the file.

    ``_base_`` may be a string or list of strings; later bases and the file
    itself override earlier ones.  A section value of ``_inherited_: False``
    drops the inherited section entirely (reference config.py:242-281).
    """
    with open(path, "r") as f:
        raw = yaml.safe_load(f) or {}

    bases = raw.pop("_base_", [])
    if isinstance(bases, str):
        bases = [bases]
    merged: Dict[str, Any] = {}
    for base in bases:
        base_path = os.path.join(os.path.dirname(path), base)
        merged = _deep_merge(merged, parse_config(base_path).to_dict())
    merged = _deep_merge(merged, raw)

    def drop_non_inherited(d: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                if v.get("_inherited_", True) is False:
                    continue
                out[k] = drop_non_inherited(v)
            else:
                out[k] = v
        return out

    return AttrDict.from_nested(drop_non_inherited(merged))


def _parse_value(text: str) -> Any:
    """Parse an override value with YAML semantics (``'True'``→bool etc.)."""
    try:
        return yaml.safe_load(text)
    except yaml.YAMLError:
        return text


def override_config(cfg: AttrDict, overrides: Optional[List[str]]) -> AttrDict:
    """Apply ``key.sub.path=value`` overrides (reference config.py:333-395)."""
    for item in overrides or []:
        if "=" not in item:
            raise ValueError(f"override must be key=value, got {item!r}")
        key, value = item.split("=", 1)
        parts = key.split(".")
        node: Any = cfg
        for p in parts[:-1]:
            if p not in node or not isinstance(node[p], dict):
                node[p] = AttrDict()
            node = node[p]
        node[parts[-1]] = AttrDict.from_nested(_parse_value(value))
    return cfg


# ---------------------------------------------------------------------------
# Semantic passes
# ---------------------------------------------------------------------------


def process_dist_config(cfg: AttrDict, num_devices: Optional[int] = None) -> AttrDict:
    """Fill/validate parallel degrees (reference config.py:33-101).

    dp_degree is inferred as ``num_devices / (mp * pp * sharding)`` when
    unset; all degrees must multiply to the device count.
    """
    dist = cfg.setdefault("Distributed", AttrDict())
    if num_devices is None:
        import jax

        num_devices = jax.device_count()

    mp = int(dist.get("mp_degree", 1) or 1)
    pp = int(dist.get("pp_degree", 1) or 1)
    sep = int(dist.get("sep_degree", 1) or 1)  # Ulysses/ring context axis
    sharding_cfg = dist.setdefault("sharding", AttrDict())
    sd = int(sharding_cfg.get("sharding_degree", 1) or 1)
    sharding_cfg.sharding_degree = sd
    # a configured degree without an explicit stage means ZeRO-1 (the
    # reference requires an explicit stage; stage-0 + degree>1 would be a
    # silent no-op that loses all memory savings)
    sharding_cfg.setdefault("sharding_stage", 1 if sd > 1 else 0)
    # accept both spellings; the engine reads the normalized one
    sharding_cfg.sharding_offload = bool(
        sharding_cfg.get("sharding_offload", sharding_cfg.get("offload", False))
    )

    other = mp * pp * sd * sep
    if num_devices % other != 0:
        raise ValueError(
            f"device count {num_devices} not divisible by mp*pp*sharding*sep = "
            f"{mp}*{pp}*{sd}*{sep}"
        )
    dp = int(dist.get("dp_degree", 0) or 0)
    inferred_dp = num_devices // other
    if dp and dp != inferred_dp:
        raise ValueError(
            f"dp_degree={dp} inconsistent with num_devices={num_devices}, "
            f"mp={mp}, pp={pp}, sharding={sd}, sep={sep} (expected {inferred_dp})"
        )
    dist.dp_degree = inferred_dp
    dist.mp_degree = mp
    dist.pp_degree = pp
    dist.sep_degree = sep
    dist.setdefault("sequence_parallel", False)
    if dist.sequence_parallel and mp == 1:
        # Megatron SP only reshards over the model axis; degenerate otherwise
        # (reference hybrid_model.py:784-788 disables it the same way).
        dist.sequence_parallel = False
    return cfg


def process_global_configs(cfg: AttrDict) -> AttrDict:
    """Reconcile global/local/micro batch sizes (reference config.py:104-148).

    global = local * dp * sharding;  accumulate_steps = local / micro.
    """
    g = cfg.setdefault("Global", AttrDict())
    dist = cfg.Distributed
    dp_world = int(dist.dp_degree) * int(dist.sharding.sharding_degree)

    gbs = g.get("global_batch_size", None)
    lbs = g.get("local_batch_size", None)
    mbs = g.get("micro_batch_size", None)

    if gbs is None and lbs is None:
        raise ValueError("one of global_batch_size / local_batch_size required")
    if lbs is None:
        if gbs % dp_world != 0:
            raise ValueError(f"global_batch_size {gbs} not divisible by dp world {dp_world}")
        lbs = gbs // dp_world
    if gbs is None:
        gbs = lbs * dp_world
    if gbs != lbs * dp_world:
        raise ValueError(f"global {gbs} != local {lbs} * dp_world {dp_world}")
    if mbs is None:
        mbs = lbs
    if lbs % mbs != 0:
        raise ValueError(f"local_batch_size {lbs} not divisible by micro {mbs}")

    g.global_batch_size = int(gbs)
    g.local_batch_size = int(lbs)
    g.micro_batch_size = int(mbs)
    ebs = g.get("eval_batch_size")
    if ebs is not None and (int(ebs) <= 0 or int(ebs) % dp_world != 0):
        raise ValueError(
            f"eval_batch_size {ebs} must be a positive multiple of "
            f"dp world {dp_world}"
        )
    g.setdefault("seed", 1024)
    g.setdefault("device", "tpu")

    eng = cfg.setdefault("Engine", AttrDict())
    eng.accumulate_steps = g.local_batch_size // g.micro_batch_size
    return cfg


def process_engine_config(cfg: AttrDict) -> AttrDict:
    """Engine defaults (reference config.py:151-189)."""
    eng = cfg.setdefault("Engine", AttrDict())
    eng.setdefault("max_steps", 500000)
    eng.setdefault("eval_freq", 1)
    eng.setdefault("eval_iters", 10)
    eng.setdefault("logging_freq", 10)
    eng.setdefault("num_train_epochs", 1)
    eng.setdefault("test_iters", eng.eval_iters * 10)
    mix = eng.setdefault("mix_precision", AttrDict())
    mix.setdefault("enable", True)
    mix.setdefault("dtype", "bfloat16")  # TPU-native; fp16+scaling kept for parity
    mix.setdefault("level", "O2")
    mix.setdefault("scale_loss", 32768.0)
    save = eng.setdefault("save_load", AttrDict())
    save.setdefault("save_steps", 1000)
    save.setdefault("save_epoch", 1)
    save.setdefault("output_dir", "./output")
    save.setdefault("ckpt_dir", None)
    save.setdefault("auto_resume", False)
    # retention GC: newest N complete checkpoints kept (0 = keep all); the
    # last verified-good one is never deleted (docs/fault_tolerance.md)
    save.setdefault("keep_last_n", 0)
    # anomaly guard budgets (core/engine.py + utils/resilience.py): past
    # them the engine rolls back to the last checkpoint
    res = eng.setdefault("resilience", AttrDict())
    res.setdefault("enable", True)
    res.setdefault("max_skip_streak", 10)
    res.setdefault("loss_spike_zscore", 0.0)  # 0 disables spike detection
    res.setdefault("loss_spike_streak", 5)
    res.setdefault("loss_window", 64)
    res.setdefault("max_rollbacks", 2)
    return cfg


def process_configs(cfg: AttrDict, num_devices: Optional[int] = None) -> AttrDict:
    cfg = process_dist_config(cfg, num_devices)
    cfg = process_global_configs(cfg)
    cfg = process_engine_config(cfg)
    return cfg


def get_config(
    path: str, overrides: Optional[List[str]] = None, num_devices: Optional[int] = None
) -> AttrDict:
    """Load + override + validate a config file (reference config.py:398)."""
    cfg = parse_config(path)
    cfg = override_config(cfg, overrides)
    cfg = process_configs(cfg, num_devices)
    return cfg


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    """CLI surface of the reference tools (config.py:637-652)."""
    parser = argparse.ArgumentParser("paddlefleetx-tpu")
    parser.add_argument("-c", "--config", type=str, required=True, help="config file path")
    parser.add_argument(
        "-o",
        "--override",
        action="append",
        default=[],
        help="override config option key.sub=value (repeatable)",
    )
    parser.add_argument(
        "--exit-after-save",
        action="store_true",
        help="stop cleanly (exit 0) right after the next periodic "
        "checkpoint completes — checkpoint-aligned work units for "
        "preemptible slices (docs/fault_tolerance.md)",
    )
    return parser.parse_args(argv)
