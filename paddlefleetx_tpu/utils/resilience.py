"""Fault tolerance: transient-I/O retry, preemption handling, anomaly
detection, and the fault-injection harness that tests all of it.

Production TPU fleets fail in exactly three ways a trainer must survive
(the reference's answer is `auto_resume` over step_N checkpoint dirs,
eager_engine.py:244,816-825 — necessary but not sufficient):

  1. **Preemption** — preemptible slices get SIGTERM with a grace window.
     ``PreemptionGuard`` turns the signal into a flag; ``Engine.fit``
     finishes the in-flight step, writes a final checkpoint with a
     ``preempted`` marker, and the process exits 0 so the relaunch
     auto-resumes (Megatron ``--exit-on-signal`` / Orbax
     preemption-checkpointing semantics).
  2. **Storage flakes and bit-rot** — ``retry`` wraps orbax save/restore
     and artifact downloads with bounded exponential backoff; corrupt
     checkpoints are quarantined by ``utils/checkpoint.py`` and resume
     falls back to the previous good one.
  3. **Numeric anomalies** — the engine already skips non-finite steps in
     lockstep (core/engine.py found_inf contract); ``AnomalyGuard``
     bounds HOW LONG that can go on (consecutive-skip budget, loss-spike
     z-score) before the engine rolls params+opt-state back to the last
     checkpoint instead of burning hardware on a poisoned run.

Fault injection (``PFX_FAULT=<site>:<step>[:<count>]``) drives the
subprocess crash-resume tests:

  ``sigterm:K``        after step K completes, SIGTERM this process
                       (exercises the real handler path)
  ``save_crash:K``     hard-exit (os._exit 17) mid-save at the first
                       save with step >= K — after the array write,
                       before meta.json, leaving a marker-less dir
  ``ckpt_truncate:K``  after the first save with step >= K completes
                       (meta.json written: the checkpoint LOOKS good),
                       truncate its array data — simulated bit-rot
  ``nan_grads:K:N``    poison the batch with NaNs for N steps starting
                       at step K (drives the anomaly-rollback path)

Serving sites (step counts are *generation request* indices — warmup
generations count; `core/serving.py` fires them, the `tools/serve.py`
traffic drills in tests/test_serve_drills.py assert the behavior):

  ``gen_crash:K``      raise RuntimeError inside generation request K
                       (after the donated KV cache was popped from the
                       pool — exercises the error path that must not
                       poison the pool; HTTP surface: one 500, server
                       keeps serving)
  ``gen_hang:K``       sleep PFX_FAULT_HANG_S (default 3600) seconds
                       inside generation request K — a wedged decode;
                       the serve watchdog flips /healthz to degraded
  ``cb_step_hang:K``   sleep PFX_FAULT_HANG_S seconds before continuous-
                       batching decode step K (`core/continuous_batching`
                       fires it between steps — a mid-decode stall that
                       carries active rows past their deadlines, driving
                       the eviction drills in tests/test_paged_drills.py)
  ``boot_crash:K``     hard-exit (os._exit 23) at `tools/serve.py` boot,
                       right after argument parsing — a replica that can
                       never come up (bad image, broken config).  Drives
                       the crash-loop -> supervisor-quarantine drill in
                       tests/test_elastic_drills.py (the supervisor must
                       stop restarting it LOUDLY within the flap budget,
                       docs/serving.md "Elastic control plane")
  ``handoff_drop:K``   drop the Kth DIRECT prefill->decode handoff send
                       on a prefill replica (`tools/serve.py` checks the
                       fire and skips the POST — a network drop before
                       any byte left).  Drives the direct-transfer
                       retry/proxy-fallback drill in
                       tests/test_disagg_drills.py
  ``adopt_crash:K``    hard-exit (os._exit 29) on a decode replica at
                       its Kth KV-handoff adoption, right after the row
                       landed in the arena — a decode replica dying
                       while holding adopted rows (the in-process
                       stand-in for SIGKILL mid-handoff).  Drives the
                       router's bounded re-prefill failover drill
                       (docs/serving.md "Disaggregated operations")
  ``spill_corrupt:K[:N]``  treat the Kth (.. K+N-1th) spill-readmit
                       probe as a torn host entry (`core/
                       continuous_batching` checks the fire and
                       discards the entry itself — no behavior here).
                       The request recomputes the prefix and SUCCEEDS;
                       pfx_prefix_spill_discards_total counts the loss
                       (docs/serving.md "KV lifecycle" graceful
                       degradation, drilled in tests/test_kv_tier.py)
  ``migrate_stall:K``  sleep PFX_FAULT_HANG_S (default 3600) seconds
                       inside the Kth drain-time prefix-migration send
                       (`tools/serve.py` caps the sleep at its
                       remaining migration deadline) — a wedged
                       receiver; the drain must STILL exit 0 within
                       PFX_MIGRATE_DEADLINE_S with the migration
                       counted failed, never stall the PR 3/11 drain
                       contract (tests/test_kv_tier.py)
  ``preempt_storm:K[:N]``  force N priority preemptions starting at
                       continuous-scheduler iteration K: the scheduler
                       checks the fire at an iteration boundary and
                       preempts the lowest-priority eligible active row
                       itself (no behavior here) — the deterministic
                       preempt -> republish -> requeue -> resume drill;
                       the preempted request's final greedy output must
                       stay token-identical to its undisturbed run
                       (docs/serving.md "Multi-tenant isolation",
                       drilled in tests/test_tenant_drills.py)

Data sites (step counts are *sample fetch* indices inside the host data
loader — ``data/batch_sampler.py`` fires them; the data drills in
tests/test_data_drills.py assert the behavior):

  ``corrupt_sample:K[:N]``  raise DataCorruptionError for N consecutive
                       sample fetches starting at fetch K — a rotten
                       record; the loader skips it under the
                       ``data.max_skips`` budget (loud past it)
  ``io_stall:K[:S]``   sleep S seconds (default 2.0, may be fractional —
                       the third field is SECONDS here, not a count)
                       inside sample fetch K — a hung storage read; the
                       prefetch starvation watchdog warns and
                       ``data_wait_s`` accounts the stall

All env knobs follow the repo's loud-parse convention (PFX_FLASH_*,
ops/flash_attention.py): a set-but-invalid value raises at first use
instead of silently running with a default.

Retry knobs: ``PFX_RETRY_ATTEMPTS`` (default 3, >= 1),
``PFX_RETRY_BACKOFF`` (base seconds, default 0.5, doubles per attempt),
``PFX_RETRY_JITTER`` (uniform fraction added to each delay, default 0.25).
"""

from __future__ import annotations

import collections
import math
import os
import random
import signal
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from paddlefleetx_tpu.utils.log import logger

# ---------------------------------------------------------------------------
# loud-parse env helpers
# ---------------------------------------------------------------------------


def _env_int(name: str, default: int, minimum: int = 0) -> int:
    raw = os.environ.get(name) or ""
    if not raw.strip():
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer (loud-parse: unset it or "
            f"pass a valid value)"
        ) from None
    if val < minimum:
        raise ValueError(f"{name}={val} must be >= {minimum}")
    return val


def _env_float(name: str, default: float, minimum: float = 0.0) -> float:
    raw = os.environ.get(name) or ""
    if not raw.strip():
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a number (loud-parse: unset it or "
            f"pass a valid value)"
        ) from None
    if val < minimum:
        raise ValueError(f"{name}={val} must be >= {minimum}")
    return val


# ---------------------------------------------------------------------------
# transient-I/O retry
# ---------------------------------------------------------------------------

# OSError covers IOError/ConnectionError/TimeoutError — the transient
# transport/storage failures worth repeating.  Corruption surfaces as
# ValueError from the tensorstore/zarr layer and must NOT be retried:
# re-reading rotten bytes wastes the backoff budget and delays the
# quarantine + fallback path.
RETRYABLE_DEFAULT: Tuple[type, ...] = (OSError,)


def retry(
    fn: Callable[[], Any],
    *,
    attempts: Optional[int] = None,
    backoff: Optional[float] = None,
    jitter: Optional[float] = None,
    retryable: Tuple[type, ...] = RETRYABLE_DEFAULT,
    desc: str = "",
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Call ``fn()`` with bounded exponential-backoff retries.

    Only exceptions in ``retryable`` are retried; anything else propagates
    immediately.  After the last attempt the final error is re-raised
    wrapped in RuntimeError naming the operation — a retried-out failure
    must be unmistakable in a crash-loop log.
    """
    attempts = attempts if attempts is not None else _env_int(
        "PFX_RETRY_ATTEMPTS", 3, minimum=1
    )
    backoff = backoff if backoff is not None else _env_float(
        "PFX_RETRY_BACKOFF", 0.5
    )
    jitter = jitter if jitter is not None else _env_float(
        "PFX_RETRY_JITTER", 0.25
    )
    what = desc or getattr(fn, "__name__", "operation")
    last: Optional[BaseException] = None
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retryable as e:  # noqa: PERF203 — bounded loop
            last = e
            if attempt == attempts:
                break
            delay = backoff * (2.0 ** (attempt - 1))
            delay *= 1.0 + random.uniform(0.0, jitter)
            logger.warning(
                f"{what}: attempt {attempt}/{attempts} failed ({e}); "
                f"retrying in {delay:.2f}s"
            )
            sleep(delay)
    raise RuntimeError(
        f"{what}: failed after {attempts} attempt(s): {last}"
    ) from last


# ---------------------------------------------------------------------------
# fault injection harness
# ---------------------------------------------------------------------------

FAULT_SITES = (
    "sigterm", "save_crash", "ckpt_truncate", "nan_grads",
    "gen_crash", "gen_hang", "cb_step_hang", "boot_crash",
    "corrupt_sample", "io_stall", "handoff_drop", "adopt_crash",
    "cb_commit_crash", "spill_corrupt", "migrate_stall",
    "preempt_storm",
)


class DataCorruptionError(RuntimeError):
    """A sample could not be fetched/decoded (rotten record, torn shard).

    Raised by the ``corrupt_sample`` injection and usable by datasets that
    detect bad records themselves; the host data loader catches it (with
    every other per-sample Exception) and applies the skip budget."""

# fires-per-site for THIS process; a relaunched run starts clean, which is
# exactly what the crash-resume tests need (inject once, resume clean)
_fires: Dict[str, int] = {}


def reset_fault_state() -> None:
    """Clear the per-process fire counters (test isolation)."""
    _fires.clear()


def fault_spec() -> Optional[Tuple[str, int, int]]:
    """Parse ``PFX_FAULT=<site>:<step>[:<count>]`` (None when unset).

    Loud-parse: an unknown site or non-integer field raises immediately —
    a typo'd injection silently not firing would green-light a test that
    exercised nothing.

    ``io_stall`` is the one site whose third field is NOT a count: it is
    the stall duration in (possibly fractional) seconds — see
    ``io_stall_seconds`` — and the fire count is always 1.
    """
    raw = os.environ.get("PFX_FAULT") or ""
    if not raw.strip():
        return None
    parts = raw.split(":")
    if len(parts) not in (2, 3):
        raise ValueError(
            f"PFX_FAULT={raw!r}; expected <site>:<step>[:<count>] with "
            f"site in {FAULT_SITES}"
        )
    site = parts[0]
    if site not in FAULT_SITES:
        raise ValueError(
            f"PFX_FAULT site {site!r} unknown; valid: {', '.join(FAULT_SITES)}"
        )
    try:
        step = int(parts[1])
        if site == "io_stall":
            if len(parts) == 3:
                float(parts[2])  # loud-parse the seconds field here too
            count = 1
        else:
            count = int(parts[2]) if len(parts) == 3 else 1
    except ValueError:
        raise ValueError(
            f"PFX_FAULT={raw!r}: step/count must be integers "
            "(io_stall's third field: seconds, int or float)"
        ) from None
    if count < 1:
        raise ValueError(f"PFX_FAULT={raw!r}: count must be >= 1")
    return site, step, count


def io_stall_seconds(default: float = 2.0) -> float:
    """Stall duration for the ``io_stall`` site: the optional third
    PFX_FAULT field, in seconds (fractional allowed)."""
    raw = os.environ.get("PFX_FAULT") or ""
    parts = raw.split(":")
    if len(parts) == 3 and parts[0] == "io_stall":
        return float(parts[2])
    return default


def maybe_fire(site: str, step: int, path: Optional[str] = None) -> bool:
    """Fire the configured fault if ``site`` matches and ``step`` has been
    reached (at most ``count`` times per process).  Returns True when it
    fired.  ``save_crash`` does not return."""
    spec = fault_spec()
    if spec is None or spec[0] != site or step < spec[1]:
        return False
    if _fires.get(site, 0) >= spec[2]:
        return False
    _fires[site] = _fires.get(site, 0) + 1
    logger.warning(
        f"PFX_FAULT: firing {site} at step {step} "
        f"({_fires[site]}/{spec[2]})"
    )
    if site == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
    elif site == "save_crash":
        # simulate a kill mid-save: the array write finished, meta.json
        # (the completeness marker) never lands.  os._exit skips every
        # finally/atexit — the closest a test can get to SIGKILL while
        # keeping the injection inside the save call.
        os._exit(17)
    elif site == "ckpt_truncate":
        if not path:
            raise ValueError("ckpt_truncate injection needs the ckpt path")
        truncate_checkpoint_payload(path)
    elif site == "gen_crash":
        raise RuntimeError(
            f"PFX_FAULT: injected gen_crash at request {step}"
        )
    elif site == "cb_commit_crash":
        # a dispatched decode step whose results never materialize: the
        # injection sits inside the engine's commit readback, so an
        # IN-FLIGHT dispatch-ahead step fails exactly where a real
        # device error would surface — the ArenaReset drill's hook
        raise RuntimeError(
            f"PFX_FAULT: injected cb_commit_crash at step {step}"
        )
    elif site == "boot_crash":
        # a replica that can never come up: os._exit skips every
        # finally/atexit, the closest in-process stand-in for a broken
        # image — the supervisor sees a nonzero exit within seconds
        os._exit(23)
    elif site == "adopt_crash":
        # a decode replica dying while holding adopted rows: os._exit
        # skips every finally/atexit — the transport sees the
        # connection die mid-exchange, never a clean error response
        os._exit(29)
    # handoff_drop carries no behavior here: the prefill replica's
    # direct-transfer send checks the fire and skips the POST itself
    # (the drop happens before any byte leaves the process).
    # spill_corrupt carries no behavior either: the engine's readmit
    # probe checks the fire and discards the host entry itself.
    # migrate_stall's sleep lives at the serve.py send site, where the
    # remaining migration deadline caps it — an uncapped sleep here
    # would outlive the very contract the drill proves.
    # preempt_storm carries no behavior here either: the continuous
    # scheduler checks the fire at an iteration boundary and forcibly
    # preempts the lowest-priority eligible active row itself — a
    # deterministic preemption-pressure drill (preempt -> republish ->
    # requeue -> resume) without needing real capacity contention.
    elif site in ("gen_hang", "cb_step_hang"):
        time.sleep(_env_float("PFX_FAULT_HANG_S", 3600.0))
    elif site == "corrupt_sample":
        raise DataCorruptionError(
            f"PFX_FAULT: injected corrupt_sample at fetch {step}"
        )
    elif site == "io_stall":
        time.sleep(io_stall_seconds())
    return True


def truncate_checkpoint_payload(ckpt_path: str) -> None:
    """Bit-rot simulator: halve the ocdbt array data files under a saved
    checkpoint so the directory still LOOKS complete (meta.json + orbax
    metadata intact) but restore fails."""
    import glob

    targets = []
    for sub in ("state", "params"):
        targets += sorted(glob.glob(os.path.join(ckpt_path, sub, "d", "*")))
        targets += sorted(
            glob.glob(os.path.join(ckpt_path, sub, "manifest.ocdbt"))
        )
    if not targets:
        raise FileNotFoundError(
            f"ckpt_truncate: no ocdbt payload under {ckpt_path}"
        )
    for t in targets:
        size = os.path.getsize(t)
        with open(t, "r+b") as f:
            f.truncate(size // 2)
        logger.warning(
            f"PFX_FAULT: truncated {t} ({size} -> {size // 2} bytes)"
        )


def poison_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
    """Replace every float leaf of a host batch with NaNs (the
    ``nan_grads`` injection: NaN loss -> NaN grads -> found_inf skip)."""
    out = dict(batch)
    poisoned = False
    for k, v in out.items():
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating):
            out[k] = np.full_like(arr, np.nan)
            poisoned = True
    if not poisoned:
        raise ValueError(
            "nan_grads injection needs at least one float batch leaf "
            f"(got {sorted(out)})"
        )
    return out


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------


class PreemptionGuard:
    """SIGTERM/SIGINT -> a flag the training loop polls between steps.

    The handler must do nothing blocking (it runs at an arbitrary bytecode
    boundary, possibly mid-XLA-dispatch): it records the request; the loop
    finishes the in-flight step, joins any async save, writes the final
    checkpoint, and returns — the process then exits 0 so the relaunch
    auto-resumes.  The FIRST signal also restores the original handlers,
    so a second SIGTERM/Ctrl-C escalates normally (force-quit) — the
    escape hatch when the in-flight step itself is wedged and the
    graceful path will never be reached.  ``uninstall`` restores the
    prior handlers.
    """

    def __init__(self) -> None:
        self.requested = False
        self.signum: Optional[int] = None
        self._orig: Dict[int, Any] = {}
        self.installed = False

    def install(self) -> "PreemptionGuard":
        def handler(signum, frame):
            self.requested = True
            self.signum = signum
            # one graceful shot: hand the signals back so the next one
            # kills/interrupts the process the ordinary way
            for sig, orig in self._orig.items():
                signal.signal(sig, orig)
            logger.warning(
                f"received signal {signum}: finishing the in-flight step, "
                "checkpointing, then exiting cleanly (send again to "
                "force-quit)"
            )

        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._orig[sig] = signal.signal(sig, handler)
            self.installed = True
        except ValueError:
            # signal.signal only works on the main thread; a fit() driven
            # from a worker thread just loses preemption awareness
            logger.warning(
                "preemption handlers unavailable off the main thread; "
                "SIGTERM will kill this run without a final checkpoint"
            )
        return self

    def uninstall(self) -> None:
        for sig, orig in self._orig.items():
            signal.signal(sig, orig)
        self._orig.clear()
        self.installed = False


# ---------------------------------------------------------------------------
# anomaly guard
# ---------------------------------------------------------------------------


class AnomalyGuard:
    """Budgeted anomaly detector over the per-step (loss, skipped) stream.

    Two independent detectors, either can trip:

      - **skip streak**: ``max_skip_streak`` consecutive non-finite
        (found_inf-skipped) steps.  The engine's per-step skip handles a
        stray overflow; a long streak means the state itself is poisoned
        (or the data is) and skipping forever just burns the slice.
      - **loss spike**: z-score of the current loss against a rolling
        window of recent finite losses exceeds ``spike_zscore`` for
        ``spike_streak`` consecutive steps.  Catches divergence that
        stays finite.  Disabled while the window holds fewer than
        ``min_window`` samples (cold-start variance) or when
        ``spike_zscore`` <= 0.

    ``observe`` returns None (healthy) or a human-readable reason string;
    the engine responds by rolling back to the last good checkpoint.
    """

    def __init__(
        self,
        max_skip_streak: int = 10,
        spike_zscore: float = 0.0,
        spike_streak: int = 5,
        window: int = 64,
        min_window: int = 16,
    ) -> None:
        self.max_skip_streak = int(max_skip_streak)
        self.spike_zscore = float(spike_zscore)
        self.spike_streak_budget = int(spike_streak)
        self.min_window = int(min_window)
        self.losses: collections.deque = collections.deque(maxlen=int(window))
        self.skip_streak = 0
        self.spike_streak = 0

    def reset(self) -> None:
        """Forget all history (called after a rollback: the restored state
        starts a fresh stream)."""
        self.losses.clear()
        self.skip_streak = 0
        self.spike_streak = 0

    def observe(self, loss: float, skipped: bool) -> Optional[str]:
        if skipped or not math.isfinite(loss):
            self.skip_streak += 1
            if self.max_skip_streak and self.skip_streak >= self.max_skip_streak:
                return (
                    f"{self.skip_streak} consecutive non-finite steps "
                    f"(budget {self.max_skip_streak})"
                )
            return None
        self.skip_streak = 0
        if self.spike_zscore > 0 and len(self.losses) >= self.min_window:
            mean = float(np.mean(self.losses))
            std = float(np.std(self.losses))
            z = (loss - mean) / std if std > 1e-12 else 0.0
            if z > self.spike_zscore:
                self.spike_streak += 1
                if self.spike_streak >= self.spike_streak_budget:
                    return (
                        f"loss spike z={z:.1f} for {self.spike_streak} "
                        f"consecutive steps (threshold "
                        f"{self.spike_zscore}, budget "
                        f"{self.spike_streak_budget})"
                    )
                # spiking losses stay OUT of the window: they would drag
                # the mean toward the divergence and mask it
                return None
            self.spike_streak = 0
        self.losses.append(loss)
        return None
