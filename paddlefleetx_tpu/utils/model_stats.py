"""Training-run observatory: per-layer-group model-internals statistics,
non-finite provenance, memory watermarks, and retrace attribution.

The engine's step records (loss / lr / global grad_norm / MFU) say *that*
a run is sick, never *where*.  Production-scale training stacks
(MegaScale, Jiang et al. 2024) treat per-layer statistics and
memory/straggler diagnostics as the thing that makes a large run
debuggable; this module is that layer for ``core/engine.py``:

  - **Group mapping** — :func:`build_group_spec` assigns EVERY parameter
    leaf of any model in the zoo to exactly one *layer group* through a
    deterministic path rule: leaves whose key path crosses a ``layers``
    stack (``models/common.stack_spec_tree`` — GPT, ERNIE, T5, ViT,
    DebertaV2 all use it) split per layer into ``block_<i>`` (prefixed
    ``encoder/``/``decoder/`` when nested); embedding-rooted leaves map
    to ``embed``; final-LN / LM-head leaves to ``head``; anything else
    keeps its (lowercased) root key.  The mapping is *total* (no leaf
    unassigned) and *stable* (pure function of the tree structure).
  - **In-graph statistics** — :func:`group_sqsum` / :func:`group_stats`
    compute per-group grad norm, param norm, update norm, update/param
    ratio and grads-fraction-non-finite as ``[G]`` vectors inside the
    jitted train step.  Sums accumulate in fp32 via the SAME per-leaf
    rule as ``optims/optimizer.global_norm_f32`` (``sqsum_f32``), so the
    engine's global grad norm is exactly ``sqrt(sum(group_sqsum))`` and
    grouping adds no second pass over the gradients.
  - **Non-finite provenance** — :func:`nonfinite_group_names` turns the
    per-group finiteness vector (free: ``isfinite`` of the group sqsums
    the norm already needs) into the ordered list of offending groups,
    carried by step records, anomaly ``rollback`` events and the flight
    recorder, so a postmortem names a culprit layer instead of
    "found_inf fired".
  - **Memory watermarks** — :func:`memory_watermarks` reads
    ``device.memory_stats()`` where the backend provides it (TPU), with
    a host-RSS fallback (``/proc/self/status``), exported as ``pfx_mem_*``
    gauges by :func:`export_memory_gauges`; the engine tracks the peak
    per fit and warns loudly when headroom drops under
    ``PFX_MEM_WARN_HEADROOM`` (default 0.05 = 5% free).
  - **Retrace attribution** — :class:`CompileWatcher` turns jax's
    compile logging into a structured compile-event log (fn name, arg
    avals diffed against the previous compile of that fn, elapsed
    seconds) feeding ``pfx_compile_events_total`` /
    ``pfx_compile_seconds_total`` and the flight ring — "why did step
    812 take 40 s" is answerable from the flight dump offline
    (``tools/report.py``).

Cadence contract (docs/observability.md): the engine computes group
stats behind ``lax.cond`` on ``Engine.logging.model_stats_every``
(default = logging cadence, ``0`` disables) and the results ride the
existing step-record device fetch — no new per-step host syncs, and at
``0`` the train step graph is byte-identical to the stats-less one
(asserted by tests/test_model_stats.py).
"""

from __future__ import annotations

import logging
import os
import re
import threading
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from paddlefleetx_tpu.utils.log import logger

# ---------------------------------------------------------------------------
# group mapping
# ---------------------------------------------------------------------------

# path components marking a stacked per-layer subtree (the leading axis is
# the layer index — models/common.stack_spec_tree's contract)
STACK_KEYS = ("layers",)
# non-stacked root classification (lowercased containment / exact match)
_HEAD_ROOTS = ("final_ln", "final_layernorm", "final_layer_norm", "lm_head",
               "head", "pooler")


class GroupSpec(NamedTuple):
    """Deterministic leaf -> layer-group assignment for one param tree.

    ``names`` is the canonical group order (``embed`` first, stacked
    blocks in layer order, scalar groups, ``head`` last) — the order
    "first offending group" provenance reports in.  ``assignments`` has
    one entry per flattened leaf: ``(group_index, None)`` for a scalar
    group, ``(first_block_index, num_layers)`` for a stacked leaf whose
    leading axis spreads over ``num_layers`` consecutive block groups.
    ``sizes`` counts float elements per group (the non-finite-fraction
    denominator); non-inexact leaves are assigned but carry zero size
    and are skipped by every statistic."""

    names: Tuple[str, ...]
    assignments: Tuple[Tuple[int, Optional[int]], ...]
    sizes: Any  # np.ndarray [G] float
    treedef: Any

    @property
    def num_groups(self) -> int:
        return len(self.names)


def _key_name(k: Any) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _is_inexact(leaf: Any) -> bool:
    dt = getattr(leaf, "dtype", None)
    return dt is not None and np.issubdtype(np.dtype(dt), np.inexact)


def _scalar_group(comps: Sequence[str]) -> str:
    root = comps[0].lower()
    if "embed" in root:
        return "embed"
    if root in _HEAD_ROOTS or "head" in root or root.startswith("final"):
        return "head"
    return root


def build_group_spec(params: Any) -> GroupSpec:
    """Map every leaf of ``params`` (arrays or ShapeDtypeStructs) to a
    layer group.  Total over any pytree — a leaf that matches no rule
    keeps its root key as its group — and a pure function of the tree
    structure, so two calls on the same model agree exactly."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    # pass 1: discover groups.  stacked[] = (base, L); scalar[] = name
    leaf_plan: List[Tuple[str, Any]] = []  # ("stacked", (base, L, layer_sz)) | ("scalar", name)
    stack_layers: Dict[str, int] = {}
    for kp, leaf in flat:
        comps = [_key_name(k) for k in kp] or ["params"]
        stack_at = next(
            (i for i, c in enumerate(comps) if c.lower() in STACK_KEYS), None
        )
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if stack_at is not None and len(shape) >= 1 and shape[0] >= 1:
            base = "/".join(c.lower() for c in comps[:stack_at])
            length = int(shape[0])
            prev = stack_layers.setdefault(base, length)
            if prev != length:
                # inconsistent stack under one prefix: refuse to guess a
                # per-layer split, fall back to one scalar group — the
                # mapping stays total either way
                leaf_plan.append(("scalar", _scalar_group(comps)))
                continue
            leaf_plan.append(("stacked", base))
        else:
            leaf_plan.append(("scalar", _scalar_group(comps)))

    # canonical order: embed, blocks (bases sorted, layers ascending),
    # other scalar groups sorted, head last
    scalar_names = {name for kind, name in leaf_plan if kind == "scalar"}
    ordered: List[str] = []
    if "embed" in scalar_names:
        ordered.append("embed")
    block_base_index: Dict[str, int] = {}
    for base in sorted(stack_layers):
        block_base_index[base] = len(ordered)
        prefix = f"{base}/" if base else ""
        ordered.extend(
            f"{prefix}block_{i}" for i in range(stack_layers[base])
        )
    for name in sorted(scalar_names - {"embed", "head"}):
        ordered.append(name)
    if "head" in scalar_names:
        ordered.append("head")
    index = {n: i for i, n in enumerate(ordered)}

    sizes = np.zeros((len(ordered),), np.float64)
    assignments: List[Tuple[int, Optional[int]]] = []
    for (kp, leaf), (kind, ref) in zip(flat, leaf_plan):
        n_el = float(np.prod(getattr(leaf, "shape", ()) or (), dtype=np.float64))
        if kind == "stacked":
            first = block_base_index[ref]
            length = stack_layers[ref]
            assignments.append((first, length))
            if _is_inexact(leaf):
                sizes[first:first + length] += n_el / length
        else:
            g = index[ref]
            assignments.append((g, None))
            if _is_inexact(leaf):
                sizes[g] += n_el
    return GroupSpec(tuple(ordered), tuple(assignments), sizes, treedef)


def group_labels(spec: GroupSpec) -> List[str]:
    """The group names in canonical (provenance) order."""
    return list(spec.names)


# ---------------------------------------------------------------------------
# in-graph statistics
# ---------------------------------------------------------------------------


def _flat_leaves(spec: GroupSpec, tree: Any) -> List[Any]:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if treedef != spec.treedef:
        raise ValueError(
            f"tree structure does not match the GroupSpec ({treedef} vs "
            f"{spec.treedef}) — build the spec from the same param tree"
        )
    return leaves


def _accumulate(spec: GroupSpec, tree: Any, leaf_fn) -> Any:
    """Sum ``leaf_fn(leaf) -> per-layer [L] or scalar`` into a [G] f32
    vector following the spec's assignments; non-float leaves skip."""
    import jax.numpy as jnp

    out = jnp.zeros((spec.num_groups,), jnp.float32)
    for leaf, (g0, length) in zip(_flat_leaves(spec, tree), spec.assignments):
        if leaf is None or not _is_inexact(leaf):
            continue
        if length is not None:
            axes = tuple(range(1, leaf.ndim))
            out = out.at[g0:g0 + length].add(leaf_fn(leaf, axes))
        else:
            out = out.at[g0].add(leaf_fn(leaf, None))
    return out


def group_sqsum(spec: GroupSpec, tree: Any) -> Any:
    """Per-group sum of squares, fp32-accumulated (the one rule behind
    ``optims/optimizer.global_norm_f32`` — ``sqrt(sum(group_sqsum))`` IS
    the global norm, so the engine computes the grouped and global grad
    norms in a single pass)."""
    import jax.numpy as jnp

    from paddlefleetx_tpu.optims.optimizer import sqsum_f32

    def leaf_fn(x, axes):
        if axes is None:
            return sqsum_f32(x)
        return jnp.sum(jnp.square(x.astype(jnp.float32)), axis=axes)

    return _accumulate(spec, tree, leaf_fn)


def group_nonfinite_count(spec: GroupSpec, tree: Any) -> Any:
    """Per-group count of non-finite elements, [G] f32."""
    import jax.numpy as jnp

    def leaf_fn(x, axes):
        bad = (~jnp.isfinite(x)).astype(jnp.float32)
        return jnp.sum(bad) if axes is None else jnp.sum(bad, axis=axes)

    return _accumulate(spec, tree, leaf_fn)


def group_stats(
    spec: GroupSpec,
    *,
    grad_sqsum: Any,
    params: Any,
    updates: Any,
    grads: Any,
) -> Dict[str, Any]:
    """The full per-group statistic set, each a [G] f32 vector:
    ``grad_norm`` / ``param_norm`` / ``update_norm`` / ``update_ratio``
    (update/param — the LR-health signal that drifts for hundreds of
    steps before a spike) / ``nonfinite_frac`` (fraction of grad
    ELEMENTS non-finite).  Called inside the train step's stats branch;
    ``grad_sqsum`` is passed in because the caller already computed it
    for the global norm."""
    import jax.numpy as jnp

    eps = jnp.float32(1e-12)
    param_norm = jnp.sqrt(group_sqsum(spec, params))
    update_norm = jnp.sqrt(group_sqsum(spec, updates))
    sizes = jnp.asarray(np.maximum(spec.sizes, 1.0), jnp.float32)
    return {
        "grad_norm": jnp.sqrt(grad_sqsum),
        "param_norm": param_norm,
        "update_norm": update_norm,
        "update_ratio": update_norm / (param_norm + eps),
        "nonfinite_frac": group_nonfinite_count(spec, grads) / sizes,
    }


def nonfinite_group_names(
    spec: GroupSpec, flags: Any, limit: Optional[int] = None
) -> List[str]:
    """Offending group names from a per-group non-finite indicator vector
    (host side, canonical order — the FIRST entry is the first offending
    group a postmortem should name)."""
    flat = np.asarray(flags).reshape(-1)
    names = [n for n, f in zip(spec.names, flat) if float(f) > 0]
    return names if limit is None else names[:limit]


# ---------------------------------------------------------------------------
# memory watermarks
# ---------------------------------------------------------------------------


def _host_rss_bytes() -> Optional[int]:
    """Resident-set size of this process: /proc (linux, current RSS)
    with a resource-module fallback (``ru_maxrss`` — a lifetime PEAK,
    in KiB on Linux/BSD but already bytes on macOS; still an honest
    watermark, just never decreasing); None when neither works."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys as _sys

        peak = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        return peak if _sys.platform == "darwin" else peak * 1024
    except Exception:  # noqa: BLE001 — no RSS source is a valid state
        return None


def memory_watermarks() -> Dict[str, Any]:
    """One memory snapshot: per-device ``bytes_in_use`` / ``peak_bytes``
    / ``bytes_limit`` where the backend exposes ``memory_stats()`` (TPU
    does; CPU returns None and contributes nothing), plus host RSS.
    ``headroom_frac`` is the WORST device's free fraction (None when no
    device reports a limit).  Pure host-side accounting — never a device
    sync."""
    devices: List[Dict[str, Any]] = []
    headroom: Optional[float] = None
    try:
        import jax

        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 — backend-optional API
                ms = None
            if not ms:
                continue
            in_use = ms.get("bytes_in_use")
            limit = ms.get("bytes_limit")
            row = {
                "id": int(getattr(d, "id", len(devices))),
                "bytes_in_use": in_use,
                "peak_bytes": ms.get("peak_bytes_in_use"),
                "bytes_limit": limit,
            }
            devices.append(row)
            if in_use is not None and limit:
                free = max(0.0, 1.0 - float(in_use) / float(limit))
                headroom = free if headroom is None else min(headroom, free)
    except Exception:  # noqa: BLE001 — no backend is a valid state here
        pass
    return {
        "devices": devices,
        "host_rss_bytes": _host_rss_bytes(),
        "device_peak_bytes": max(
            (d["peak_bytes"] for d in devices if d.get("peak_bytes")), default=None
        ),
        "device_in_use_bytes": max(
            (d["bytes_in_use"] for d in devices if d.get("bytes_in_use")),
            default=None,
        ),
        "headroom_frac": headroom,
    }


def export_memory_gauges(registry, wm: Dict[str, Any]) -> None:
    """Mirror a watermark snapshot onto ``pfx_mem_*`` gauges."""
    if wm.get("host_rss_bytes") is not None:
        registry.gauge("pfx_mem_host_rss_bytes").set(wm["host_rss_bytes"])
    for d in wm.get("devices", ()):
        lab = {"device": str(d["id"])}
        if d.get("bytes_in_use") is not None:
            registry.gauge("pfx_mem_device_bytes_in_use", **lab).set(
                d["bytes_in_use"]
            )
        if d.get("peak_bytes") is not None:
            registry.gauge("pfx_mem_device_peak_bytes", **lab).set(
                d["peak_bytes"]
            )
        if d.get("bytes_limit") is not None:
            registry.gauge("pfx_mem_device_limit_bytes", **lab).set(
                d["bytes_limit"]
            )
    if wm.get("headroom_frac") is not None:
        registry.gauge("pfx_mem_headroom_frac").set(
            round(wm["headroom_frac"], 4)
        )


def warn_headroom(wm: Dict[str, Any], threshold: Optional[float] = None) -> bool:
    """Loud warning when the worst device's free-HBM fraction drops
    under the threshold (``PFX_MEM_WARN_HEADROOM``, default 0.05).
    Returns True when it warned — callers rate-limit (the engine warns
    once per fit)."""
    from paddlefleetx_tpu.utils.telemetry import _env_float

    threshold = (
        threshold if threshold is not None
        else _env_float("PFX_MEM_WARN_HEADROOM", 0.05)
    )
    head = wm.get("headroom_frac")
    if head is None or head >= threshold:
        return False
    # worst by free FRACTION — the same quantity headroom_frac (and the
    # breach decision) is computed from, so the named device is the one
    # that tripped the warning even on heterogeneous fleets
    worst = min(
        (d for d in wm.get("devices", ()) if d.get("bytes_limit")),
        key=lambda d: 1.0 - (d["bytes_in_use"] or 0) / d["bytes_limit"],
        default=None,
    )
    detail = (
        f" (device {worst['id']}: {worst['bytes_in_use']}/"
        f"{worst['bytes_limit']} bytes in use)" if worst else ""
    )
    logger.warning(
        f"HBM headroom low: {head:.1%} free < {threshold:.1%} threshold"
        f"{detail} — the next allocation spike (eval, checkpoint "
        "snapshot, retrace) may OOM; shrink the batch/model or raise "
        "PFX_MEM_WARN_HEADROOM to silence"
    )
    return True


# ---------------------------------------------------------------------------
# retrace attribution: the compile-event log
# ---------------------------------------------------------------------------

_COMPILING_RE = re.compile(
    r"Compiling ([^\s]+) with global shapes and types (\[.*\])\.", re.DOTALL
)
_CACHE_HIT_RE = re.compile(r"Persistent compilation cache hit")
# the per-compile chatter jax_log_compiles turns on (suppressed from run
# logs once the watcher owns those loggers); anything NOT matching —
# e.g. jax._src.compiler's "Unable to generate cache key" errors — is
# forwarded to the repo logger so real problems stay visible
_COMPILE_CHATTER_RE = re.compile(
    r"Compiling |Finished tracing|Finished jaxpr|Finished XLA compilation|"
    r"compilation cache hit|persistent compilation cache|"
    r"compile_requests|get_compile_options|cache_key"
)


def _split_avals(avals: str) -> List[str]:
    """Split jax's ``[ShapedArray(f32[4]), ...]`` listing into per-arg
    strings (best-effort: balanced-paren split, robust to nested
    parentheses inside an aval)."""
    body = avals.strip()
    if body.startswith("["):
        body = body[1:]
    if body.endswith("]"):
        body = body[:-1]
    out, depth, cur = [], 0, []
    for ch in body:
        if ch == "," and depth == 0:
            if "".join(cur).strip():
                out.append("".join(cur).strip())
            cur = []
            continue
        depth += ch in "([{"
        depth -= ch in ")]}"
        cur.append(ch)
    if "".join(cur).strip():
        out.append("".join(cur).strip())
    return out


def diff_avals(prev: Optional[List[str]], cur: List[str], cap: int = 3) -> str:
    """Human-readable diff of two compile keys' aval lists: what changed
    since the previous compile of this fn (the retrace attribution)."""
    if prev is None:
        return "first compile"
    if len(prev) != len(cur):
        return f"arg count {len(prev)} -> {len(cur)}"
    changed = [
        f"arg{i}: {p} -> {c}" for i, (p, c) in enumerate(zip(prev, cur))
        if p != c
    ]
    if not changed:
        return "same avals (sharding/donation/compiler-option change)"
    extra = f" (+{len(changed) - cap} more)" if len(changed) > cap else ""
    return "; ".join(changed[:cap])[:400] + extra


class CompileWatcher:
    """Structured compile-event log fed from jax's own compile logging.

    ``install()`` flips ``jax_log_compiles`` on and attaches a logging
    handler to jax's pxla logger, whose "Compiling <fn> with global
    shapes and types [...]" line carries the fn name + the full abstract
    arg list; a ``jax.monitoring`` duration listener then stamps the
    backend-compile elapsed seconds onto the pending event (the two fire
    on the same thread, in order).  Each finished event lands in:

      - the bounded ``events`` ring (``PFX_COMPILE_LOG_CAP``, default
        256) — served offline by ``tools/report.py``;
      - the flight recorder ring (``event: "compile"``) so a crash dump
        explains late retraces;
      - ``pfx_compile_events_total`` / ``pfx_compile_seconds_total``.

    The jax loggers it taps get ``propagate = False`` while installed so
    per-compile chatter does not spam run logs; records that are NOT
    compile chatter (a broken persistent cache logs errors through the
    same ``jax._src.compiler`` logger) are re-emitted through the repo
    logger at their original level, so owning the loggers never hides a
    real problem (uninstall restores propagation).  Gate:
    ``PFX_COMPILE_LOG=0`` disables installation entirely."""

    _TAPPED_LOGGERS = (
        "jax._src.interpreters.pxla",
        "jax._src.dispatch",
        "jax._src.compiler",  # persistent-cache-hit lines (also silenced)
    )

    def __init__(self, capacity: Optional[int] = None) -> None:
        from paddlefleetx_tpu.utils.telemetry import _env_int

        cap = capacity if capacity is not None else _env_int(
            "PFX_COMPILE_LOG_CAP", 256
        )
        self.events: deque = deque(maxlen=cap)
        self._lock = threading.Lock()
        self._fn_counts: Dict[str, int] = {}
        self._prev_avals: Dict[str, List[str]] = {}
        self._pending = threading.local()
        self._installed = False
        self._handler: Optional[logging.Handler] = None
        self._was_propagating: Dict[str, bool] = {}

    # -- ingestion ------------------------------------------------------
    def observe_compile_start(self, fn: str, avals_str: str) -> None:
        self._pending.value = (fn, _split_avals(avals_str), False)

    def observe_cache_hit(self) -> None:
        pending = getattr(self._pending, "value", None)
        if pending is not None:
            self._pending.value = (pending[0], pending[1], True)

    def observe_compile_done(self, elapsed_s: float) -> None:
        pending = getattr(self._pending, "value", None)
        self._pending.value = None
        if pending is None:
            return
        fn, avals, cache_hit = pending
        with self._lock:
            prev = self._prev_avals.get(fn)
            diff = diff_avals(prev, avals)
            self._prev_avals[fn] = avals
            n = self._fn_counts[fn] = self._fn_counts.get(fn, 0) + 1
            event = {
                "event": "compile",
                "fn": fn,
                "elapsed_s": round(float(elapsed_s), 4),
                "n_args": len(avals),
                "diff": diff,
                "nth_for_fn": n,
            }
            if cache_hit:
                # the retrace happened (a new compile key) but the
                # executable came from the persistent cache — the step
                # paid trace time, not XLA time
                event["cache_hit"] = True
            self.events.append(event)
        try:
            from paddlefleetx_tpu.utils.telemetry import (
                get_flight_recorder,
                get_registry,
            )

            get_flight_recorder().record(dict(event))
            reg = get_registry()
            reg.counter("pfx_compile_events_total").inc()
            reg.counter("pfx_compile_seconds_total").inc(float(elapsed_s))
        except Exception as e:  # noqa: BLE001 — observability must not
            # take down a compile (e.g. a test-scoped registry reset race)
            logger.warning(f"compile-event export failed: {e}")

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self.events)

    # -- wiring ---------------------------------------------------------
    def install(self) -> "CompileWatcher":
        if self._installed:
            return self
        import jax
        from jax._src import monitoring

        watcher = self

        class _Handler(logging.Handler):
            def emit(self, record: logging.LogRecord) -> None:
                try:
                    msg = record.getMessage()
                    m = _COMPILING_RE.search(msg)
                    if m:
                        watcher.observe_compile_start(m.group(1), m.group(2))
                        return
                    if _CACHE_HIT_RE.search(msg):
                        watcher.observe_cache_hit()
                        return
                    if (
                        record.levelno >= logging.WARNING
                        and not _COMPILE_CHATTER_RE.search(msg)
                    ):
                        # not per-compile chatter: this logger's
                        # propagation is off, so re-emit through the repo
                        # logger — a broken persistent cache (ERROR via
                        # jax._src.compiler) must stay visible
                        logger.log(
                            record.levelno, f"[{record.name}] {msg}"
                        )
                except Exception:  # noqa: BLE001 — never raise from logging
                    pass

        self._handler = _Handler(level=logging.DEBUG)
        for name in self._TAPPED_LOGGERS:
            lg = logging.getLogger(name)
            self._was_propagating[name] = lg.propagate
            lg.addHandler(self._handler)
            # jax's per-compile lines log at WARNING once jax_log_compiles
            # is on; without this they would spam every run's stderr
            lg.propagate = False

        def _on_duration(name: str, secs: float, **_kw) -> None:
            if name == "/jax/core/compile/backend_compile_duration":
                watcher.observe_compile_done(secs)

        monitoring.register_event_duration_secs_listener(_on_duration)
        jax.config.update("jax_log_compiles", True)
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Detach the logging taps (test isolation; the monitoring
        listener stays registered — jax offers no unregister — but goes
        quiet once ``_installed`` is cleared via the pending gate)."""
        if not self._installed:
            return
        import jax

        for name in self._TAPPED_LOGGERS:
            lg = logging.getLogger(name)
            if self._handler is not None:
                lg.removeHandler(self._handler)
            lg.propagate = self._was_propagating.get(name, True)
        jax.config.update("jax_log_compiles", False)
        self._installed = False


_watcher: Optional[CompileWatcher] = None


def get_compile_watcher() -> CompileWatcher:
    """The process-wide compile watcher (not yet installed)."""
    global _watcher
    if _watcher is None:
        _watcher = CompileWatcher()
    return _watcher


def install_compile_watcher() -> Optional[CompileWatcher]:
    """Install the process-wide watcher unless ``PFX_COMPILE_LOG=0``.
    Idempotent — the engine and the serve CLI both call this."""
    raw = (os.environ.get("PFX_COMPILE_LOG") or "").strip()
    if raw and raw not in ("1", "true", "on"):
        if raw in ("0", "false", "off"):
            return None
        raise ValueError(
            f"PFX_COMPILE_LOG={raw!r}: use 0/1 (loud-parse: unset it or "
            "pass a valid value)"
        )
    return get_compile_watcher().install()
