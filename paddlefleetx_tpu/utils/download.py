"""Pretrained-artifact fetch/cache utilities.

TPU-native counterpart of the reference ``ppfleetx/utils/download.py``
(cached_path :43, _download with retry :60-120, md5 check :123-150): a
small, dependency-light cache keyed on the source name with checksum
validation.  Local paths pass through untouched; URLs download into
``~/.cache/paddlefleetx_tpu`` with bounded retries and an atomic rename so
a killed download never leaves a half-written artifact in the cache.

Checksums: ``md5sum`` (reference parity) and/or ``sha256sum`` (collision-
resistant — the one to publish for new artifacts); both are checked when
given.  A CACHED file that no longer matches is quarantined (renamed
``*.corrupt``, the fault-tolerance convention — docs/fault_tolerance.md)
and re-fetched under the shared retry; exhaustion fails loudly naming the
URL.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tempfile
import urllib.request
from typing import Optional

from paddlefleetx_tpu.utils.log import logger

DOWNLOAD_RETRY_LIMIT = 3
DEFAULT_CACHE_DIR = "~/.cache/paddlefleetx_tpu"


def is_url(path: str) -> bool:
    return path.startswith("http://") or path.startswith("https://")


def _hashfile(path: str, algo: str, chunk: int = 1 << 20) -> str:
    h = hashlib.new(algo)
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def md5file(path: str, chunk: int = 1 << 20) -> str:
    return _hashfile(path, "md5", chunk)


def sha256file(path: str, chunk: int = 1 << 20) -> str:
    return _hashfile(path, "sha256", chunk)


def check_md5(path: str, md5sum: Optional[str]) -> bool:
    """True when the file matches the expected digest (or no digest given,
    reference md5check semantics)."""
    if md5sum is None:
        return True
    ok = md5file(path) == md5sum
    if not ok:
        logger.warning(f"md5 mismatch for {path} (expected {md5sum})")
    return ok


def check_sha256(path: str, sha256sum: Optional[str]) -> bool:
    """True when the file matches the expected sha256 (or none given)."""
    if sha256sum is None:
        return True
    ok = sha256file(path) == sha256sum
    if not ok:
        logger.warning(f"sha256 mismatch for {path} (expected {sha256sum})")
    return ok


def _checksums_ok(
    path: str, md5sum: Optional[str], sha256sum: Optional[str]
) -> bool:
    return check_md5(path, md5sum) and check_sha256(path, sha256sum)


def quarantine_file(path: str) -> str:
    """Rename a corrupt cached artifact to ``*.corrupt`` (the shared
    utils/checkpoint.corrupt_rename convention) so it can never be served
    from cache again; loud by design."""
    from paddlefleetx_tpu.utils.checkpoint import CORRUPT_SUFFIX, corrupt_rename

    dst = corrupt_rename(path)
    if dst is None:  # raced away: treat as already quarantined
        return path + CORRUPT_SUFFIX
    logger.error(
        f"QUARANTINED corrupt cached artifact: {path} -> {dst} "
        "(checksum mismatch; re-fetching — inspect or delete the .corrupt "
        "file)"
    )
    return dst


def _download(
    url: str,
    dst: str,
    md5sum: Optional[str],
    sha256sum: Optional[str] = None,
) -> str:
    """Fetch ``url`` to ``dst`` atomically with bounded retries (the shared
    utils/resilience.retry helper: PFX_RETRY_* knobs apply; default
    attempts come from DOWNLOAD_RETRY_LIMIT for reference parity)."""
    from paddlefleetx_tpu.utils.resilience import _env_int, retry

    os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)

    def fetch():
        tmp_fd, tmp_path = tempfile.mkstemp(dir=os.path.dirname(dst) or ".")
        os.close(tmp_fd)
        try:
            logger.info(f"downloading {url}")
            with urllib.request.urlopen(url) as r, open(tmp_path, "wb") as f:
                shutil.copyfileobj(r, f)
            if not _checksums_ok(tmp_path, md5sum, sha256sum):
                # a checksum mismatch IS retryable here: the mirror may
                # have served a truncated body this attempt
                raise IOError(f"checksum mismatch downloading {url}")
            os.replace(tmp_path, dst)  # atomic: cache never half-written
            return dst
        finally:
            if os.path.exists(tmp_path):
                os.remove(tmp_path)

    import http.client

    return retry(
        fetch,
        attempts=_env_int("PFX_RETRY_ATTEMPTS", DOWNLOAD_RETRY_LIMIT, minimum=1),
        # urllib transport errors are URLError/HTTPError (OSError
        # subclasses), but a connection dropped MID-BODY surfaces from
        # copyfileobj as http.client.IncompleteRead — an HTTPException,
        # NOT an OSError — and must stay retryable too
        retryable=(OSError, http.client.HTTPException),
        desc=f"download {url}",
    )


def cached_path(
    url_or_path: str,
    cache_dir: Optional[str] = None,
    md5sum: Optional[str] = None,
    sha256sum: Optional[str] = None,
) -> str:
    """Resolve a local path or URL to a local file, downloading into the
    cache when needed (reference cached_path :43-58).  A cached file whose
    checksum no longer matches is QUARANTINED (``*.corrupt``) and
    re-fetched; a local (non-cache) file that mismatches raises — renaming
    a user's own file out from under them is not this module's call."""
    if not is_url(url_or_path):
        path = os.path.expanduser(url_or_path)
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        if not _checksums_ok(path, md5sum, sha256sum):
            raise IOError(f"checksum mismatch for local file {path}")
        return path

    cache_dir = os.path.expanduser(cache_dir or DEFAULT_CACHE_DIR)
    fname = os.path.split(url_or_path)[-1]
    dst = os.path.join(cache_dir, fname)
    if os.path.exists(dst):
        if _checksums_ok(dst, md5sum, sha256sum):
            return dst
        # bit-rot (or a stale artifact under a reused name): get it out of
        # the cache loudly, then fall through to a fresh fetch
        quarantine_file(dst)
    return _download(url_or_path, dst, md5sum, sha256sum)
