"""Model export: AOT-compiled / serialized inference artifacts.

Reference: ``export_inference_model`` (ppfleetx/utils/export.py:24-72, via
paddle.jit.save -> .pdmodel/.pdiparams) and the InferenceEngine consuming it.
TPU-native: the forward is staged to StableHLO with ``jax.export`` (portable
serialized artifact, reloadable without the model code) and params are saved
as an orbax checkpoint next to it.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Sequence

import jax

from paddlefleetx_tpu.utils.log import logger


def export_inference_model(
    fn: Callable,
    example_args: Sequence[Any],
    params: Any,
    out_dir: str,
) -> str:
    """Serialize jit(fn) at example shapes + params -> out_dir/{model.stablehlo,
    params/}."""
    import orbax.checkpoint as ocp
    from jax import export as jax_export

    os.makedirs(out_dir, exist_ok=True)
    exported = jax_export.export(jax.jit(fn))(params, *example_args)
    blob = exported.serialize()
    with open(os.path.join(out_dir, "model.stablehlo"), "wb") as f:
        f.write(blob)

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(os.path.abspath(out_dir), "params"), params, force=True)
    ckptr.wait_until_finished()
    logger.info(f"exported inference model -> {out_dir} ({len(blob)/1e6:.1f}MB HLO)")
    return out_dir


def load_inference_model(out_dir: str, params_target: Any = None):
    """Reload (exported_fn, params).  ``params_target`` supplies abstract
    shapes for orbax; None restores with saved metadata."""
    import orbax.checkpoint as ocp
    from jax import export as jax_export

    with open(os.path.join(out_dir, "model.stablehlo"), "rb") as f:
        exported = jax_export.deserialize(f.read())
    ckptr = ocp.StandardCheckpointer()
    path = os.path.join(os.path.abspath(out_dir), "params")
    params = ckptr.restore(path, params_target) if params_target is not None else ckptr.restore(path)
    return exported.call, params
