"""Checkpoint helpers: discovery, integrity validation, quarantine,
retention GC, and params-only restore.

The full train-state save/load contract lives in the Engine
(core/engine.py, orbax + meta.json); this module owns everything AROUND a
saved directory: deciding whether it is restorable, picking the newest
good one for auto-resume (quarantining corrupt ones so the crash-loop
falls back instead of wedging), and bounding how many the run keeps.

Checkpoint validity has two tiers:

  - **structural** (`validate_checkpoint`, cheap, no orbax import): a
    parseable ``meta.json`` (written last + atomically by the Engine, so
    it marks write-completeness) AND an orbax payload dir (``state/`` or
    ``params/``) holding ``_METADATA`` plus non-empty array data.  Catches
    crashed saves, half-synced dirs, and stray ``meta.json``-only stubs.
  - **restorability**: only an actual orbax restore proves the bytes are
    sound.  Bit-rot inside an array file passes the structural check; the
    Engine's load (and `restore_params`) quarantine on restore failure so
    the next resume attempt falls back to the previous good directory.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, List, Optional, Tuple

from paddlefleetx_tpu.utils.log import logger

CORRUPT_SUFFIX = ".corrupt"


def corrupt_rename(path: str) -> Optional[str]:
    """Rename ``path`` to the first free ``*.corrupt[.N]`` name — THE
    quarantine convention, shared by checkpoint dirs (here), index-map
    caches (data/index_cache.py), and cached download artifacts
    (utils/download.py), so operators grep for one suffix.  Returns the
    new path, or None when another process already renamed/removed it
    (shared-storage race: the goal — that path no longer selects — is
    achieved either way)."""
    path = os.path.abspath(path.rstrip("/"))
    dst = path + CORRUPT_SUFFIX
    n = 1
    while os.path.exists(dst):
        dst = f"{path}{CORRUPT_SUFFIX}.{n}"
        n += 1
    try:
        os.rename(path, dst)
    except FileNotFoundError:
        return None
    return dst


def _step_dirs(output_dir: str) -> List[Tuple[int, str]]:
    """(step, path) for every ``step_N`` dir with a PARSEABLE meta.json,
    newest first.  Dirs without a parseable meta are crashed/in-flight
    saves: skipped here (never quarantined — an async save from a live
    process legitimately has no meta yet)."""
    found: List[Tuple[int, str]] = []
    if not os.path.isdir(output_dir):
        return found
    for name in os.listdir(output_dir):
        if not name.startswith("step_") or name.endswith(CORRUPT_SUFFIX):
            continue
        path = os.path.join(output_dir, name)
        try:
            step = int(name[len("step_"):])
            with open(os.path.join(path, "meta.json")) as f:
                json.load(f)
        except (ValueError, OSError, json.JSONDecodeError):
            continue
        found.append((step, path))
    found.sort(reverse=True)
    return found


def validate_checkpoint(path: str) -> Optional[str]:
    """Structural integrity check; returns None when OK, else the reason.

    Validates beyond meta.json: the orbax payload dir must exist, carry
    its ``_METADATA`` tree descriptor, and hold non-empty array data —
    a meta.json-only stub (half-synced restore source, crashed post-save
    cleanup) must not be selected for resume."""
    try:
        with open(os.path.join(path, "meta.json")) as f:
            json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return f"meta.json missing/unparseable ({e})"
    payload = None
    for sub in ("state", "params"):
        if os.path.isdir(os.path.join(path, sub)):
            payload = sub
            break
    if payload is None:
        return "no state/ or params/ payload dir"
    root = os.path.join(path, payload)
    if not os.path.exists(os.path.join(root, "_METADATA")):
        return f"{payload}/_METADATA missing (interrupted array write)"
    # array dirs present: the ocdbt layout stores chunk data under d/
    # (consolidated) and/or ocdbt.process_*/d/ (per-process); a payload
    # with tree metadata but no chunk bytes is a half-synced stub
    import glob

    data_files = glob.glob(os.path.join(root, "d", "*")) + glob.glob(
        os.path.join(root, "ocdbt.process_*", "d", "*")
    )
    if not any(os.path.getsize(f) > 0 for f in data_files):
        return f"{payload}/ holds no array data"
    return None


def quarantine_checkpoint(path: str) -> str:
    """Rename a corrupt checkpoint dir to ``<path>.corrupt`` (``.corrupt.N``
    when colliding) so resume cannot pick it again; returns the new path.
    Loud by design: a quarantine should never scroll past unnoticed.

    Race-tolerant for multi-host resume over shared storage: when another
    process already renamed (or removed) the dir, the rename's
    FileNotFoundError is absorbed — the goal (that path no longer selects)
    is achieved either way, and crashing the loser host would recreate the
    crash-loop this module exists to prevent."""
    dst = corrupt_rename(path)
    if dst is None:
        logger.warning(
            f"quarantine of {path}: already renamed/removed by another "
            "process; continuing"
        )
        return os.path.abspath(path.rstrip("/")) + CORRUPT_SUFFIX
    logger.error(
        f"QUARANTINED corrupt checkpoint: {path} -> {dst} "
        "(inspect or delete manually; resume falls back to the previous "
        "good checkpoint)"
    )
    return dst


class QuarantineBudget:
    """Shared cap on how many directories one logical resume attempt may
    quarantine, across BOTH the structural walk (latest_checkpoint) and
    the restore-failure path (resume_with_fallback) — without a shared
    counter, alternating structural/bit-rot failures could multiply the
    two bounds and still eat the history."""

    def __init__(self, remaining: int) -> None:
        self.remaining = int(remaining)

    def spend(self, path: str, reason: str, output_dir: str) -> None:
        """Quarantine ``path`` if budget remains, else raise the systemic
        error."""
        if self.remaining <= 0:
            raise RuntimeError(
                f"quarantine budget exhausted under {output_dir} and {path} "
                f"failed too ({reason}) — this is systemic (storage, "
                "config/topology mismatch), not per-checkpoint corruption; "
                "refusing to quarantine further"
            )
        quarantine_checkpoint(path)
        self.remaining -= 1


def latest_checkpoint(
    output_dir: str,
    validate: bool = True,
    quarantine: bool = True,
    max_quarantines: int = 3,
    budget: Optional[QuarantineBudget] = None,
) -> Optional[str]:
    """Newest restorable ``step_N`` checkpoint dir (None if none).

    Only complete checkpoints count: the Engine writes meta.json last (and
    atomically), so a dir without a parseable meta.json is a crashed save
    and is skipped.  With ``validate`` (the default), each candidate must
    also pass the structural check; a newest-but-broken checkpoint is
    quarantined (renamed ``*.corrupt``) when ``quarantine`` is set, and
    selection falls back to the next older one.

    Quarantines are bounded by ``max_quarantines`` per call (or by a
    caller-shared ``budget``): more broken-looking dirs in a row than
    that means the problem is systemic (a storage mount showing
    half-synced dirs, a layout change breaking the validator) — renaming
    the entire history over it would destroy good checkpoints, so the
    walk stops with a loud error instead."""
    budget = budget if budget is not None else QuarantineBudget(max_quarantines)
    for _step, path in _step_dirs(output_dir):
        if not validate:
            return path
        reason = validate_checkpoint(path)
        if reason is None:
            return path
        logger.error(f"checkpoint {path} failed validation: {reason}")
        if quarantine:
            budget.spend(path, reason, output_dir)
    return None


def gc_checkpoints(
    output_dir: str, keep_last_n: int, protect: Optional[str] = None
) -> List[str]:
    """Retention GC: delete all but the newest ``keep_last_n`` complete
    ``step_N`` dirs.  ``protect`` (the last verified-good checkpoint — the
    rollback target) is NEVER deleted regardless of age.  Structurally
    invalid dirs don't count toward the keep quota (keeping N corrupt dirs
    while deleting the good one would defeat the fallback); they are left
    in place for `latest_checkpoint` to quarantine.  Returns the removed
    paths."""
    if keep_last_n <= 0:
        return []
    protect_abs = os.path.abspath(protect) if protect else None
    kept = 0
    removed: List[str] = []
    for _step, path in _step_dirs(output_dir):
        if validate_checkpoint(path) is not None:
            continue
        if kept < keep_last_n or os.path.abspath(path) == protect_abs:
            kept += 1
            continue
        shutil.rmtree(path)
        removed.append(path)
        logger.info(f"retention GC (keep_last_n={keep_last_n}): removed {path}")
    return removed


# Substrings of the ValueError messages tensorstore/zarr/orbax raise for
# BAD BYTES (observed: "DATA_LOSS: ... Error decoding local file ...
# manifest", "OUT_OF_RANGE: ... Error reading ... in OCDBT database").
# Only these quarantine a directory: a ValueError can equally mean a
# config/topology mismatch (shape/sharding/tree vs the restore target),
# which condemns EVERY checkpoint and must propagate instead of renaming
# good multi-GB artifacts over a config typo.
_CORRUPTION_MARKERS = (
    "DATA_LOSS", "OUT_OF_RANGE", "Error decoding", "Error reading",
    "Error opening", "manifest", "ocdbt", "zarr", "checksum",
)


def is_corruption_error(e: BaseException) -> bool:
    """True when a restore failure indicates bad bytes in THIS directory
    (quarantine-worthy), as opposed to a systemic problem — retry-exhausted
    transient I/O (RuntimeError), OOM, orbax API drift, or a restore-target
    mismatch — that is no evidence against the checkpoint itself.
    json.JSONDecodeError (rotten meta.json) is a ValueError subclass."""
    if isinstance(e, json.JSONDecodeError):
        return True
    if not isinstance(e, ValueError):
        return False
    msg = str(e)
    return any(marker in msg for marker in _CORRUPTION_MARKERS)


def resume_with_fallback(
    engine, output_dir: str, max_quarantines: int = 3
) -> Optional[str]:
    """auto_resume: load the newest valid checkpoint into ``engine``,
    quarantining any whose RESTORE fails with a corruption error (bit-rot
    passes the structural check) and falling back to the next older one.
    Returns the path that loaded, or None when no checkpoint exists.

    Two guards bound the blast radius so a systemic failure can never eat
    the whole checkpoint history: only corruption-class errors
    (``is_corruption_error``) quarantine — a storage outage that survives
    the retry budget, or a config/topology mismatch that breaks EVERY
    dir, re-raises on the spot — and at most ``max_quarantines``
    directories are quarantined per resume attempt, SHARED between the
    structural walk and restore failures via one QuarantineBudget (more
    corrupt-in-a-row than that means the problem is not the
    checkpoints)."""
    budget = QuarantineBudget(max_quarantines)
    while True:
        path = latest_checkpoint(output_dir, budget=budget)
        if path is None:
            return None
        logger.info(f"auto_resume: found {path}")
        try:
            engine.load(path)
            return path
        except Exception as e:  # noqa: BLE001 — classified right below
            if not is_corruption_error(e):
                raise
            logger.error(
                f"auto_resume: checkpoint {path} failed to load ({e}); "
                "quarantining and falling back"
            )
            budget.spend(path, str(e), output_dir)


def restore_params(ckpt_dir: str) -> Any:
    """Params from either checkpoint layout: a full Engine state dir
    (``state/`` holding params+opt_state) or a params-only dir
    (``params/``, e.g. from tools/convert_hf_gpt2.py).

    Transient I/O errors are retried (PFX_RETRY_* knobs); a restore that
    still fails quarantines the directory and raises an actionable error
    naming the quarantined path."""
    import orbax.checkpoint as ocp

    from paddlefleetx_tpu.utils.resilience import retry

    ckpt_dir = os.path.abspath(ckpt_dir)
    try:
        if os.path.isdir(os.path.join(ckpt_dir, "params")):
            return retry(
                lambda: ocp.StandardCheckpointer().restore(
                    os.path.join(ckpt_dir, "params")
                ),
                desc=f"params restore {ckpt_dir}",
            )
        # full train-state checkpoint: partially restore ONLY the params
        # subtree (a standard restore would materialize the optimizer
        # moments — ~2x the param bytes — on the host just to throw away)
        import jax

        path = os.path.join(ckpt_dir, "state")
        ckptr = ocp.PyTreeCheckpointer()
        meta = ckptr.metadata(path)
        tree = getattr(meta, "item_metadata", meta)
        tree = getattr(tree, "tree", tree)
        item = {"params": jax.tree.map(lambda _m: 0.0, dict(tree)["params"])}
        restored = retry(
            lambda: ckptr.restore(
                path,
                args=ocp.args.PyTreeRestore(item=item, partial_restore=True),
            ),
            desc=f"params restore {ckpt_dir}",
        )
        return restored["params"]
    except Exception as e:  # noqa: BLE001 — classified right below
        # only corruption-class failures condemn the directory; an
        # exhausted transient retry (RuntimeError), OOM, a restore-target
        # mismatch, or orbax API drift propagates untouched — renaming a
        # good multi-GB artifact over a code bug would be worse than the
        # corruption it guards
        if not is_corruption_error(e) or not os.path.isdir(ckpt_dir):
            raise
        quarantined = quarantine_checkpoint(ckpt_dir)
        raise RuntimeError(
            f"checkpoint {ckpt_dir} failed to restore and was quarantined "
            f"to {quarantined}: {e}.  Re-fetch the artifact, or (for "
            "training resume) rely on auto_resume falling back to the "
            "previous good step_N directory."
        ) from e


def load_pretrained_params(cfg) -> Optional[Any]:
    """Params from ``Engine.save_load.ckpt_dir`` (None when unset)."""
    ckpt_dir = cfg.get("Engine", {}).get("save_load", {}).get("ckpt_dir")
    if not ckpt_dir:
        return None
    return restore_params(ckpt_dir)


def save_params_checkpoint(out_dir: str, params, source: str, model_fields: dict) -> str:
    """Write the params-only checkpoint contract shared by the HF import
    tools: ``params/`` (orbax), ``meta.json`` (format+source), and
    ``model.yaml`` (the matching Model config block)."""
    import orbax.checkpoint as ocp

    out = os.path.abspath(out_dir)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(out, "params"), params, force=True)
    ckptr.wait_until_finished()
    with open(os.path.join(out, "meta.json"), "w") as f:
        json.dump({"format": "params-only", "source": source}, f)
    with open(os.path.join(out, "model.yaml"), "w") as f:
        f.write("Model:\n")
        for k, v in model_fields.items():
            if isinstance(v, float):
                # YAML 1.1 reads "1e-12" as a STRING; force a float form
                text = repr(v)
                if "e" in text and "." not in text.split("e")[0]:
                    mant, exp = text.split("e")
                    text = f"{mant}.0e{exp}"
                v = text
            f.write(f"  {k}: {v}\n")
    return out
