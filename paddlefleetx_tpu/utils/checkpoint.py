"""Small checkpoint helpers shared by the CLI tools.

The full train-state save/load contract lives in the Engine
(core/engine.py, orbax + meta.json); deploy-side tools only ever need the
params subtree of a saved state — this is that one snippet, in one place.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def load_pretrained_params(cfg) -> Optional[Any]:
    """Params from ``Engine.save_load.ckpt_dir`` (None when unset)."""
    ckpt_dir = cfg.get("Engine", {}).get("save_load", {}).get("ckpt_dir")
    if not ckpt_dir:
        return None
    import orbax.checkpoint as ocp

    restored = ocp.StandardCheckpointer().restore(
        os.path.join(os.path.abspath(ckpt_dir), "state")
    )
    return restored["params"]
