"""Small checkpoint helpers shared by the CLI tools.

The full train-state save/load contract lives in the Engine
(core/engine.py, orbax + meta.json); deploy-side tools only ever need the
params subtree of a saved state — this is that one snippet, in one place.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def latest_checkpoint(output_dir: str) -> Optional[str]:
    """Newest ``step_N`` checkpoint dir under ``output_dir`` (None if none).

    Only complete checkpoints count: the Engine writes meta.json last (and
    atomically), so a dir without a *parseable* meta.json is a crashed save
    and is skipped — the crash-loop then falls back to the previous one.
    """
    import json

    best_step, best = -1, None
    if not os.path.isdir(output_dir):
        return None
    for name in os.listdir(output_dir):
        if not name.startswith("step_"):
            continue
        path = os.path.join(output_dir, name)
        try:
            step = int(name[len("step_"):])
            with open(os.path.join(path, "meta.json")) as f:
                json.load(f)
        except (ValueError, OSError, json.JSONDecodeError):
            continue
        if step > best_step:
            best_step, best = step, path
    return best


def restore_params(ckpt_dir: str) -> Any:
    """Params from either checkpoint layout: a full Engine state dir
    (``state/`` holding params+opt_state) or a params-only dir
    (``params/``, e.g. from tools/convert_hf_gpt2.py)."""
    import orbax.checkpoint as ocp

    ckpt_dir = os.path.abspath(ckpt_dir)
    if os.path.isdir(os.path.join(ckpt_dir, "params")):
        return ocp.StandardCheckpointer().restore(os.path.join(ckpt_dir, "params"))
    # full train-state checkpoint: partially restore ONLY the params subtree
    # (a standard restore would materialize the optimizer moments — ~2x the
    # param bytes — on the host just to throw them away)
    import jax

    path = os.path.join(ckpt_dir, "state")
    ckptr = ocp.PyTreeCheckpointer()
    meta = ckptr.metadata(path)
    tree = getattr(meta, "item_metadata", meta)
    tree = getattr(tree, "tree", tree)
    item = {"params": jax.tree.map(lambda _m: 0.0, dict(tree)["params"])}
    restored = ckptr.restore(
        path, args=ocp.args.PyTreeRestore(item=item, partial_restore=True)
    )
    return restored["params"]


def load_pretrained_params(cfg) -> Optional[Any]:
    """Params from ``Engine.save_load.ckpt_dir`` (None when unset)."""
    ckpt_dir = cfg.get("Engine", {}).get("save_load", {}).get("ckpt_dir")
    if not ckpt_dir:
        return None
    return restore_params(ckpt_dir)


def save_params_checkpoint(out_dir: str, params, source: str, model_fields: dict) -> str:
    """Write the params-only checkpoint contract shared by the HF import
    tools: ``params/`` (orbax), ``meta.json`` (format+source), and
    ``model.yaml`` (the matching Model config block)."""
    import json

    import orbax.checkpoint as ocp

    out = os.path.abspath(out_dir)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(out, "params"), params, force=True)
    ckptr.wait_until_finished()
    with open(os.path.join(out, "meta.json"), "w") as f:
        json.dump({"format": "params-only", "source": source}, f)
    with open(os.path.join(out, "model.yaml"), "w") as f:
        f.write("Model:\n")
        for k, v in model_fields.items():
            if isinstance(v, float):
                # YAML 1.1 reads "1e-12" as a STRING; force a float form
                text = repr(v)
                if "e" in text and "." not in text.split("e")[0]:
                    mant, exp = text.split("e")
                    text = f"{mant}.0e{exp}"
                v = text
            f.write(f"  {k}: {v}\n")
    return out
