"""Unified telemetry: the process-wide metrics registry, span timing, MFU
accounting, and the crash flight recorder.

PRs 1-4 each grew an ad-hoc stats surface (``GenerationServer.stats``,
``RequestQueue.stats``, loader ``stats()``, the ``/healthz`` counter dict,
the engine's JSONL metrics stream) with no single place to scrape and no
hardware-utilization signal.  This module is the one layer under all of
them:

  - **Registry** — thread-safe counters, gauges, and histograms with label
    support.  Every metric NAME must be declared in the ``METRICS`` table
    below and match ``^pfx_[a-z0-9_]+$`` (``tools/lint.py`` E10 enforces
    both statically; the registry raises on undeclared names at runtime),
    so the ``/metrics`` namespace cannot fragment the way the per-module
    dicts did.  ``snapshot()`` returns ONE locked, consistent view;
    ``render_prometheus()`` renders that same view as Prometheus text
    exposition — ``/metrics`` and ``/healthz`` in ``tools/serve.py`` are
    two renderings of one snapshot, never two racing read paths.
  - **StatsView** — a dict-like per-instance stats object (drop-in for the
    old hand-rolled dicts, so ``server.stats["traces"] += 1`` keeps
    working) whose numeric keys are exported onto the registry through a
    weakly-referenced collector.  Instance-local semantics stay exactly as
    before (tests assert absolute per-instance counts); the registry sums
    across live instances at snapshot time.
  - **Span** — lightweight monotonic-clock phase timing.  ``mark()``
    stamps a labeled instant (callers may inject externally-captured
    timestamps, e.g. the request queue's pickup time); ``phases()`` turns
    consecutive marks into durations; ``event()`` shapes the span for the
    flight recorder.
  - **MFU accounting** — the analytic GPT-family FLOPs estimator
    (6·N per token for fwd+bwd, 2·N forward-only; PaLM's convention,
    Chowdhery et al. 2022) plus the per-device-kind peak-FLOPs table
    behind the ``PFX_PEAK_FLOPS`` override, shared by the engine's step
    records, ``bench.py``, and ``benchmarks/bench_decode.py`` so every
    throughput number is hardware-normalized by the SAME estimator.
  - **FlightRecorder** — a bounded ring of recent structured events (step
    records, data_skip, rollback, preempt_save, gen_errors, watchdog
    flips, request spans) dumped to ``flight_recorder.jsonl`` on crash,
    force-quit, watchdog-degraded, and anomaly rollback — postmortems no
    longer depend on having had ``Engine.metrics_file`` set.

Knobs (loud-parse, repo convention): ``PFX_PEAK_FLOPS`` (per-chip peak
FLOP/s used as the MFU denominator; default per detected device kind),
``PFX_FLIGHT_DIR`` (artifact directory for dumps + trace exports,
default ./artifacts/), ``PFX_FLIGHT_RECORDER`` (explicit dump path —
overrides everything), ``PFX_FLIGHT_RECORDER_CAP`` (ring capacity,
default 256).  The :class:`SLOTracker` evaluates configured serving
objectives (p99 TTFT, error rate) over rolling multi-window burn rates
and exports them as ``pfx_slo_*`` gauges (docs/observability.md).

Contract notes: metric *mutations* never take the registry lock (each
metric/collector owns a private lock), so hot paths (the serving scheduler,
the train loop) never contend with a scrape; ``snapshot()`` takes the
registry lock and then each collector's lock, and nothing acquires them in
the other order.  No jax import at module scope — ``bench.py``'s parent
process and ``tools/lint.py`` stay jax-free.
"""

from __future__ import annotations

import bisect
import json
import os
import re
import sys
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from paddlefleetx_tpu.utils.log import logger

METRIC_NAME_RE = re.compile(r"^pfx_[a-z0-9_]+$")

# ---------------------------------------------------------------------------
# THE metric declaration table: name -> (kind, help).  Every name emitted
# through the registry must live here (runtime check + tools/lint.py E10).
# Naming schema: pfx_<subsystem>_<what>[_<unit>][_total]; seconds for time,
# *_total for monotonic cumulatives.
# ---------------------------------------------------------------------------
METRICS: Dict[str, Tuple[str, str]] = {
    # serving core (core/serving.py GenerationServer)
    "pfx_serving_requests_total": ("counter", "Completed generate_ids calls"),
    "pfx_serving_tokens_out_total": ("counter", "Generated tokens delivered"),
    "pfx_serving_gen_seconds_total": ("counter", "Wall seconds inside generate_ids"),
    "pfx_serving_traces_total": ("counter", "Decode jit trace-time entries (retrace probe)"),
    "pfx_serving_gen_errors_total": ("counter", "Generation failures"),
    "pfx_serving_last_latency_seconds": ("gauge", "Latency of the most recent generate_ids call"),
    "pfx_serving_warmup_seconds_total": ("counter", "Seconds spent in warmup compiles"),
    # request queue (core/request_queue.py)
    "pfx_queue_submitted_total": ("counter", "Requests admitted"),
    "pfx_queue_completed_total": ("counter", "Requests answered"),
    "pfx_queue_batches_total": ("counter", "Runner batches executed"),
    "pfx_queue_coalesced_batches_total": ("counter", "Batches that merged >1 request"),
    "pfx_queue_coalesced_requests_total": ("counter", "Requests served via a coalesced batch"),
    "pfx_queue_shed_deadline_total": ("counter", "Requests shed at their deadline"),
    "pfx_queue_rejected_full_total": ("counter", "Admissions rejected: queue full"),
    "pfx_queue_rejected_closed_total": ("counter", "Admissions rejected: draining"),
    "pfx_queue_gen_errors_total": ("counter", "Runner batches that raised"),
    "pfx_queue_depth": ("gauge", "Requests waiting in the admission queue"),
    "pfx_queue_busy_seconds": ("gauge", "Seconds the current runner call has been executing"),
    # HTTP surface (tools/serve.py)
    "pfx_batch_occupancy": ("gauge", "Active rows / capacity of the continuous decode batch"),
    "pfx_kv_blocks_used": ("gauge", "Paged KV arena blocks allocated to live sequences"),
    "pfx_kv_blocks_free": ("gauge", "Paged KV arena blocks available"),
    "pfx_kv_blocks_available": ("gauge", "Arena blocks admissible right now: free plus reclaimable cached-prefix blocks (the decode-pool scale signal)"),
    "pfx_request_evictions_total": ("counter", "Rows evicted mid-decode (deadline shed frees their blocks)"),
    "pfx_prefill_admits_total": ("counter", "Rows admitted into the running batch (prefill-on-admit)"),
    # speculative decoding + KV quantization (ops/speculative.py,
    # models/gpt/generation.py spec loops, core/continuous_batching.py)
    "pfx_spec_proposed_total": ("counter", "Draft tokens proposed to the speculative verify step"),
    "pfx_spec_accepted_total": ("counter", "Draft tokens accepted and committed by the verify step"),
    "pfx_spec_accept_rate": ("gauge", "Lifetime accepted/proposed draft ratio"),
    "pfx_kv_bytes": ("gauge", "Live KV-cache payload bytes (used blocks x K+V bytes per block)"),
    # shared-prefix KV reuse + chunked prefill (core/paged_cache.py
    # PrefixIndex, core/continuous_batching.py)
    "pfx_prefix_hits_total": ("counter", "Admissions that reused cached prefix blocks"),
    "pfx_prefix_misses_total": ("counter", "Admissions that found no cached prefix (cache enabled)"),
    "pfx_prefix_hit_tokens_total": ("counter", "Prompt tokens whose KV was reused instead of recomputed"),
    "pfx_prefix_evictions_total": ("counter", "Cached prefix blocks evicted (LRU budget or allocation pressure)"),
    "pfx_prefix_cached_blocks": ("gauge", "Arena blocks currently pinned by the prefix index"),
    "pfx_prefill_chunks_total": ("counter", "Chunked-prefill dispatches (one prompt chunk per scheduler iteration)"),
    # host-RAM spill tier (core/paged_cache.py PrefixSpillStore,
    # core/continuous_batching.py spill/readmit sites)
    "pfx_prefix_spill_bytes": ("gauge", "Host-RAM bytes held by spilled prefix blocks (--prefix-spill-bytes tier)"),
    "pfx_prefix_spill_entries": ("gauge", "Prefix blocks currently resident in the host-RAM spill store"),
    "pfx_prefix_spills_total": ("counter", "Evicted prefix blocks demoted to the host-RAM spill store"),
    "pfx_prefix_readmits_total": ("counter", "Spilled prefix blocks promoted back into the arena on a prefix match"),
    "pfx_prefix_spill_discards_total": ("counter", "Spilled entries lost instead of readmitted (checksum/corruption, budget pressure, failed spill or readmit) — the graceful-degradation counter"),

    "pfx_http_requests_in_flight": ("gauge", "In-flight /generate requests"),
    "pfx_http_responses_total": ("counter", "HTTP responses by status code"),
    "pfx_http_client_gone_total": ("counter", "Responses lost to client disconnects"),
    "pfx_request_latency_seconds": ("histogram", "End-to-end /generate latency"),
    "pfx_request_ttft_seconds": ("histogram", "Time to first token (request receipt to first flush; non-streamed: decode done)"),
    "pfx_request_itl_seconds": ("histogram", "Inter-token latency: gap between consecutive streamed token flushes"),
    "pfx_request_queue_wait_seconds": ("histogram", "Admission to scheduler pickup"),
    "pfx_request_decode_seconds": ("histogram", "Scheduler pickup to decode completion"),
    "pfx_request_per_token_seconds": ("histogram", "Decode seconds per delivered token"),
    "pfx_serve_draining": ("gauge", "1 while the server drains for shutdown"),
    "pfx_serve_degraded": ("gauge", "1 while the wedged-generation watchdog is tripped"),
    # training (core/engine.py)
    "pfx_train_steps_total": ("counter", "Optimizer steps completed"),
    "pfx_train_tokens_total": ("counter", "Training tokens consumed"),
    "pfx_train_loss": ("gauge", "Loss at the last logged step"),
    "pfx_train_tokens_per_second": ("gauge", "Throughput over the last logging window"),
    "pfx_train_model_flops_per_second": ("gauge", "Achieved model FLOP/s (analytic estimator)"),
    "pfx_train_mfu": ("gauge", "Model FLOPs utilization vs per-chip peak"),
    "pfx_train_compile_seconds": ("gauge", "First-dispatch trace+compile seconds"),
    "pfx_train_data_wait_seconds_total": ("counter", "Cumulative seconds the step loop waited on data"),
    "pfx_train_host_seconds_total": ("counter", "Cumulative host-side seconds (placement + dispatch)"),
    "pfx_train_rollbacks_total": ("counter", "Anomaly rollbacks executed"),
    "pfx_train_preempt_saves_total": ("counter", "Preemption-path final checkpoints"),
    # training observatory (utils/model_stats.py; labels: group)
    "pfx_train_group_grad_norm": ("gauge", "Per-layer-group gradient L2 norm at the last stats step"),
    "pfx_train_group_param_norm": ("gauge", "Per-layer-group parameter L2 norm at the last stats step"),
    "pfx_train_group_update_ratio": ("gauge", "Per-layer-group update-norm / param-norm ratio at the last stats step"),
    "pfx_train_group_nonfinite_frac": ("gauge", "Per-layer-group fraction of non-finite gradient elements at the last stats step"),
    # memory watermarks (utils/model_stats.py; labels: device)
    "pfx_mem_host_rss_bytes": ("gauge", "Host resident-set size of this process"),
    "pfx_mem_device_bytes_in_use": ("gauge", "Accelerator bytes currently allocated, per device"),
    "pfx_mem_device_peak_bytes": ("gauge", "Peak accelerator bytes allocated, per device"),
    "pfx_mem_device_limit_bytes": ("gauge", "Accelerator memory capacity, per device"),
    "pfx_mem_headroom_frac": ("gauge", "Worst-device free-memory fraction (None-limit devices excluded)"),
    # retrace attribution (utils/model_stats.py CompileWatcher)
    "pfx_compile_events_total": ("counter", "Backend compiles observed by the compile watcher"),
    "pfx_compile_seconds_total": ("counter", "Cumulative backend-compile seconds observed"),
    # data pipeline (data/batch_sampler.py loader stats)
    "pfx_data_skips_total": ("counter", "Corrupt samples skipped under the budget"),
    "pfx_data_stall_warnings_total": ("counter", "Prefetch starvation warnings"),
    "pfx_data_wait_seconds_total": ("counter", "Loader-reported cumulative data wait"),
    "pfx_data_prefetch_depth": ("gauge", "Batches currently buffered by the prefetcher"),
    # profiler (utils/profiler.py)
    "pfx_profiler_traces_total": ("counter", "Profiler trace windows captured"),
    "pfx_profiler_trace_seconds": ("gauge", "Wall seconds of the last trace window"),
    # deep-dive tracing (utils/tracing.py)
    "pfx_trace_sampled_total": ("counter", "Requests/runs sampled into the trace buffer"),
    # fleet metrics federation (core/router.py FleetFederation): the
    # router re-exports every replica's own pfx_* samples from its scrape
    # under ONE generic family — the original sample name rides the
    # `name` label (histogram _bucket/_sum/_count samples federate as
    # their flat spellings), original labels ride along, and counters
    # re-export as their current value (Prometheus-federation style)
    "pfx_fleet_metric": ("gauge", "Federated replica sample re-exported by the router (labels: replica, pool, name=original sample name + the original labels)"),
    "pfx_fleet_scrape_age_seconds": ("gauge", "Seconds since the replica's last successful federation scrape (labels: replica) — the staleness gauge"),
    "pfx_fleet_scrapes_total": ("counter", "Federation scrape attempts (labels: replica, outcome=ok|missing|error)"),
    "pfx_fleet_series": ("gauge", "Federated series currently re-exported (after the cardinality cap)"),
    "pfx_fleet_series_dropped": ("gauge", "Federated series dropped by the PFX_FLEET_SERIES_CAP label-cardinality cap (warned loudly; 0 when everything fits)"),
    # disaggregated KV handoff (core/continuous_batching.py replica side)
    "pfx_handoff_exports_total": ("counter", "Prefilled rows exported as KV-handoff payloads (prefill replica)"),
    "pfx_handoff_adopts_total": ("counter", "KV-handoff payloads adopted into the arena (decode replica)"),
    "pfx_handoff_bytes_total": ("counter", "KV-handoff payload bytes through THIS replica (labels: transport=direct|proxy; prefill counts direct sends, decode counts receives)"),
    "pfx_handoff_direct_total": ("counter", "Direct prefill->decode transfer attempts on the prefill replica (labels: outcome=ok|fallback|rejected|decode_dead)"),
    # drain-time prefix migration (tools/serve.py donor send,
    # core/continuous_batching.py adopt_prefixes receiver)
    "pfx_migrate_sent_total": ("counter", "Prefix-migration payloads accepted by a surviving peer during this replica's drain"),
    "pfx_migrate_adopted_total": ("counter", "Prefix blocks adopted into this arena from a draining peer's migration payload"),
    "pfx_migrate_failed_total": ("counter", "Prefix-migration sends abandoned (retries exhausted or the PFX_MIGRATE_DEADLINE_S ladder expired) — the drain exits 0 regardless"),
    # multi-host router (core/router.py + tools/router.py; labels noted)
    "pfx_router_requests_total": ("counter", "Requests dispatched by the router (labels: replica, outcome)"),
    "pfx_router_rejected_total": ("counter", "Router admissions rejected before dispatch (labels: reason)"),
    "pfx_router_retries_total": ("counter", "Dispatches retried on another replica after connection-refused"),
    "pfx_router_in_flight": ("gauge", "Requests currently inside the router"),
    "pfx_router_replica_depth": ("gauge", "Queue depth last reported by the replica /healthz (labels: replica)"),
    "pfx_router_replica_state": ("gauge", "Replica lifecycle state code: 0 booting, 1 warm, 2 serving, 3 draining, 4 gone (labels: replica)"),
    "pfx_router_replica_latency_seconds": ("histogram", "Downstream dispatch latency (labels: replica)"),
    "pfx_router_poll_failures_total": ("counter", "Failed replica health polls (labels: replica)"),
    "pfx_router_drains_total": ("counter", "Replica drains initiated through the router"),
    "pfx_router_handoff_bytes_total": ("counter", "KV-handoff payload bytes PROXIED through the router (flat under direct transfer)"),
    "pfx_router_handoff_seconds": ("histogram", "Prefill dispatch + handoff transfer seconds per prompt (direct transport: the whole prefill->decode relay — the router cannot see the legs separately)"),
    "pfx_handoff_failovers_total": ("counter", "Handoff legs failed over by the router (labels: leg=prefill|decode)"),
    # elastic control plane (core/controller.py + tools/router.py
    # --supervise; docs/serving.md "Elastic control plane")
    "pfx_controller_ticks_total": ("counter", "Control-loop evaluations, one decision row each (labels: pool on disaggregated pool controllers; unlabeled for the monolith fleet)"),
    "pfx_controller_scale_ups_total": ("counter", "Replica scale-up decisions executed (labels: pool on disaggregated pool controllers)"),
    "pfx_controller_scale_downs_total": ("counter", "Replica scale-down (rolling-drain) decisions executed (labels: pool on disaggregated pool controllers)"),
    "pfx_controller_target_replicas": ("gauge", "Replica count the controller is steering toward (labels: pool on disaggregated pool controllers)"),
    "pfx_controller_breach": ("gauge", "1 while the controller sees a scale signal breached (SLO burn / depth / occupancy / low blocks; labels: pool on disaggregated pool controllers)"),
    "pfx_replica_restarts_total": ("counter", "Supervisor restarts of managed replicas after unexpected exits (labels: replica; only crashes spend the flap budget)"),
    "pfx_replica_quarantines_total": ("counter", "Managed replicas quarantined after crash-looping past the flap budget (labels: replica)"),
    # control-plane survivability (core/router.py FleetJournal +
    # tools/router.py recovery; docs/serving.md "Control-plane recovery")
    "pfx_router_recoveries_total": ("counter", "Router boots that recovered control-plane state from the fleet journal (fleet_state.jsonl)"),
    "pfx_router_adopted_replicas_total": ("counter", "Live replicas re-adopted into their supervised slots at boot without a respawn (labels: replica)"),
    "pfx_router_journal_records": ("gauge", "Records appended to the fleet journal since its last compaction snapshot"),
    "pfx_router_journal_bytes": ("gauge", "Bytes in the fleet journal file (compaction rewrites it atomically)"),
    "pfx_replica_registrations_total": ("counter", "Replica self-registration heartbeats accepted at POST /admin/register (labels: outcome=register|deregister)"),
    # SLO burn rates (telemetry.SLOTracker; labels: objective, window)
    "pfx_slo_objective": ("gauge", "Configured SLO objective value by objective label"),
    "pfx_slo_burn_rate": ("gauge", "Error-budget burn rate over a rolling window (labels: objective, window)"),
    "pfx_slo_breach": ("gauge", "1 while the labeled objective burns >threshold on every window"),
    "pfx_slo_ttft_p99_seconds": ("gauge", "Rolling short-window p99 TTFT seen by the SLO tracker"),
    # multi-tenant isolation (core/tenancy.py vocabulary; emitted by
    # core/router.py, core/continuous_batching.py, tools/serve.py.
    # Every `tenant` label is pre-folded through TenantLabelCap: the
    # first PFX_TENANT_LABEL_TOPK distinct tenants keep their name,
    # later ones share the `__other__` overflow bucket — cardinality
    # is bounded even though tenants are not)
    "pfx_tenant_admitted_total": ("counter", "Rows admitted by the weighted-fair scheduler pull (labels: tenant)"),
    "pfx_tenant_preemptions_total": ("counter", "Active rows preempted mid-decode by a higher-priority arrival and requeued as re-prefill continuations (labels: tenant = the victim's)"),
    "pfx_tenant_rejected_total": ("counter", "Router front-door admissions rejected by a tenant quota (labels: tenant, reason=rate|inflight)"),
    "pfx_tenant_in_flight": ("gauge", "Requests currently inside the router per tenant (labels: tenant)"),
    "pfx_tenant_queue_depth": ("gauge", "Entries waiting in the scheduler's admission queue per tenant (labels: tenant)"),
    "pfx_tenant_ttft_seconds": ("histogram", "Time to first token per tenant (labels: tenant)"),
    "pfx_tenant_slo_burn_rate": ("gauge", "Short-window SLO burn rate per tenant (labels: tenant, objective)"),
    # goodput ledgers (core/continuous_batching.py ContinuousScheduler,
    # core/engine.py fit loop; docs/observability.md "Goodput ledger").
    # The time buckets are exhaustive and mutually exclusive — their sum
    # closes against pfx_sched_wall_seconds_total within 1%; the token
    # dispositions close EXACTLY: admitted == delivered + evicted_lost +
    # preempt_refunded + shed_after_admit + in_flight
    "pfx_sched_time_seconds_total": ("counter", "Scheduler-thread wall seconds by attribution bucket (labels: bucket=device_decode|device_prefill|host_sched|readback|stream_flush|idle)"),
    "pfx_sched_wall_seconds_total": ("counter", "Total scheduler-thread wall seconds the time buckets must close against"),
    "pfx_sched_host_gap_seconds_total": ("counter", "Host seconds the device sat idle waiting for its next dispatch (goodput_frac subtrahend; overlaps the bucket family)"),
    "pfx_train_time_seconds_total": ("counter", "Fit-loop wall seconds by attribution bucket (labels: bucket=compile|device_step|data_wait|host|eval)"),
    "pfx_token_ledger_total": ("counter", "Admitted-token dispositions (labels: disposition=admitted|delivered|evicted_lost|preempt_refunded|shed_after_admit)"),
    "pfx_token_ledger_in_flight": ("gauge", "Admitted tokens still on the books in live decode slots (the exact-closure remainder)"),
    "pfx_tenant_slot_seconds_total": ("counter", "Decode-slot occupancy in slot-seconds per tenant — billing-grade cost attribution (labels: tenant)"),
    "pfx_tenant_kv_block_seconds_total": ("counter", "KV-block occupancy in block-seconds per tenant (labels: tenant)"),
}

# latency-shaped default buckets (seconds): sub-ms to minutes, exponential-ish
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)
# reservoir per histogram child: enough for stable p50/p99 on /healthz
# without unbounded memory (the old serve.py deque was maxlen=256 too)
_RESERVOIR = 256


def _env_float(name: str, default: float, minimum: float = 0.0) -> float:
    """Loud-parse float env knob (repo convention, utils/resilience.py)."""
    raw = os.environ.get(name) or ""
    if not raw.strip():
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a number (loud-parse: unset it or "
            f"pass a valid value)"
        ) from None
    if val < minimum:
        raise ValueError(f"{name}={val} must be >= {minimum}")
    return val


def _env_int(name: str, default: int, minimum: int = 1) -> int:
    """Loud-parse int env knob."""
    raw = os.environ.get(name) or ""
    if not raw.strip():
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer (loud-parse: unset it or "
            f"pass a valid value)"
        ) from None
    if val < minimum:
        raise ValueError(f"{name}={val} must be >= {minimum}")
    return val


# ---------------------------------------------------------------------------
# metric children
# ---------------------------------------------------------------------------


class Counter:
    """Monotonic counter.  ``set()`` exists for exporter-style cumulative
    imports (a loader's own ``data_wait_s`` total pushed as-is) and must
    only ever be called with non-decreasing values."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def get(self) -> float:
        with self._lock:
            return self._value


class Gauge(Counter):
    """Settable instantaneous value; ``add()`` for in-flight up/downs."""

    __slots__ = ()

    def add(self, v: float) -> None:
        self.inc(v)


class Histogram:
    """Cumulative-bucket histogram + a bounded reservoir for percentiles.

    Buckets render in Prometheus ``_bucket{le=...}`` form; the reservoir
    (last ``_RESERVOIR`` observations) feeds ``percentile()`` for the
    /healthz p50/p99 fields without a full-series store."""

    __slots__ = ("buckets", "_counts", "_sum", "_count", "_reservoir", "_lock")

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0
        self._reservoir: deque = deque(maxlen=_RESERVOIR)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            self._reservoir.append(v)

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the reservoir (0.0 when empty)."""
        with self._lock:
            vals = sorted(self._reservoir)
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
        return vals[idx]

    def state(self) -> Dict[str, Any]:
        with self._lock:
            cum, total = [], 0
            for c in self._counts:
                total += c
                cum.append(total)
            vals = sorted(self._reservoir)
            sum_ = self._sum

        def pct(q: float) -> float:
            if not vals:
                return 0.0
            return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]

        return {
            "buckets": list(zip(self.buckets, cum[:-1])),
            "count": cum[-1],
            "sum": sum_,
            "p50": pct(0.50),
            "p99": pct(0.99),
        }


class _Family:
    """One declared metric: kind + per-labelset children."""

    __slots__ = ("name", "kind", "help", "buckets", "children")

    def __init__(self, name: str, kind: str, help_: str, buckets=None) -> None:
        self.name = name
        self.kind = kind
        self.help = help_
        self.buckets = buckets
        self.children: Dict[Tuple[Tuple[str, str], ...], Any] = OrderedDict()


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class Registry:
    """Process-wide metric registry.  One instance per process in
    production (``get_registry()``); tests may build private instances
    for absolute-count isolation."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = OrderedDict()
        self._collectors: List[weakref.ref] = []

    # -- declaration-checked accessors ---------------------------------
    def _family(self, name: str, kind: str, buckets=None) -> _Family:
        declared = METRICS.get(name)
        if declared is None or declared[0] != kind:
            raise ValueError(
                f"metric {name!r} ({kind}) is not declared in "
                "telemetry.METRICS — every emitted name must be declared "
                "there (and match ^pfx_[a-z0-9_]+$; tools/lint.py E10)"
            )
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, declared[1], buckets)
                self._families[name] = fam
            return fam

    def _child(self, name: str, kind: str, labels: Dict[str, str], buckets=None):
        fam = self._family(name, kind, buckets)
        key = _label_key(labels)
        with self._lock:
            child = fam.children.get(key)
            if child is None:
                if kind == "histogram":
                    child = Histogram(fam.buckets or DEFAULT_BUCKETS)
                elif kind == "gauge":
                    child = Gauge()
                else:
                    child = Counter()
                fam.children[key] = child
            return child

    def counter(self, name: str, **labels: str) -> Counter:
        return self._child(name, "counter", labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._child(name, "gauge", labels)

    def histogram(self, name: str, buckets: Optional[Tuple[float, ...]] = None,
                  **labels: str) -> Histogram:
        return self._child(name, "histogram", labels, buckets)

    # -- collectors -----------------------------------------------------
    def register_collector(self, obj: Any) -> None:
        """Register an object with a ``collect() -> iterable of
        (metric_name, labels_dict, value)`` method.  Held by WEAK
        reference: a dead GenerationServer/RequestQueue silently drops
        out of the snapshot instead of reporting stale values forever."""
        names = {n for n, _, _ in obj.collect()}
        for n in names:
            if n not in METRICS:
                raise ValueError(
                    f"collector exports undeclared metric {n!r}; declare "
                    "it in telemetry.METRICS"
                )
        with self._lock:
            self._collectors.append(weakref.ref(obj))

    # -- snapshot + exposition -----------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """ONE consistent view of every metric: owned children plus live
        collectors, read under the registry lock.  Counters from multiple
        collectors of the same name sum (process-wide total); gauges are
        last-writer-wins.  Shape::

            {name: {"kind": ..., "help": ...,
                    "values": [(labels_dict, value)], ...}}

        histogram entries instead carry ``buckets``/``count``/``sum``/
        ``p50``/``p99`` per labelset.
        """
        snap: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for name, fam in self._families.items():
                entry = {"kind": fam.kind, "help": fam.help, "values": []}
                for key, child in fam.children.items():
                    labels = dict(key)
                    if fam.kind == "histogram":
                        entry["values"].append((labels, child.state()))
                    else:
                        entry["values"].append((labels, child.get()))
                snap[name] = entry
            live = []
            for ref in self._collectors:
                obj = ref()
                if obj is None:
                    continue
                live.append(ref)
                for name, labels, value in obj.collect():
                    kind, help_ = METRICS[name]
                    entry = snap.setdefault(
                        name, {"kind": kind, "help": help_, "values": []}
                    )
                    labels = dict(labels or {})
                    for i, (lab, old) in enumerate(entry["values"]):
                        if lab == labels:
                            entry["values"][i] = (
                                lab,
                                old + value if kind == "counter" else value,
                            )
                            break
                    else:
                        entry["values"].append((labels, float(value)))
            self._collectors[:] = live
        return snap

    def render_prometheus(self, snap: Optional[Dict[str, Dict[str, Any]]] = None) -> str:
        """Prometheus text exposition (format 0.0.4) of a snapshot —
        pass the snapshot a ``/healthz`` view was built from to guarantee
        the two endpoints agree."""
        snap = snap if snap is not None else self.snapshot()
        lines: List[str] = []
        for name in sorted(snap):
            entry = snap[name]
            lines.append(f"# HELP {name} {entry['help']}")
            lines.append(f"# TYPE {name} {entry['kind']}")
            for labels, value in entry["values"]:
                lstr = _render_labels(labels)
                if entry["kind"] == "histogram":
                    extra = dict(labels)
                    for le, cum in value["buckets"]:
                        bl = _render_labels({**extra, "le": _fmt(le)})
                        lines.append(f"{name}_bucket{bl} {cum}")
                    bl = _render_labels({**extra, "le": "+Inf"})
                    lines.append(f"{name}_bucket{bl} {value['count']}")
                    lines.append(f"{name}_sum{lstr} {_fmt(value['sum'])}")
                    lines.append(f"{name}_count{lstr} {value['count']}")
                else:
                    lines.append(f"{name}{lstr} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    def value(self, name: str, default: Any = 0.0,
              snap: Optional[Dict[str, Dict[str, Any]]] = None,
              **labels: str) -> Any:
        """Convenience read of one metric value — a counter/gauge float,
        or a histogram's state dict.  Pass ``snap`` to read out of an
        already-taken snapshot (tools/serve.py renders /healthz and
        /metrics from ONE snapshot so the endpoints agree)."""
        entry = (snap if snap is not None else self.snapshot()).get(name)
        if not entry:
            return default
        want = {str(k): str(v) for k, v in labels.items()}
        for lab, val in entry["values"]:
            if lab == want:
                return val
        return default

    def reset(self) -> None:
        """Drop every family and collector (test isolation only)."""
        with self._lock:
            self._families.clear()
            self._collectors.clear()


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


_SAMPLE_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?\s+(?P<value>[^\s]+)\s*$"
)
_LABEL_PAIR_RE = re.compile(r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"\s*$')


def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse Prometheus text exposition into ``(name, labels, value)``
    sample rows, order preserved — the federation scrape's reader
    (core/router.py).  Tolerant the way a scraper must be: comment and
    blank lines skip, a malformed sample line skips (counted into the
    scrape outcome by the caller via the returned rows being fewer, not
    by raising mid-scrape), label escapes (\\\\, \\", \\n) unescape."""
    rows: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_LINE_RE.match(line)
        if not m:
            continue
        labels: Dict[str, str] = {}
        raw = (m.group("labels") or "{}")[1:-1]
        ok = True
        for part in _split_label_pairs(raw):
            lm = _LABEL_PAIR_RE.match(part)
            if not lm:
                ok = False
                break
            # single left-to-right pass: sequential .replace calls
            # would corrupt values containing literal backslashes
            # (\\n must decode to backslash+n, not newline)
            labels[lm.group("k")] = re.sub(
                r"\\(.)",
                lambda m: {"n": "\n"}.get(m.group(1), m.group(1)),
                lm.group("v"),
            )
        if not ok:
            continue
        try:
            val = float(m.group("value").replace("+Inf", "inf")
                        .replace("Inf", "inf"))
        except ValueError:
            continue
        rows.append((m.group("name"), labels, val))
    return rows


def _split_label_pairs(raw: str) -> List[str]:
    """Split ``k="v",k2="v2"`` on commas OUTSIDE quoted values."""
    if not raw.strip():
        return []
    parts, buf, in_q, esc = [], [], False, False
    for ch in raw:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\":
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            buf.append(ch)
            continue
        if ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return parts


def _render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


_registry = Registry()


def get_registry() -> Registry:
    """The process-wide default registry."""
    return _registry


# ---------------------------------------------------------------------------
# StatsView: dict-like per-instance stats exported via a collector
# ---------------------------------------------------------------------------


class StatsView:
    """Per-instance stats with the old hand-rolled-dict interface
    (``stats["requests"] += 1``, ``dict(stats)``, ``**stats``) whose
    numeric keys are ALSO exported onto the registry.

    ``exported`` maps dict key -> declared metric name; keys mapped to
    ``None`` (and any key assigned later, e.g. ``warmup_s``/``last_error``)
    stay instance-local.  The registry holds only a weak reference, so a
    test-scoped server's counters vanish with it."""

    def __init__(
        self,
        exported: Dict[str, Optional[str]],
        init: Optional[Dict[str, Any]] = None,
        registry: Optional[Registry] = None,
    ) -> None:
        self._exported = dict(exported)
        self._lock = threading.Lock()
        self._vals: Dict[str, Any] = {k: 0 for k in exported}
        if init:
            self._vals.update(init)
        (registry or get_registry()).register_collector(self)

    # collector protocol
    def collect(self) -> List[Tuple[str, Dict[str, str], float]]:
        with self._lock:
            return [
                (metric, {}, float(self._vals[key]))
                for key, metric in self._exported.items()
                if metric is not None
                and isinstance(self._vals.get(key), (int, float))
                and not isinstance(self._vals.get(key), bool)
            ]

    # mapping protocol (enough for dict(view), **view, view.items())
    def __getitem__(self, key: str) -> Any:
        with self._lock:
            return self._vals[key]

    def __setitem__(self, key: str, value: Any) -> None:
        with self._lock:
            self._vals[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._vals.get(key, default)

    def keys(self):
        with self._lock:
            return list(self._vals.keys())

    def items(self):
        with self._lock:
            return list(self._vals.items())

    def values(self):
        with self._lock:
            return list(self._vals.values())

    def __iter__(self):
        return iter(self.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._vals)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._vals

    def __repr__(self) -> str:
        return f"StatsView({dict(self.items())!r})"


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class Span:
    """Monotonic-clock phase timing: consecutive ``mark()`` calls define
    phases.  Callers may inject timestamps captured elsewhere (the request
    queue stamps pickup/resolve under its own lock) via ``mark(label, t=)``;
    marks are kept time-ordered so injected stamps slot in correctly."""

    __slots__ = ("name", "marks")

    def __init__(self, name: str, t0: Optional[float] = None) -> None:
        self.name = name
        self.marks: List[Tuple[str, float]] = [
            ("start", time.monotonic() if t0 is None else float(t0))
        ]

    def mark(self, label: str, t: Optional[float] = None) -> None:
        self.marks.append((label, time.monotonic() if t is None else float(t)))
        self.marks.sort(key=lambda m: m[1])

    def phases(self) -> "OrderedDict[str, float]":
        """label -> seconds since the previous mark (phase ENDING at the
        label), insertion-ordered by time."""
        out: "OrderedDict[str, float]" = OrderedDict()
        for (_, t_prev), (label, t) in zip(self.marks, self.marks[1:]):
            out[label] = out.get(label, 0.0) + (t - t_prev)
        return out

    def total(self) -> float:
        return self.marks[-1][1] - self.marks[0][1]

    def event(self, **extra: Any) -> Dict[str, Any]:
        """Shape this span as a flight-recorder event."""
        return {
            "event": "span",
            "span": self.name,
            "total_s": round(self.total(), 6),
            "phases": {k: round(v, 6) for k, v in self.phases().items()},
            **extra,
        }


# ---------------------------------------------------------------------------
# MFU accounting
# ---------------------------------------------------------------------------

# per-chip dense bf16 peak FLOP/s by device kind substring (lowercased
# containment match against jax's device_kind).  The cpu entry is a NOMINAL
# 1 TFLOP/s so CPU smoke runs still produce a finite, comparable-over-time
# mfu column — it is not a hardware claim (records carry the platform).
PEAK_FLOPS_BY_DEVICE_KIND: Dict[str, float] = {
    "v6e": 918e12,
    "v6 lite": 918e12,
    "v5p": 459e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v4": 275e12,
    "cpu": 1e12,
}


def gpt_param_count(
    *,
    vocab_size: int,
    hidden_size: int,
    num_layers: int,
    ffn_hidden_size: Optional[int] = None,
) -> int:
    """Analytic matmul-bearing parameter count N for a GPT-family stack:
    tied token embedding/LM head counted once, per-layer fused-QKV +
    output projection + 2-matmul MLP with biases, 2 LayerNorms per layer
    plus the final one.  Position embeddings are excluded (lookup, not
    matmul) — this is the N in the 6·N·T FLOPs convention."""
    h = int(hidden_size)
    ffn = int(ffn_hidden_size or 4 * h)
    per_layer = (
        (3 * h * h + 3 * h)      # fused qkv
        + (h * h + h)            # attention output projection
        + (h * ffn + ffn)        # mlp up
        + (ffn * h + h)          # mlp down
        + 4 * h                  # 2 LayerNorms (scale + bias)
    )
    return int(vocab_size) * h + int(num_layers) * per_layer + 2 * h


def model_flops_per_token(config: Any = None, *, backward: bool = True,
                          **fields: int) -> Optional[float]:
    """Model FLOPs per token for a GPT-family config: ``6·N`` for a
    training step (1 fwd + 2 bwd matmul passes, PaLM's MFU convention —
    no remat extra, attention-score FLOPs excluded) or ``2·N`` forward-
    only (``backward=False``, the decode/serving basis).

    Accepts a config object carrying ``vocab_size``/``hidden_size``/
    ``num_layers`` (``ffn_hidden_size`` optional) or the same as kwargs;
    returns None when the fields are missing — non-GPT modules (ViT,
    protein) simply get no MFU column rather than a wrong one."""
    def grab(name):
        if name in fields:
            return fields[name]
        return getattr(config, name, None)

    vocab, hidden, layers = (
        grab("vocab_size"), grab("hidden_size"), grab("num_layers")
    )
    if not vocab or not hidden or not layers:
        return None
    n = gpt_param_count(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        ffn_hidden_size=grab("ffn_hidden_size"),
    )
    return float((6 if backward else 2) * n)


def detect_device_kind() -> str:
    """The backend's device_kind string ('TPU v5e', 'cpu', ...); 'unknown'
    when no backend is reachable.  Lazy jax import: callers that never ask
    for a peak (bench parent, lint) stay jax-free."""
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001 — no backend is a valid state here
        return "unknown"


def peak_flops(default: Optional[float] = None,
               device_kind: Optional[str] = None) -> Optional[float]:
    """Per-chip peak FLOP/s for the MFU denominator.

    Resolution order: ``PFX_PEAK_FLOPS`` env (loud-parse, > 0) ->
    ``PEAK_FLOPS_BY_DEVICE_KIND`` by detected device kind -> ``default``
    (None = caller omits MFU rather than fabricating one)."""
    env = _env_float("PFX_PEAK_FLOPS", 0.0)
    if env > 0.0:
        return env
    kind = (device_kind if device_kind is not None else detect_device_kind()).lower()
    for sub, peak in PEAK_FLOPS_BY_DEVICE_KIND.items():
        if sub in kind:
            return peak
    if default is not None:
        return float(default)
    logger.warning(
        f"peak_flops: unknown device kind {kind!r} and no PFX_PEAK_FLOPS "
        "set; MFU unavailable"
    )
    return None


def mfu(tokens_per_sec: float, flops_per_token: float, n_devices: int,
        peak: Optional[float] = None) -> Optional[float]:
    """Model FLOPs utilization: achieved model FLOP/s over the fleet's
    aggregate peak.  None when no peak is resolvable."""
    peak = peak if peak is not None else peak_flops()
    if not peak or n_devices < 1:
        return None
    return tokens_per_sec * flops_per_token / (peak * n_devices)


# ---------------------------------------------------------------------------
# SLO burn rates
# ---------------------------------------------------------------------------


class SLOTracker:
    """Rolling multi-window burn-rate evaluation of serving SLOs
    (docs/observability.md), Google-SRE style: an objective grants an
    error budget (p99 TTFT <= X allows 1% of requests over X; error
    rate <= Y allows a Y fraction of failures), and the *burn rate* is
    how many times faster than sustainable the current window spends
    it.  Breach = every window burning past ``burn_threshold`` — the
    short window makes the flag flip fast, the long window keeps a
    single slow request from paging anyone.

    ``observe_request`` ingests one served request (called by
    ``tools/serve.py`` per response — the HTTP layer, never the decode
    hot path); ``evaluate`` returns the operator view ``/healthz``
    embeds as its ``slo`` block; ``collect`` exports the same numbers
    as ``pfx_slo_*`` gauges for ``/metrics`` (register the tracker as
    a registry collector).  Explicit ``t``/``now`` injection keeps the
    unit tests wall-clock-free."""

    def __init__(self, *, ttft_p99_s: float = 0.0, error_rate: float = 0.0,
                 windows_s=(60.0, 600.0), burn_threshold: float = 1.0,
                 cap: int = 131072, tenant_label_fn=None) -> None:
        if ttft_p99_s < 0 or error_rate < 0:
            raise ValueError("SLO objectives must be >= 0 (0 disables)")
        ws = tuple(float(w) for w in windows_s)
        if len(ws) < 1 or any(w <= 0 for w in ws):
            raise ValueError(f"SLO windows must be positive, got {windows_s}")
        self.ttft_p99_s = float(ttft_p99_s)
        self.error_rate = float(error_rate)
        self.windows_s = tuple(sorted(ws))
        self.burn_threshold = float(burn_threshold)
        # time-pruned on observe (events older than the LONG window drop
        # off), so the long window is not silently truncated by a count
        # bound under load; ``cap`` is a memory backstop (default bites
        # at ~218 rps sustained over a 600s window) that WARNS when it
        # evicts a still-in-window event — the long-window burn is then
        # computed over less history than configured
        self.cap = int(cap)
        self._cap_warned = False
        self._events: deque = deque()
        self._lock = threading.Lock()
        self._memo: Optional[Tuple[float, Dict[str, Any]]] = None
        # per-tenant burn: events carry a pre-folded tenant label.  The
        # fold fn is injected (tools/serve.py shares ONE TenantLabelCap
        # across SLO/metrics/debug surfaces); when absent, a private
        # cap is built lazily on the first labeled observation so the
        # gauge cardinality is bounded either way
        self._tenant_label_fn = tenant_label_fn

    @property
    def enabled(self) -> bool:
        return self.ttft_p99_s > 0.0 or self.error_rate > 0.0

    def _tenant_label(self, tenant: str) -> str:
        if self._tenant_label_fn is None:
            from paddlefleetx_tpu.core.tenancy import TenantLabelCap
            self._tenant_label_fn = TenantLabelCap().label
        return self._tenant_label_fn(tenant)

    def observe_request(self, *, ttft_s: Optional[float] = None,
                        ok: bool = True, t: Optional[float] = None,
                        tenant: Optional[str] = None) -> None:
        """One served request: ``ok`` means the server answered within
        contract (200); a shed/error (500, 503, 429) is budget spend.
        ``ttft_s`` is set only for requests that delivered tokens — a
        failed request (no first token ever) counts as a TTFT violation
        in :meth:`evaluate`, not as a missing sample.  ``tenant`` (when
        set) joins the event pre-folded through the label cap and feeds
        the per-tenant short-window burn gauges."""
        if not self.enabled:
            return
        now = time.monotonic() if t is None else float(t)
        horizon = self.windows_s[-1]
        label = None if tenant is None else self._tenant_label(tenant)
        with self._lock:
            self._events.append((
                now,
                None if ttft_s is None else float(ttft_s),
                bool(ok),
                label,
            ))
            while self._events and self._events[0][0] < now - horizon:
                self._events.popleft()
            truncated = False
            while len(self._events) > self.cap:
                self._events.popleft()
                truncated = True
            if truncated and not self._cap_warned:
                self._cap_warned = True
                logger.warning(
                    f"SLOTracker: event cap {self.cap} evicted events "
                    f"still inside the {horizon:g}s window — long-window "
                    "burn rates now cover less history than configured "
                    "(sustained rps exceeds cap/window; raise cap= or "
                    "shorten --slo-windows)"
                )

    @staticmethod
    def _window_name(w: float) -> str:
        return f"{w:g}s"

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/healthz`` ``slo`` block: per-objective burn rates per
        window, the breach flag (+ per-objective ``breached`` map), and
        a human reason naming the burning objective.  Empty windows burn
        0 (a quiesced server recovers).  Live calls (``now=None``) are
        memoized for 0.2s: one /healthz request evaluates once even
        though both the registry collector and the JSON block read it —
        at the event cap a double evaluation is ~1.5M tuple scans."""
        if now is None:
            live = time.monotonic()
            memo = self._memo
            if memo is not None and live - memo[0] < 0.2:
                return memo[1]
            out = self.evaluate(now=live)
            self._memo = (live, out)
            return out
        now = float(now)
        with self._lock:
            events = list(self._events)
        out: Dict[str, Any] = {
            "enabled": self.enabled,
            "windows_s": list(self.windows_s),
            "burn_threshold": self.burn_threshold,
            "objectives": {},
            "burn": {},
            "breached": {},
            "breach": False,
            "reason": None,
        }
        if not self.enabled:
            return out
        reasons = []
        short = self.windows_s[0]
        if self.ttft_p99_s > 0:
            out["objectives"]["ttft_p99"] = self.ttft_p99_s
            burns = {}
            for w in self.windows_s:
                win = [e for e in events if e[0] >= now - w]
                ttfts = [e[1] for e in win if e[1] is not None]
                # a FAILED request (shed/error: no first token, ever) is
                # a TTFT violation, not a missing sample — otherwise a
                # fully wedged server, where every request 503s, would
                # report zero TTFT burn exactly when TTFT is worst
                failed = sum(1 for e in win if e[1] is None and not e[2])
                total = len(ttfts) + failed
                bad = sum(1 for v in ttfts if v > self.ttft_p99_s) + failed
                frac = bad / total if total else 0.0
                # p99 objective => 1% error budget
                burns[self._window_name(w)] = round(frac / 0.01, 3)
            out["burn"]["ttft_p99"] = burns
            # observed p99 over DELIVERED requests only (failures have
            # no finite TTFT; they show up in the burn rate above, and
            # an inf here would break strict Prometheus rendering)
            short_ttfts = sorted(
                e[1] for e in events
                if e[0] >= now - short and e[1] is not None
            )
            out["ttft_p99_s"] = (
                short_ttfts[min(len(short_ttfts) - 1,
                                int(round(0.99 * (len(short_ttfts) - 1))))]
                if short_ttfts else 0.0
            )
            breached = all(b > self.burn_threshold for b in burns.values())
            out["breached"]["ttft_p99"] = breached
            if breached:
                reasons.append(
                    f"ttft_p99: burn {'/'.join(str(b) for b in burns.values())}"
                    f"x over the {self.ttft_p99_s:g}s objective"
                )
        if self.error_rate > 0:
            out["objectives"]["error_rate"] = self.error_rate
            burns = {}
            for w in self.windows_s:
                evs = [e for e in events if e[0] >= now - w]
                bad = sum(1 for e in evs if not e[2])
                frac = bad / len(evs) if evs else 0.0
                burns[self._window_name(w)] = round(frac / self.error_rate, 3)
            out["burn"]["error_rate"] = burns
            breached = all(b > self.burn_threshold for b in burns.values())
            out["breached"]["error_rate"] = breached
            if breached:
                reasons.append(
                    f"error_rate: burn "
                    f"{'/'.join(str(b) for b in burns.values())}x over the "
                    f"{self.error_rate:g} objective"
                )
        # per-tenant short-window burn (labels arrive pre-folded through
        # the TenantLabelCap, so this block is bounded at top-k + 1
        # tenants no matter how many distinct callers exist)
        tenant_labels = sorted({e[3] for e in events if len(e) > 3 and e[3]})
        if tenant_labels:
            short_t0 = now - short
            tview: Dict[str, Any] = {}
            for tn in tenant_labels:
                tev = [e for e in events
                       if len(e) > 3 and e[3] == tn and e[0] >= short_t0]
                row: Dict[str, Any] = {"requests": len(tev)}
                if self.ttft_p99_s > 0:
                    ttfts = [e[1] for e in tev if e[1] is not None]
                    failed = sum(1 for e in tev if e[1] is None and not e[2])
                    total = len(ttfts) + failed
                    bad = sum(1 for v in ttfts if v > self.ttft_p99_s) + failed
                    row["ttft_p99"] = round(
                        (bad / total if total else 0.0) / 0.01, 3
                    )
                if self.error_rate > 0:
                    bad = sum(1 for e in tev if not e[2])
                    row["error_rate"] = round(
                        (bad / len(tev) if tev else 0.0) / self.error_rate, 3
                    )
                tview[tn] = row
            out["tenants"] = tview
        if reasons:
            out["breach"] = True
            out["reason"] = "; ".join(reasons)
        return out

    def collect(self):
        """Registry-collector protocol: the evaluate() numbers as
        ``pfx_slo_*`` gauges (labels: objective, window)."""
        ev = self.evaluate()
        rows = []
        for obj, target in ev["objectives"].items():
            rows.append(("pfx_slo_objective", {"objective": obj}, target))
        for obj, burns in ev["burn"].items():
            for window, burn in burns.items():
                rows.append((
                    "pfx_slo_burn_rate",
                    {"objective": obj, "window": window},
                    burn,
                ))
            rows.append((
                "pfx_slo_breach", {"objective": obj},
                # the structured per-objective flag, NOT a substring
                # match on the human reason text (rewording the message
                # must never zero the gauge)
                1.0 if ev["breached"].get(obj) else 0.0,
            ))
        if "ttft_p99_s" in ev:
            rows.append(("pfx_slo_ttft_p99_seconds", {}, ev["ttft_p99_s"]))
        for tn, row in ev.get("tenants", {}).items():
            for obj in ("ttft_p99", "error_rate"):
                if obj in row:
                    rows.append((
                        "pfx_tenant_slo_burn_rate",
                        {"tenant": tn, "objective": obj},
                        row[obj],
                    ))
        return rows


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

DEFAULT_FLIGHT_DIR = "artifacts"


def flight_dir() -> str:
    """Directory for operational artifacts (flight-recorder dumps, trace
    exports): ``PFX_FLIGHT_DIR``, default ``./artifacts/`` — dumps used
    to land in the process cwd and pollute the repo root."""
    return os.environ.get("PFX_FLIGHT_DIR") or DEFAULT_FLIGHT_DIR


def atomic_artifact_write(path: str, write_fn) -> bool:
    """THE crash-path artifact-write recipe, shared by the flight
    recorder and the trace exporter: makedirs + pid-unique tmp +
    ``os.replace``.  The pid-unique tmp matters on multi-host shared
    storage — a preemption fans a dump out to every process, and each
    must publish whole files only (last writer wins, never a torn
    interleave).  Returns False on OSError (logged, never raised: this
    runs inside crash handlers where a secondary failure must not mask
    the primary); ``write_fn(f)`` does the actual writing."""
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            write_fn(f)
        os.replace(tmp, path)
    except OSError as e:
        logger.warning(f"artifact write to {path} failed: {e}")
        return False
    return True


class FlightRecorder:
    """Bounded ring of recent structured events, dumped as JSONL on the
    bad-day paths (crash, force-quit, watchdog-degraded, rollback).

    ``record()`` is cheap (deque append under a lock) so hot-ish paths —
    step records, request spans — can feed it unconditionally; ``dump()``
    writes atomically (tmp + os.replace) and never raises: it runs inside
    crash handlers where a secondary failure must not mask the primary."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        cap = capacity if capacity is not None else _env_int(
            "PFX_FLIGHT_RECORDER_CAP", 256
        )
        self._events: deque = deque(maxlen=cap)
        self._lock = threading.Lock()
        self._seq = 0
        self._hook_installed = False

    def record(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self._seq += 1
            self._events.append({"seq": self._seq, "ts": time.time(), **event})

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def dump(self, path: Optional[str] = None, reason: str = "") -> Optional[str]:
        """Write the ring to JSONL (newest last) under a dump header.
        Path resolution: ``PFX_FLIGHT_RECORDER`` env first (the operator's
        word wins even over an explicit caller path), then the caller's
        ``path`` (the engine passes its checkpoint ``output_dir``), then
        ``<PFX_FLIGHT_DIR>/flight_recorder.jsonl`` (default
        ``./artifacts/`` — dumps no longer litter the process cwd).
        Returns the path, or None when the write failed (logged, never
        raised — this runs on crash paths)."""
        path = (
            os.environ.get("PFX_FLIGHT_RECORDER") or path
            or os.path.join(flight_dir(), "flight_recorder.jsonl")
        )
        events = self.events()
        header = {
            "event": "flight_recorder_dump",
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "events": len(events),
        }
        def write(f):
            f.write(json.dumps(header) + "\n")
            for ev in events:
                f.write(json.dumps(ev, default=str) + "\n")

        if not atomic_artifact_write(path, write):
            return None
        logger.warning(
            f"flight recorder: {len(events)} event(s) dumped to {path}"
            + (f" ({reason})" if reason else "")
        )
        return path

    def install_excepthook(self, path: Optional[str] = None) -> None:
        """Chain onto sys.excepthook AND threading.excepthook: an
        uncaught exception — main thread or not — dumps the ring (reason
        names the exception) before the normal traceback prints.
        sys.excepthook alone never fires for worker threads, and the
        serving process does its real work in them (scheduler, watchdog,
        HTTP handlers); a watchdog thread dying silently would otherwise
        leave no postmortem AND no degraded-detection.  ``path`` sets the
        dump target (tools/train.py passes its checkpoint output_dir;
        PFX_FLIGHT_RECORDER still wins).  Idempotent per recorder."""
        if self._hook_installed:
            return
        self._hook_installed = True
        prior = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                self.record({
                    "event": "crash",
                    "error": f"{exc_type.__name__}: {exc}",
                })
                self.dump(path=path, reason=f"uncaught {exc_type.__name__}")
            finally:
                prior(exc_type, exc, tb)

        sys.excepthook = hook
        prior_thread = threading.excepthook

        def thread_hook(args):
            try:
                name = args.thread.name if args.thread else "?"
                self.record({
                    "event": "crash",
                    "thread": name,
                    "error": f"{args.exc_type.__name__}: {args.exc_value}",
                })
                self.dump(
                    path=path,
                    reason=f"uncaught {args.exc_type.__name__} "
                           f"in thread {name}",
                )
            finally:
                prior_thread(args)

        threading.excepthook = thread_hook


_flight = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder."""
    return _flight
