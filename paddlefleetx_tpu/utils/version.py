"""Version info (reference utils/version.py)."""

__version__ = "0.1.0"


def show() -> str:
    import jax

    return f"paddlefleetx-tpu {__version__} (jax {jax.__version__})"
