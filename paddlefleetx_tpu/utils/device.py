"""Device detection / synchronization (reference utils/device.py:19-62,
which maps gpu/xpu/rocm/npu/mlu/intel_gpu/cpu and exposes synchronize()).

On the JAX side the backend zoo collapses: tpu / gpu / cpu, picked by
``jax.default_backend()``; synchronize = block on an empty computation.
"""

from __future__ import annotations

import os
from typing import List

import jax


def apply_platform_env() -> None:
    """Honor PFX_PLATFORM before backend init (the axon sitecustomize
    overrides a bare JAX_PLATFORMS env var; jax.config wins).  Call this
    at the top of every CLI entry point."""
    plat = os.environ.get("PFX_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)


def get_device_type() -> str:
    """'tpu' | 'gpu' | 'cpu' (plus experimental plugin names)."""
    return jax.default_backend()


def get_devices() -> List[jax.Device]:
    return list(jax.devices())


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def synchronize() -> None:
    """Block until all in-flight device work completes (reference
    paddle.device.synchronize equivalent)."""
    for d in jax.local_devices():
        jax.device_put(0.0, d).block_until_ready()


def memory_stats() -> dict:
    """Per-device memory stats where the backend reports them."""
    out = {}
    for d in jax.local_devices():
        try:
            out[str(d)] = d.memory_stats()
        except Exception:
            out[str(d)] = {}
    return out
