"""Deep-dive tracing: per-request trace timelines, a bounded sampled
trace buffer, and a Chrome-trace/Perfetto JSON exporter.

The PR 5 telemetry registry (`utils/telemetry.py`) answers "what are the
aggregate rates?"; this module answers "why was THIS request slow" and
"what did the scheduler decide at step N":

  - :class:`TraceContext` — one traced unit of work (a served request, a
    training run): a ``trace_id`` plus a flat list of spans and instant
    events on the monotonic clock.  Producers stamp phases with
    externally-captured timestamps (the request queue's ``enqueued``/
    ``picked`` stamps, the paged engine's prefill dispatch window), so
    the timeline is reconstructable offline exactly as it happened.
  - :class:`TraceBuffer` — the bounded, sampled, in-memory store.
    ``PFX_TRACE_SAMPLE`` (0..1, default 1.0) gates sampling with a
    deterministic accumulator (sample=0.5 traces every other request);
    ``PFX_TRACE_CAP`` (default 256) bounds retained traces (oldest
    evicted).  With ``PFX_TRACE_SAMPLE=0`` the buffer is disabled and
    ``maybe_start`` returns ``None`` without taking any lock or touching
    the registry — the serving hot path then carries zero tracing work.
  - :func:`chrome_trace` / :func:`export_chrome_trace` — render traces
    as Chrome trace-event JSON (``{"traceEvents": [...]}``, all events
    ``ph="X"`` complete spans with microsecond ``ts``/``dur``), loadable
    directly in Perfetto / chrome://tracing.  Exports land under
    ``PFX_FLIGHT_DIR`` (default ``./artifacts/``) next to the flight
    recorder dumps.
  - :func:`replay_decision_log` — fold a ``ContinuousScheduler``
    per-iteration decision log (`core/continuous_batching.py`) back into
    the counters it must agree with (``pfx_prefill_admits_total``,
    ``pfx_request_evictions_total``, ``pfx_spec_accepted_total``, ...):
    a silently dropped decision row shows up as a replay/counter
    mismatch in the agreement tests.

Redaction contract: traces carry NO prompt or token CONTENTS — only
lengths, counts, slots, and timings — so `/debug/trace` and trace
exports are safe to hand to an operator or attach to a ticket.

Serving wiring (tools/serve.py, docs/observability.md): every
``RequestFuture`` carries ``trace`` (a sampled :class:`TraceContext` or
None); both schedulers stamp their phases onto it; ``GET /debug/trace``
returns one timeline and ``GET /debug/traces`` the recent window as
Perfetto-loadable JSON.

Cross-process tracing (PR 15, docs/observability.md "Fleet tracing"):

  - **wall-clock anchoring**: every process captures ONE monotonic <->
    epoch anchor pair at first use (:func:`clock_anchor`); span stamps
    stay monotonic in memory, but anything that crosses a process
    boundary — the Chrome-trace export's ``ts`` values, the span
    summaries below — is converted through the anchor so spans from
    different processes land on one comparable wall-clock axis.
  - **span summaries**: :func:`span_summary` renders a trace as a
    BOUNDED envelope (``SPAN_SUMMARY_CAP`` spans, repeated per-step
    instants aggregated with their numeric args summed; counts and
    timings only, never contents) that a replica returns in the
    ``X-Span-Summary`` response header of a fabric-internal hop.
  - **propagation**: an inter-process hop carries ``X-Trace-Id`` +
    ``X-Parent-Span`` request headers (:func:`outbound_trace_headers`);
    the callee binds them via the :func:`remote_parent` context so
    ``attach_request_trace`` FORCE-samples the child trace (a stitched
    timeline must not lose a leg to the child's own sampler; sample=0
    still disables everything).
  - **stitching + the skew rule**: the caller folds returned summaries
    into its own trace with :meth:`TraceContext.add_remote_summary`.
    Clocks across hosts drift, so each hop's spans are trusted only up
    to the REQUEST/RESPONSE ENVELOPE the caller observed on its own
    clock: if the anchored child window starts before the request was
    sent (or ends after the response arrived), every span of that hop
    is shifted by the minimal constant that pulls it inside the
    envelope, and the applied ``skew_s`` is recorded on the hop bar.
    Per-hop skew is therefore bounded by the envelope width; relative
    order WITHIN a hop is always preserved.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.telemetry import (
    _env_float,
    _env_int,
    atomic_artifact_write,
    flight_dir,
    get_registry,
)


# events retained per trace (oldest dropped): request traces are
# naturally bounded by request length, but a long training fit appends
# one step_window span per logged window for its whole life — without a
# ring, a million-step run pins tens of MB on one context
TRACE_EVENT_CAP = 4096

# spans per cross-process summary (the X-Span-Summary response header):
# bounded so a long decode cannot grow an unbounded HTTP header — dense
# per-step instants aggregate first, then middle spans drop (first/last
# kept, `dropped` counted honestly)
SPAN_SUMMARY_CAP = 48
# per-name aggregation threshold inside a summary: more than this many
# events of one name (decode_chunk instants) collapse into ONE span
# covering their window, numeric args summed, `count` recorded
SPAN_AGG_THRESHOLD = 4


# ---------------------------------------------------------------------------
# wall-clock anchoring: ONE monotonic <-> epoch pair per process
# ---------------------------------------------------------------------------

_anchor_lock = threading.Lock()
_anchor: Optional[tuple] = None


def clock_anchor() -> tuple:
    """This process's ``(monotonic, epoch)`` anchor, captured ONCE at
    first use: every cross-process timestamp conversion in this process
    goes through the same pair, so the conversion is a constant offset
    (jitter between the two clock reads lands in the per-hop envelope
    bound, not in span-relative ordering)."""
    global _anchor
    if _anchor is None:
        with _anchor_lock:
            if _anchor is None:
                _anchor = (time.monotonic(), time.time())
    return _anchor


def mono_to_epoch(t: float) -> float:
    """Monotonic seconds -> epoch seconds through this process's anchor."""
    mono, epoch = clock_anchor()
    return float(t) - mono + epoch


def epoch_to_mono(t: float) -> float:
    """Epoch seconds -> this process's monotonic frame (the inverse of
    :func:`mono_to_epoch`; remote spans are stored in the LOCAL
    monotonic frame so timeline/export code paths stay uniform)."""
    mono, epoch = clock_anchor()
    return float(t) - epoch + mono


# ---------------------------------------------------------------------------
# process identity: who stamped a span (serving processes set replica
# id + role at boot; defaults keep single-process exports working)
# ---------------------------------------------------------------------------

_proc_identity: Dict[str, Any] = {}


def set_process_identity(**fields: Any) -> None:
    """Label this process's spans (``replica_id=``, ``role=``) for
    cross-process exports; tools/serve.py and tools/router.py call it
    at boot."""
    _proc_identity.update({k: v for k, v in fields.items() if v})


def process_identity() -> Dict[str, Any]:
    """``{"pid", "replica_id"?, "role"?}`` — carried in span summaries
    and used to name Perfetto pid lanes."""
    return {"pid": os.getpid(), **_proc_identity}


def _proc_label(proc: Dict[str, Any]) -> str:
    rid = proc.get("replica_id") or f"pid {proc.get('pid', '?')}"
    role = proc.get("role")
    return f"{rid} ({role})" if role else str(rid)


class TraceContext:
    """One traced unit of work: ``trace_id`` + time-ordered spans and
    instant events on the monotonic clock.

    Events are plain dicts ``{"name", "ph", "t", "dur", "args"}`` with
    ``t``/``dur`` in monotonic SECONDS (the exporter converts to the
    Chrome trace format's microseconds).  ``ph`` is ``"X"`` (complete
    span) for phases and ``"i"``-style instants are stored as ``"X"``
    with ``dur=0`` so consumers parse exactly one event shape.  The
    event list is a bounded ring (``TRACE_EVENT_CAP``, newest kept) so
    no single long-lived trace grows without bound.

    Thread-safe: a request trace is stamped by the scheduler thread and
    finished by the HTTP handler thread."""

    __slots__ = ("trace_id", "name", "meta", "t0", "t_end", "_lock", "_events")

    def __init__(self, trace_id: str, name: str, t0: Optional[float] = None,
                 **meta: Any) -> None:
        self.trace_id = trace_id
        self.name = name
        self.meta = dict(meta)
        self.t0 = time.monotonic() if t0 is None else float(t0)
        self.t_end: Optional[float] = None
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=TRACE_EVENT_CAP)

    def event(self, name: str, t: Optional[float] = None, **args: Any) -> None:
        """Record an instant (zero-duration) event — a scheduler
        decision, a decode chunk's commit counts, the respond stamp."""
        self.span(name, t0=t, t1=t, **args)

    def span(self, name: str, t0: Optional[float] = None,
             t1: Optional[float] = None, **args: Any) -> None:
        """Record a completed span [t0, t1] (monotonic seconds; ``None``
        means "now").  Negative durations are clamped to 0 — injected
        stamps may quantize, and the exporter promises non-negative
        ``dur``."""
        now = time.monotonic()
        a = now if t0 is None else float(t0)
        b = now if t1 is None else float(t1)
        ev = {
            "name": name,
            "ph": "X",
            "t": a,
            "dur": max(0.0, b - a),
            "args": args,
        }
        with self._lock:
            self._events.append(ev)

    def add_remote_summary(self, summary: Dict[str, Any],
                           t_send: float, t_recv: float) -> float:
        """Stitch one hop's span summary (:func:`span_summary`, parsed
        off the callee's ``X-Span-Summary`` response header) into this
        trace, applying THE SKEW RULE: the hop's anchored spans are
        converted into this process's monotonic frame and then shifted
        by the minimal constant that pulls the whole hop window inside
        the ``[t_send, t_recv]`` request/response envelope observed on
        THIS process's clock — per-hop skew is bounded by the envelope,
        and relative order within the hop is preserved.  Returns the
        applied skew in seconds (0.0 for well-synced clocks).

        Each remote span lands as an event carrying the hop process's
        ``pid``/``proc`` identity, so the exporter gives every process
        its own Perfetto lane; an enclosing hop bar (named after the
        remote process) is added for valid nesting in that lane."""
        proc = dict(summary.get("proc") or {})
        spans = list(summary.get("spans") or [])[:SPAN_SUMMARY_CAP]
        if not spans:
            return 0.0
        local = []
        for s in spans:
            t0 = epoch_to_mono(float(s.get("t0", 0.0)))
            dur = max(0.0, float(s.get("dur", 0.0)))
            local.append((t0, dur, s))
        w0 = min(t0 for t0, _, _ in local)
        w1 = max(t0 + dur for t0, dur, _ in local)
        skew = 0.0
        if w0 < t_send:
            skew = t_send - w0
        elif w1 > t_recv:
            # shift back, but never past the send stamp: a hop window
            # wider than its own envelope (should not happen — the hop
            # ran inside it) pins to the send edge rather than lying
            # about the request's start
            skew = max(t_send - w0, t_recv - w1)
        pid = proc.get("pid")
        label = _proc_label(proc)
        bar = {
            "name": label, "ph": "X",
            "t": w0 + skew, "dur": max(0.0, w1 - w0),
            "args": {
                "trace_id": summary.get("trace_id"),
                "skew_s": round(skew, 6),
                "dropped": int(summary.get("dropped", 0)),
            },
            "pid": pid, "proc": proc,
        }
        evs = [bar]
        for t0, dur, s in local:
            evs.append({
                "name": str(s.get("name", "?")), "ph": "X",
                "t": t0 + skew, "dur": dur,
                "args": dict(s.get("args") or {}),
                "pid": pid, "proc": proc,
            })
        with self._lock:
            self._events.extend(evs)
        return skew

    def finish(self, t: Optional[float] = None) -> None:
        """Stamp the end of the whole trace (idempotent: first wins)."""
        with self._lock:
            if self.t_end is None:
                self.t_end = time.monotonic() if t is None else float(t)

    def events(self) -> List[Dict[str, Any]]:
        """Time-ordered copies of the recorded events."""
        with self._lock:
            evs = [dict(e) for e in self._events]
        evs.sort(key=lambda e: (e["t"], -e["dur"]))
        return evs

    def total_s(self) -> float:
        end = self.t_end
        if end is None:
            with self._lock:
                end = max(
                    [e["t"] + e["dur"] for e in self._events], default=self.t0
                )
        return max(0.0, end - self.t0)

    def timeline(self) -> Dict[str, Any]:
        """The offline-reconstruction view (`GET /debug/trace?id=`):
        start-relative phase rows, newest last.  Carries no prompt/token
        contents — only names, counts, and timings."""
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "meta": dict(self.meta),
            "total_s": round(self.total_s(), 6),
            "done": self.t_end is not None,
            "events": [
                {
                    "name": e["name"],
                    "at_s": round(e["t"] - self.t0, 6),
                    "dur_s": round(e["dur"], 6),
                    "args": e["args"],
                    # stitched remote spans name their process; local
                    # events omit the key (the common single-process
                    # timeline shape is unchanged)
                    **({"proc": e["proc"]} if e.get("proc") else {}),
                }
                for e in self.events()
            ],
        }


class TraceBuffer:
    """Bounded, sampled, in-memory trace store (process-wide via
    :func:`get_trace_buffer`; tests may build private instances).

    Sampling is a deterministic accumulator — ``sample=1.0`` traces
    everything, ``0.5`` every other request, ``0`` disables tracing
    entirely (``maybe_start`` returns None without taking this buffer's
    lock or touching the registry: the acceptance contract is that the
    serving hot path does zero tracing work at sample 0)."""

    def __init__(self, sample: Optional[float] = None,
                 cap: Optional[int] = None) -> None:
        self.sample = (
            _env_float("PFX_TRACE_SAMPLE", 1.0) if sample is None
            else float(sample)
        )
        if not 0.0 <= self.sample <= 1.0:
            raise ValueError(
                f"PFX_TRACE_SAMPLE={self.sample} must be within [0, 1]"
            )
        self.cap = cap if cap is not None else _env_int("PFX_TRACE_CAP", 256)
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, TraceContext]" = OrderedDict()
        self._acc = 0.0
        self._seq = 0
        self._sampled_counter = None  # lazy registry child

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    def _start_locked(self, name: str, t0: Optional[float],
                      meta: Dict[str, Any]) -> TraceContext:
        # caller holds self._lock
        self._seq += 1
        trace_id = f"{os.getpid():x}-{self._seq:08x}"
        tc = TraceContext(trace_id, name, t0=t0, **meta)
        self._traces[trace_id] = tc
        while len(self._traces) > self.cap:
            self._traces.popitem(last=False)  # evict oldest
        return tc

    def _count_sampled(self) -> None:
        counter = self._sampled_counter
        if counter is None:
            counter = get_registry().counter("pfx_trace_sampled_total")
            self._sampled_counter = counter
        counter.inc()

    def maybe_start(self, name: str, t0: Optional[float] = None,
                    **meta: Any) -> Optional[TraceContext]:
        """Start a trace if the sampler picks this request; None
        otherwise.  The fast path at sample=0 is a single float compare."""
        if self.sample <= 0.0:
            return None
        with self._lock:
            self._acc += self.sample
            if self._acc < 1.0:
                return None
            self._acc -= 1.0
            tc = self._start_locked(name, t0, meta)
        self._count_sampled()
        return tc

    def start(self, name: str, t0: Optional[float] = None,
              **meta: Any) -> Optional[TraceContext]:
        """Start a trace UNCONDITIONALLY (bypassing the sampling
        accumulator) — the remote-parent path: a request that arrived
        carrying ``X-Trace-Id`` is already part of a sampled timeline
        at its caller, and losing the child leg to this process's own
        sampler would leave a hole in every stitched trace.  Still None
        when tracing is disabled outright (sample=0: the zero-work
        contract wins over stitching)."""
        if self.sample <= 0.0:
            return None
        with self._lock:
            tc = self._start_locked(name, t0, meta)
        self._count_sampled()
        return tc

    def get(self, trace_id: str) -> Optional[TraceContext]:
        with self._lock:
            return self._traces.get(trace_id)

    def discard(self, trace_id: str) -> None:
        """Drop a trace that never became a unit of work (an admission
        that was rejected after sampling) so the retained window holds
        only real timelines."""
        with self._lock:
            self._traces.pop(trace_id, None)

    def traces(self) -> List[TraceContext]:
        """Oldest-first snapshot of the retained window."""
        with self._lock:
            return list(self._traces.values())


# ---------------------------------------------------------------------------
# cross-process propagation: request headers + the remote-parent binding
# ---------------------------------------------------------------------------

TRACE_ID_HEADER = "X-Trace-Id"
PARENT_SPAN_HEADER = "X-Parent-Span"
SPAN_SUMMARY_HEADER = "X-Span-Summary"

_remote_tls = threading.local()


def outbound_trace_headers(trace, span: str) -> Dict[str, str]:
    """Request headers for one inter-process hop: the caller's trace id
    plus the hop name the callee's spans nest under.  Empty when the
    request is untraced (the callee then applies its own sampler)."""
    if trace is None:
        return {}
    return {TRACE_ID_HEADER: trace.trace_id, PARENT_SPAN_HEADER: str(span)}


def remote_parent_from_headers(headers: Any) -> Optional[Dict[str, str]]:
    """Parse the propagation headers off an incoming request (any
    ``.get()``-able mapping); None when the hop is untraced."""
    tid = str((headers.get(TRACE_ID_HEADER) if headers is not None else "")
              or "").strip()
    if not tid:
        return None
    return {
        "trace_id": tid,
        "span": str(headers.get(PARENT_SPAN_HEADER) or "").strip(),
    }


class remote_parent:
    """Bind an incoming hop's parent identity for the duration of the
    ``submit`` call (thread-local; the HTTP handler submits on its own
    thread, synchronously): ``attach_request_trace`` then FORCE-samples
    the trace and records the parent ids.  ``parent=None`` is a no-op
    so call sites stay unconditional."""

    def __init__(self, parent: Optional[Dict[str, str]]) -> None:
        self._parent = parent

    def __enter__(self) -> "remote_parent":
        if self._parent is not None:
            self._prev = getattr(_remote_tls, "parent", None)
            _remote_tls.parent = self._parent
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._parent is not None:
            _remote_tls.parent = self._prev


def current_remote_parent() -> Optional[Dict[str, str]]:
    return getattr(_remote_tls, "parent", None)


def _scalar_args(args: Dict[str, Any]) -> Dict[str, Any]:
    """Counts/timings only (the redaction contract, applied again at
    the process boundary): keep numeric/bool/short-string values, drop
    anything structured."""
    out = {}
    for k, v in args.items():
        if isinstance(v, bool) or isinstance(v, (int, float)):
            out[k] = v
        elif isinstance(v, str) and len(v) <= 64:
            out[k] = v
    return out


def span_summary(trace: TraceContext,
                 cap: int = SPAN_SUMMARY_CAP) -> Dict[str, Any]:
    """Render a trace as the bounded cross-process envelope a replica
    returns in its ``X-Span-Summary`` response header: spans on the
    wall-clock axis (epoch seconds through this process's anchor), this
    process's identity, scalar args only.  Dense repeated instants (one
    ``decode_chunk`` per iteration) aggregate into one span with their
    numeric args summed and ``count`` recorded; past ``cap`` spans the
    middle drops (first/last kept) and ``dropped`` says how many."""
    evs = [e for e in trace.events() if not e.get("proc")]
    by_name: Dict[str, int] = {}
    for e in evs:
        by_name[e["name"]] = by_name.get(e["name"], 0) + 1
    agg: Dict[str, Dict[str, Any]] = {}
    spans: List[Dict[str, Any]] = []
    for e in evs:
        name = e["name"]
        if by_name[name] > SPAN_AGG_THRESHOLD:
            a = agg.get(name)
            if a is None:
                a = agg[name] = {
                    "name": name, "t0": e["t"], "end": e["t"] + e["dur"],
                    "args": {"count": 0},
                }
                spans.append(a)
            a["t0"] = min(a["t0"], e["t"])
            a["end"] = max(a["end"], e["t"] + e["dur"])
            a["args"]["count"] += 1
            for k, v in e["args"].items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                a["args"][k] = a["args"].get(k, 0) + v
        else:
            spans.append({
                "name": name, "t0": e["t"], "end": e["t"] + e["dur"],
                "args": _scalar_args(e["args"]),
            })
    dropped = 0
    if len(spans) > cap:
        dropped = len(spans) - cap
        spans = spans[:cap - 1] + [spans[-1]]
    return {
        "trace_id": trace.trace_id,
        "proc": process_identity(),
        "spans": [
            {
                "name": s["name"],
                "t0": round(mono_to_epoch(s["t0"]), 6),
                "dur": round(max(0.0, s["end"] - s["t0"]), 6),
                "args": s["args"],
            }
            for s in spans
        ],
        "dropped": dropped,
    }


def parse_span_summaries(raw: str) -> List[Dict[str, Any]]:
    """Parse an ``X-Span-Summary`` header value (a JSON LIST of
    summaries — a relay hop appends its own to the ones it carried).
    Malformed input returns [] (a broken header must never fail the
    request it rode on)."""
    try:
        doc = json.loads(raw)
    except (ValueError, TypeError):
        return []
    if isinstance(doc, dict):
        doc = [doc]
    return [s for s in doc if isinstance(s, dict)] if isinstance(doc, list) else []


def attach_request_trace(future, *, t0: float, scheduler: str,
                         prompts: int, max_new: int) -> None:
    """THE scheduler-side request-trace attach recipe (both
    `RequestQueue.submit` and `ContinuousScheduler.submit` use it, so
    the admission-event shape cannot drift between schedulers): sample
    a trace, hang it on the future BEFORE the entry becomes visible to
    the scheduler thread, stamp the admission instant.  No-op when
    sampled out.

    A request that arrived on a traced inter-process hop (the handler
    bound :class:`remote_parent` around submit) is FORCE-sampled with
    the parent ids on its meta — the caller's stitched timeline must
    not lose this leg to the local sampler."""
    parent = current_remote_parent()
    buf = get_trace_buffer()
    if parent is not None:
        tr = buf.start(
            "request", t0=t0, scheduler=scheduler,
            parent_trace=parent["trace_id"],
            parent_span=parent.get("span", ""),
        )
    else:
        tr = buf.maybe_start("request", t0=t0, scheduler=scheduler)
    if tr is not None:
        future.trace = tr
        tr.event("admission", t=t0, prompts=prompts, max_new=max_new)


def discard_request_trace(future) -> None:
    """Undo :func:`attach_request_trace` for an admission that was
    REJECTED (QueueFull/QueueClosed): the trace never became a unit of
    work and must not sit in the sampled window as an empty timeline."""
    tr = getattr(future, "trace", None)
    if tr is not None:
        future.trace = None
        get_trace_buffer().discard(tr.trace_id)


_buffer: Optional[TraceBuffer] = None
_buffer_lock = threading.Lock()


def get_trace_buffer() -> TraceBuffer:
    """The process-wide trace buffer (knobs read at first use)."""
    global _buffer
    if _buffer is None:
        with _buffer_lock:
            if _buffer is None:
                _buffer = TraceBuffer()
    return _buffer


# ---------------------------------------------------------------------------
# Chrome trace-event / Perfetto export
# ---------------------------------------------------------------------------


def chrome_trace(traces: List[TraceContext]) -> Dict[str, Any]:
    """Render traces as a Chrome trace-event document (Perfetto- and
    chrome://tracing-loadable).  Every event is a ``ph="X"`` complete
    span carrying ``ts``/``dur`` in microseconds, ``pid`` (the process
    that stamped it — stitched remote spans keep their own pid, so each
    process gets its own Perfetto lane), ``tid`` (one lane per trace),
    and ``name``; each trace additionally gets an enclosing span named
    after the trace so the phase rows nest under one bar per request.

    WALL-CLOCK ANCHORED: ``ts`` is epoch microseconds through this
    process's :func:`clock_anchor`, not raw monotonic — two processes'
    exports (or one stitched export) overlay on one comparable axis.
    Monotonic exports could never be overlaid at all (each process's
    zero is its own boot).  ``ph="M"`` ``process_name`` metadata rows
    label the pid lanes."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    proc_names: Dict[int, str] = {pid: _proc_label(process_identity())}
    for tid, tc in enumerate(traces, start=1):
        # ONE event-list snapshot per trace, and the enclosing bar's end
        # derived from that SAME snapshot: an in-flight trace (scraped
        # mid-decode) may grow concurrently, and re-reading the live
        # events per child would let a just-appended child overhang the
        # already-computed bar — the partial overlap the nesting
        # contract forbids
        evs = tc.events()
        t_end = tc.t_end
        if t_end is None:
            t_end = max([e["t"] + e["dur"] for e in evs], default=tc.t0)
        bar_end = max(tc.t0, t_end)
        bar_ts = round(mono_to_epoch(tc.t0) * 1e6, 3)
        bar_dur = round((bar_end - tc.t0) * 1e6, 3)
        events.append({
            "ph": "X",
            "ts": bar_ts,
            "dur": bar_dur,
            "pid": pid,
            "tid": tid,
            "name": tc.name,
            "cat": "trace",
            "args": {"trace_id": tc.trace_id, **tc.meta},
        })
        for ev in evs:
            # clamp children into the enclosing bar so nesting stays
            # valid even when a stamp lands after finish()
            t0 = max(tc.t0, ev["t"])
            dur = min(ev["dur"], max(0.0, bar_end - t0))
            ev_pid = ev.get("pid") or pid
            if ev_pid not in proc_names and ev.get("proc"):
                proc_names[ev_pid] = _proc_label(ev["proc"])
            # SECOND clamp, in the ROUNDED domain: epoch-anchored ts is
            # ~2^50 us, where one float64 ulp is 0.25 us and round(x, 3)
            # can no longer move a value — independently rounded child
            # endpoints can overshoot the bar by a few ulps (the nesting
            # flake under contended laps).  Clamping the exported
            # numbers themselves keeps the document's nesting exact
            # instead of merely within float error.
            ts_c = max(round(mono_to_epoch(t0) * 1e6, 3), bar_ts)
            dur_c = max(
                0.0, min(round(dur * 1e6, 3), bar_ts + bar_dur - ts_c)
            )
            events.append({
                "ph": "X",
                "ts": ts_c,
                "dur": dur_c,
                "pid": ev_pid,
                "tid": tid,
                "name": ev["name"],
                "cat": tc.name,
                "args": dict(ev["args"]),
            })
    meta = [
        {"ph": "M", "pid": p, "tid": 0, "name": "process_name",
         "args": {"name": label}}
        for p, label in sorted(proc_names.items())
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def export_chrome_trace(path: Optional[str] = None,
                        buffer: Optional[TraceBuffer] = None) -> Optional[str]:
    """Write the buffer's retained window as Chrome-trace JSON.  Default
    path: ``<PFX_FLIGHT_DIR>/trace.json`` (next to the flight-recorder
    dumps).  Atomic write; returns the path, or None on failure (logged,
    never raised — callers include crash/debug paths)."""
    buf = buffer if buffer is not None else get_trace_buffer()
    path = path or os.path.join(flight_dir(), "trace.json")
    doc = chrome_trace(buf.traces())
    if not atomic_artifact_write(path, lambda f: json.dump(doc, f)):
        return None
    logger.info(
        f"trace export: {len(doc['traceEvents'])} event(s) to {path}"
    )
    return path


# ---------------------------------------------------------------------------
# decision-log replay
# ---------------------------------------------------------------------------


def replay_decision_log(rows) -> Dict[str, Any]:
    """Fold ContinuousScheduler decision-log rows back into the counters
    they must reproduce.  The agreement contract (tested): on a run whose
    log was not truncated, ``prefill_admits`` == pfx_prefill_admits_total,
    ``evictions`` == pfx_request_evictions_total, ``spec_accepted`` ==
    pfx_spec_accepted_total, ``prefix_hits`` == pfx_prefix_hits_total,
    the spill/migration quartet ``spills`` / ``readmits`` /
    ``spill_discards`` / ``migrate_adopted`` == pfx_prefix_spills_total
    / pfx_prefix_readmits_total / pfx_prefix_spill_discards_total /
    pfx_migrate_adopted_total, and the tenancy trio: ``preempted`` and
    per-label ``preempted_tenants`` == pfx_tenant_preemptions_total,
    per-label ``tenants`` == pfx_tenant_admitted_total — a trace event
    silently dropped by the scheduler shows up here as a mismatch."""
    out: Dict[str, Any] = {
        "iterations": 0,
        "prefill_admits": 0,
        "evictions": 0,
        "shed": 0,
        "finished": 0,
        "spec_proposed": 0,
        "spec_accepted": 0,
        "prefix_hits": 0,
        "prefix_hit_tokens": 0,
        "prefix_evictions": 0,
        "chunks": 0,
        "spills": 0,
        "readmits": 0,
        "spill_discards": 0,
        "migrate_adopted": 0,
        "preempted": 0,
        "tok_admitted": 0,
        "tok_delivered": 0,
        "tok_evicted_lost": 0,
        "tok_preempt_refunded": 0,
        "tok_shed_after_admit": 0,
        "tenants": {},
        "preempted_tenants": {},
    }
    for row in rows:
        out["iterations"] += 1
        out["prefill_admits"] += int(row.get("admitted", 0))
        out["evictions"] += int(row.get("evicted", 0))
        out["shed"] += int(row.get("shed", 0))
        out["finished"] += int(row.get("finished", 0))
        out["spec_proposed"] += int(row.get("spec_proposed", 0))
        out["spec_accepted"] += int(row.get("spec_accepted", 0))
        out["prefix_hits"] += int(row.get("prefix_hits", 0))
        out["prefix_hit_tokens"] += int(row.get("prefix_hit_tokens", 0))
        out["prefix_evictions"] += int(row.get("prefix_evictions", 0))
        out["chunks"] += int(row.get("chunks", 0))
        out["spills"] += int(row.get("spills", 0))
        out["readmits"] += int(row.get("readmits", 0))
        out["spill_discards"] += int(row.get("spill_discards", 0))
        out["migrate_adopted"] += int(row.get("migrate_adopted", 0))
        out["preempted"] += int(row.get("preempted", 0))
        # token-ledger columns (PR 20): folding an untruncated log
        # reproduces every pfx_token_ledger_total disposition exactly
        for key in ("tok_admitted", "tok_delivered", "tok_evicted_lost",
                    "tok_preempt_refunded", "tok_shed_after_admit"):
            out[key] += int(row.get(key, 0))
        for tn, n in (row.get("tenants") or {}).items():
            out["tenants"][tn] = out["tenants"].get(tn, 0) + int(n)
        for tn, n in (row.get("preempted_tenants") or {}).items():
            out["preempted_tenants"][tn] = (
                out["preempted_tenants"].get(tn, 0) + int(n)
            )
    return out
