"""Deep-dive tracing: per-request trace timelines, a bounded sampled
trace buffer, and a Chrome-trace/Perfetto JSON exporter.

The PR 5 telemetry registry (`utils/telemetry.py`) answers "what are the
aggregate rates?"; this module answers "why was THIS request slow" and
"what did the scheduler decide at step N":

  - :class:`TraceContext` — one traced unit of work (a served request, a
    training run): a ``trace_id`` plus a flat list of spans and instant
    events on the monotonic clock.  Producers stamp phases with
    externally-captured timestamps (the request queue's ``enqueued``/
    ``picked`` stamps, the paged engine's prefill dispatch window), so
    the timeline is reconstructable offline exactly as it happened.
  - :class:`TraceBuffer` — the bounded, sampled, in-memory store.
    ``PFX_TRACE_SAMPLE`` (0..1, default 1.0) gates sampling with a
    deterministic accumulator (sample=0.5 traces every other request);
    ``PFX_TRACE_CAP`` (default 256) bounds retained traces (oldest
    evicted).  With ``PFX_TRACE_SAMPLE=0`` the buffer is disabled and
    ``maybe_start`` returns ``None`` without taking any lock or touching
    the registry — the serving hot path then carries zero tracing work.
  - :func:`chrome_trace` / :func:`export_chrome_trace` — render traces
    as Chrome trace-event JSON (``{"traceEvents": [...]}``, all events
    ``ph="X"`` complete spans with microsecond ``ts``/``dur``), loadable
    directly in Perfetto / chrome://tracing.  Exports land under
    ``PFX_FLIGHT_DIR`` (default ``./artifacts/``) next to the flight
    recorder dumps.
  - :func:`replay_decision_log` — fold a ``ContinuousScheduler``
    per-iteration decision log (`core/continuous_batching.py`) back into
    the counters it must agree with (``pfx_prefill_admits_total``,
    ``pfx_request_evictions_total``, ``pfx_spec_accepted_total``, ...):
    a silently dropped decision row shows up as a replay/counter
    mismatch in the agreement tests.

Redaction contract: traces carry NO prompt or token CONTENTS — only
lengths, counts, slots, and timings — so `/debug/trace` and trace
exports are safe to hand to an operator or attach to a ticket.

Serving wiring (tools/serve.py, docs/observability.md): every
``RequestFuture`` carries ``trace`` (a sampled :class:`TraceContext` or
None); both schedulers stamp their phases onto it; ``GET /debug/trace``
returns one timeline and ``GET /debug/traces`` the recent window as
Perfetto-loadable JSON.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.telemetry import (
    _env_float,
    _env_int,
    atomic_artifact_write,
    flight_dir,
    get_registry,
)


# events retained per trace (oldest dropped): request traces are
# naturally bounded by request length, but a long training fit appends
# one step_window span per logged window for its whole life — without a
# ring, a million-step run pins tens of MB on one context
TRACE_EVENT_CAP = 4096


class TraceContext:
    """One traced unit of work: ``trace_id`` + time-ordered spans and
    instant events on the monotonic clock.

    Events are plain dicts ``{"name", "ph", "t", "dur", "args"}`` with
    ``t``/``dur`` in monotonic SECONDS (the exporter converts to the
    Chrome trace format's microseconds).  ``ph`` is ``"X"`` (complete
    span) for phases and ``"i"``-style instants are stored as ``"X"``
    with ``dur=0`` so consumers parse exactly one event shape.  The
    event list is a bounded ring (``TRACE_EVENT_CAP``, newest kept) so
    no single long-lived trace grows without bound.

    Thread-safe: a request trace is stamped by the scheduler thread and
    finished by the HTTP handler thread."""

    __slots__ = ("trace_id", "name", "meta", "t0", "t_end", "_lock", "_events")

    def __init__(self, trace_id: str, name: str, t0: Optional[float] = None,
                 **meta: Any) -> None:
        self.trace_id = trace_id
        self.name = name
        self.meta = dict(meta)
        self.t0 = time.monotonic() if t0 is None else float(t0)
        self.t_end: Optional[float] = None
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=TRACE_EVENT_CAP)

    def event(self, name: str, t: Optional[float] = None, **args: Any) -> None:
        """Record an instant (zero-duration) event — a scheduler
        decision, a decode chunk's commit counts, the respond stamp."""
        self.span(name, t0=t, t1=t, **args)

    def span(self, name: str, t0: Optional[float] = None,
             t1: Optional[float] = None, **args: Any) -> None:
        """Record a completed span [t0, t1] (monotonic seconds; ``None``
        means "now").  Negative durations are clamped to 0 — injected
        stamps may quantize, and the exporter promises non-negative
        ``dur``."""
        now = time.monotonic()
        a = now if t0 is None else float(t0)
        b = now if t1 is None else float(t1)
        ev = {
            "name": name,
            "ph": "X",
            "t": a,
            "dur": max(0.0, b - a),
            "args": args,
        }
        with self._lock:
            self._events.append(ev)

    def finish(self, t: Optional[float] = None) -> None:
        """Stamp the end of the whole trace (idempotent: first wins)."""
        with self._lock:
            if self.t_end is None:
                self.t_end = time.monotonic() if t is None else float(t)

    def events(self) -> List[Dict[str, Any]]:
        """Time-ordered copies of the recorded events."""
        with self._lock:
            evs = [dict(e) for e in self._events]
        evs.sort(key=lambda e: (e["t"], -e["dur"]))
        return evs

    def total_s(self) -> float:
        end = self.t_end
        if end is None:
            with self._lock:
                end = max(
                    [e["t"] + e["dur"] for e in self._events], default=self.t0
                )
        return max(0.0, end - self.t0)

    def timeline(self) -> Dict[str, Any]:
        """The offline-reconstruction view (`GET /debug/trace?id=`):
        start-relative phase rows, newest last.  Carries no prompt/token
        contents — only names, counts, and timings."""
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "meta": dict(self.meta),
            "total_s": round(self.total_s(), 6),
            "done": self.t_end is not None,
            "events": [
                {
                    "name": e["name"],
                    "at_s": round(e["t"] - self.t0, 6),
                    "dur_s": round(e["dur"], 6),
                    "args": e["args"],
                }
                for e in self.events()
            ],
        }


class TraceBuffer:
    """Bounded, sampled, in-memory trace store (process-wide via
    :func:`get_trace_buffer`; tests may build private instances).

    Sampling is a deterministic accumulator — ``sample=1.0`` traces
    everything, ``0.5`` every other request, ``0`` disables tracing
    entirely (``maybe_start`` returns None without taking this buffer's
    lock or touching the registry: the acceptance contract is that the
    serving hot path does zero tracing work at sample 0)."""

    def __init__(self, sample: Optional[float] = None,
                 cap: Optional[int] = None) -> None:
        self.sample = (
            _env_float("PFX_TRACE_SAMPLE", 1.0) if sample is None
            else float(sample)
        )
        if not 0.0 <= self.sample <= 1.0:
            raise ValueError(
                f"PFX_TRACE_SAMPLE={self.sample} must be within [0, 1]"
            )
        self.cap = cap if cap is not None else _env_int("PFX_TRACE_CAP", 256)
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, TraceContext]" = OrderedDict()
        self._acc = 0.0
        self._seq = 0
        self._sampled_counter = None  # lazy registry child

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    def maybe_start(self, name: str, t0: Optional[float] = None,
                    **meta: Any) -> Optional[TraceContext]:
        """Start a trace if the sampler picks this request; None
        otherwise.  The fast path at sample=0 is a single float compare."""
        if self.sample <= 0.0:
            return None
        with self._lock:
            self._acc += self.sample
            if self._acc < 1.0:
                return None
            self._acc -= 1.0
            self._seq += 1
            trace_id = f"{os.getpid():x}-{self._seq:08x}"
            tc = TraceContext(trace_id, name, t0=t0, **meta)
            self._traces[trace_id] = tc
            while len(self._traces) > self.cap:
                self._traces.popitem(last=False)  # evict oldest
            counter = self._sampled_counter
        if counter is None:
            counter = get_registry().counter("pfx_trace_sampled_total")
            self._sampled_counter = counter
        counter.inc()
        return tc

    def get(self, trace_id: str) -> Optional[TraceContext]:
        with self._lock:
            return self._traces.get(trace_id)

    def discard(self, trace_id: str) -> None:
        """Drop a trace that never became a unit of work (an admission
        that was rejected after sampling) so the retained window holds
        only real timelines."""
        with self._lock:
            self._traces.pop(trace_id, None)

    def traces(self) -> List[TraceContext]:
        """Oldest-first snapshot of the retained window."""
        with self._lock:
            return list(self._traces.values())


def attach_request_trace(future, *, t0: float, scheduler: str,
                         prompts: int, max_new: int) -> None:
    """THE scheduler-side request-trace attach recipe (both
    `RequestQueue.submit` and `ContinuousScheduler.submit` use it, so
    the admission-event shape cannot drift between schedulers): sample
    a trace, hang it on the future BEFORE the entry becomes visible to
    the scheduler thread, stamp the admission instant.  No-op when
    sampled out."""
    tr = get_trace_buffer().maybe_start(
        "request", t0=t0, scheduler=scheduler,
    )
    if tr is not None:
        future.trace = tr
        tr.event("admission", t=t0, prompts=prompts, max_new=max_new)


def discard_request_trace(future) -> None:
    """Undo :func:`attach_request_trace` for an admission that was
    REJECTED (QueueFull/QueueClosed): the trace never became a unit of
    work and must not sit in the sampled window as an empty timeline."""
    tr = getattr(future, "trace", None)
    if tr is not None:
        future.trace = None
        get_trace_buffer().discard(tr.trace_id)


_buffer: Optional[TraceBuffer] = None
_buffer_lock = threading.Lock()


def get_trace_buffer() -> TraceBuffer:
    """The process-wide trace buffer (knobs read at first use)."""
    global _buffer
    if _buffer is None:
        with _buffer_lock:
            if _buffer is None:
                _buffer = TraceBuffer()
    return _buffer


# ---------------------------------------------------------------------------
# Chrome trace-event / Perfetto export
# ---------------------------------------------------------------------------


def chrome_trace(traces: List[TraceContext]) -> Dict[str, Any]:
    """Render traces as a Chrome trace-event document (Perfetto- and
    chrome://tracing-loadable).  Every event is a ``ph="X"`` complete
    span carrying ``ts``/``dur`` in microseconds, ``pid`` (this
    process), ``tid`` (one lane per trace), and ``name``; each trace
    additionally gets an enclosing span named after the trace so the
    phase rows nest under one bar per request."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    for tid, tc in enumerate(traces, start=1):
        # ONE event-list snapshot per trace, and the enclosing bar's end
        # derived from that SAME snapshot: an in-flight trace (scraped
        # mid-decode) may grow concurrently, and re-reading the live
        # events per child would let a just-appended child overhang the
        # already-computed bar — the partial overlap the nesting
        # contract forbids
        evs = tc.events()
        t_end = tc.t_end
        if t_end is None:
            t_end = max([e["t"] + e["dur"] for e in evs], default=tc.t0)
        bar_end = max(tc.t0, t_end)
        events.append({
            "ph": "X",
            "ts": round(tc.t0 * 1e6, 3),
            "dur": round((bar_end - tc.t0) * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "name": tc.name,
            "cat": "trace",
            "args": {"trace_id": tc.trace_id, **tc.meta},
        })
        for ev in evs:
            # clamp children into the enclosing bar so nesting stays
            # valid even when a stamp lands after finish()
            t0 = max(tc.t0, ev["t"])
            dur = min(ev["dur"], max(0.0, bar_end - t0))
            events.append({
                "ph": "X",
                "ts": round(t0 * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "name": ev["name"],
                "cat": tc.name,
                "args": dict(ev["args"]),
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(path: Optional[str] = None,
                        buffer: Optional[TraceBuffer] = None) -> Optional[str]:
    """Write the buffer's retained window as Chrome-trace JSON.  Default
    path: ``<PFX_FLIGHT_DIR>/trace.json`` (next to the flight-recorder
    dumps).  Atomic write; returns the path, or None on failure (logged,
    never raised — callers include crash/debug paths)."""
    buf = buffer if buffer is not None else get_trace_buffer()
    path = path or os.path.join(flight_dir(), "trace.json")
    doc = chrome_trace(buf.traces())
    if not atomic_artifact_write(path, lambda f: json.dump(doc, f)):
        return None
    logger.info(
        f"trace export: {len(doc['traceEvents'])} event(s) to {path}"
    )
    return path


# ---------------------------------------------------------------------------
# decision-log replay
# ---------------------------------------------------------------------------


def replay_decision_log(rows) -> Dict[str, int]:
    """Fold ContinuousScheduler decision-log rows back into the counters
    they must reproduce.  The agreement contract (tested): on a run whose
    log was not truncated, ``prefill_admits`` == pfx_prefill_admits_total,
    ``evictions`` == pfx_request_evictions_total, ``spec_accepted`` ==
    pfx_spec_accepted_total, and ``prefix_hits`` ==
    pfx_prefix_hits_total — a trace event silently dropped by the
    scheduler shows up here as a mismatch."""
    out = {
        "iterations": 0,
        "prefill_admits": 0,
        "evictions": 0,
        "shed": 0,
        "finished": 0,
        "spec_proposed": 0,
        "spec_accepted": 0,
        "prefix_hits": 0,
        "prefix_hit_tokens": 0,
        "prefix_evictions": 0,
        "chunks": 0,
    }
    for row in rows:
        out["iterations"] += 1
        out["prefill_admits"] += int(row.get("admitted", 0))
        out["evictions"] += int(row.get("evicted", 0))
        out["shed"] += int(row.get("shed", 0))
        out["finished"] += int(row.get("finished", 0))
        out["spec_proposed"] += int(row.get("spec_proposed", 0))
        out["spec_accepted"] += int(row.get("spec_accepted", 0))
        out["prefix_hits"] += int(row.get("prefix_hits", 0))
        out["prefix_hit_tokens"] += int(row.get("prefix_hit_tokens", 0))
        out["prefix_evictions"] += int(row.get("prefix_evictions", 0))
        out["chunks"] += int(row.get("chunks", 0))
    return out
