"""Cross-host replica-consistency checks (the reference's `check` fused
comm group analogue, comm_groups.py:64: Paddle runs cross-rank consistency
verification over mp+pp; SURVEY §5.2 prescribes param-hash checks as the
TPU-native rebuild).

Under single-controller GSPMD a replicated value is consistent by
construction *within* one process; the risk surface is multi-host
training — a bad checkpoint restore, a host that skipped a step (e.g.
divergent found_inf handling), or nondeterministic data order feeding one
process.  The check fingerprints the param pytree on device (bitwise: any
1-ulp divergence changes the fingerprint), gathers the scalar across
processes, and raises if any host disagrees.

Engine integration: ``Engine.consistency_check_freq: N`` runs the check
every N steps (0 = off, the default).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.utils.log import logger

# Knuth multiplicative hash constant; uint32 arithmetic wraps (defined
# behavior in XLA)
_MULT = np.uint32(2654435761)

_UINT_FOR_SIZE = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def _fmix32(h: jax.Array) -> jax.Array:
    """murmur3 finalizer: nonlinear per-element mixing so the commutative
    sum below cannot be fooled by compensating bit changes (a plain sum of
    raw bits lets +d on one element cancel -d on another)."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def _leaf_fingerprint(x: jax.Array) -> jax.Array:
    """Position-sensitive bitwise hash of one leaf as uint32.

    Each element's bit pattern is murmur-mixed and weighted by a hash of
    its logical index, then summed.  The sum makes the reduction
    layout/sharding independent (element i keeps logical index i under
    any GSPMD partitioning); the index weight makes transposed values
    fingerprint differently (a misordered restore is exactly the
    divergence the check exists to catch)."""
    if x.dtype == jnp.bool_:
        bits = x.astype(jnp.uint32)
    else:
        if jnp.issubdtype(x.dtype, jnp.complexfloating):
            x = jnp.stack([jnp.real(x), jnp.imag(x)])
        bits = jax.lax.bitcast_convert_type(x, _UINT_FOR_SIZE[x.dtype.itemsize])
    if bits.dtype == jnp.uint64:
        # fold the high word in before the uint32 mix — truncation alone
        # would blind the check to divergence confined to the top 32 bits
        bits = (bits ^ (bits >> 32)).astype(jnp.uint32)
    bits = bits.astype(jnp.uint32).reshape(-1)
    idx = jax.lax.iota(jnp.uint32, bits.shape[0])
    weight = _fmix32(idx * _MULT + jnp.uint32(1))
    return jnp.sum(_fmix32(bits) * weight)


def tree_fingerprint(tree: Any) -> jax.Array:
    """uint32 fingerprint of a pytree: rolling hash over per-leaf bitwise
    sums (leaf order = canonical pytree order, so two structurally equal
    trees with any differing bit disagree with probability ~1-2^-32).

    Jittable; under a mesh the result is replicated (XLA inserts the
    cross-device reductions for sharded leaves)."""
    acc = jnp.uint32(0)
    for leaf in jax.tree.leaves(tree):
        acc = acc * _MULT + _leaf_fingerprint(leaf)
    return acc


# one wrapper for the process: per-call jax.jit(...) would re-trace the
# whole param tree on every check
_jitted_fingerprint = jax.jit(tree_fingerprint)


def check_replica_consistency(
    tree: Any, name: str = "params", raise_on_mismatch: bool = True
) -> int:
    """Fingerprint ``tree`` and verify every process computed the same
    value.  Returns the fingerprint.  Single-process: the gather is a
    no-op and the call just yields the fingerprint for logging."""
    fp = int(_jitted_fingerprint(tree))
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        all_fps = np.asarray(
            multihost_utils.process_allgather(np.uint32(fp))
        ).reshape(-1)
        if len(set(int(v) for v in all_fps)) != 1:
            msg = (
                f"replica consistency check FAILED for {name}: "
                f"process fingerprints {[hex(int(v)) for v in all_fps]} "
                f"(this host: {hex(fp)})"
            )
            if raise_on_mismatch:
                raise RuntimeError(msg)
            logger.error(msg)
    return fp
