"""Seed / PRNG-key discipline.

The reference maintains three seed streams (``ppfleetx/distributed/apis/
env.py:34-98``): a parameter seed shared across dp/sharding ranks, a
``global_seed`` equal within an mp group (dropout on replicated activations)
and a ``local_seed`` unique per rank (dropout on sharded activations),
registered in Paddle's RNG-state tracker for TP determinism.

Under JAX+GSPMD the same guarantees come from key *derivation*, not rank
bookkeeping: programs are written against global arrays, so one root key
yields identical init/dropout regardless of the mesh layout — which is
exactly the reference's "precision validation across layouts" goal
(env.py:62-71).  The tracker below provides named, collision-free streams:

    params    — model init (root, fold_in=0)
    global    — dropout applied to activations replicated across `model`
    local     — dropout applied to activations sharded across `model`
    data      — dataset shuffling / sampler seeds

Per-step keys fold in the step counter; per-layer keys fold in layer id.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

_STREAM_IDS = {"params": 0, "global": 1, "local": 2, "data": 3}


class SeedTracker:
    """Named PRNG streams derived from one root seed.

    ``impl`` selects the PRNG bit generator: "threefry2x32" (default,
    fully reproducible across backends) or "rbg" (hardware RNG path —
    substantially cheaper dropout on TPU at the cost of weaker
    cross-backend reproducibility guarantees)."""

    def __init__(self, seed: int, impl: Optional[str] = None):
        self.seed = int(seed)
        self.impl = impl
        self._root = jax.random.key(self.seed, impl=impl)
        self._streams: Dict[str, jax.Array] = {
            name: jax.random.fold_in(self._root, sid) for name, sid in _STREAM_IDS.items()
        }

    def key(self, stream: str, *folds: int) -> jax.Array:
        """Key for ``stream`` with optional (step, layer, ...) folds."""
        k = self._streams[stream]
        for f in folds:
            k = jax.random.fold_in(k, f)
        return k

    def params_key(self) -> jax.Array:
        return self.key("params")

    def dropout_key(self, step: int) -> jax.Array:
        return self.key("global", step)

    def data_seed(self) -> int:
        # int seed for host-side numpy RNGs (sampler shuffling)
        return int(jax.random.randint(self.key("data"), (), 0, 2**31 - 1))


_TRACKER: Optional[SeedTracker] = None


def init_seed(seed: int, impl: Optional[str] = None) -> SeedTracker:
    global _TRACKER
    _TRACKER = SeedTracker(seed, impl=impl)
    return _TRACKER


def get_seed_tracker() -> SeedTracker:
    if _TRACKER is None:
        raise RuntimeError("seed tracker not initialised; call init_seed first")
    return _TRACKER
