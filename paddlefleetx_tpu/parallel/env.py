"""Distributed environment bootstrap.

TPU-native replacement for the reference's ``init_dist_env``
(ppfleetx/distributed/apis/env.py:121-151): where the reference builds a
fleet DistributedStrategy + NCCL hybrid groups, we initialise multi-host JAX
(if needed), build the global mesh from the ``Distributed`` config block, and
seed the PRNG streams.
"""

from __future__ import annotations

import os

import jax

from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh, set_mesh
from paddlefleetx_tpu.parallel.seed import init_seed
from paddlefleetx_tpu.utils.log import logger


def init_dist_env(cfg, devices=None) -> jax.sharding.Mesh:
    """Initialise mesh + seeds from a processed config.

    Multi-host: controlled by standard JAX env vars; ``jax.distributed.
    initialize`` is invoked when a coordinator address is configured
    (the ``paddle.distributed.launch --master`` analogue).
    """
    # _dist_initialized inspects the coordination client without touching
    # the backend: jax.process_count() here would initialise XLA and make
    # the subsequent initialize() call an error
    coord = os.environ.get("PFX_COORDINATOR_ADDRESS")
    if coord and not _dist_initialized():
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ["PFX_NUM_PROCESSES"]),
            process_id=int(os.environ["PFX_PROCESS_ID"]),
        )
        logger.info(
            f"jax.distributed initialised: process {jax.process_index()}/{jax.process_count()}"
        )

    mesh_cfg = MeshConfig.from_config(cfg)
    mesh = build_mesh(mesh_cfg, devices)
    set_mesh(mesh)
    seed = int(cfg.get("Global", {}).get("seed", 1024))
    # prng_impl "rbg" = hardware RNG (cheap TPU dropout); default threefry
    init_seed(seed, impl=cfg.get("Global", {}).get("prng_impl", None))
    logger.info(f"mesh axes {dict(mesh.shape)} over {mesh.size} devices; seed {seed}")
    return mesh


def _dist_initialized() -> bool:
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False
