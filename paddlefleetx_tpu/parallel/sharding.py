"""Sharding rules: logical axis names -> mesh PartitionSpecs.

This module is the TPU-native replacement for the reference's explicit
parallel layers (``ColumnParallelLinear`` / ``RowParallelLinear`` /
``VocabParallelEmbedding`` in hybrid_model.py:153-196,699 and the ZeRO
``group_sharded_parallel`` wrap, eager_engine.py:281-307).  Models annotate
every parameter with *logical* axis names; rules map logical names to mesh
axes; pjit/GSPMD inserts the same collectives the reference issues manually:

    column-parallel matmul  = kernel sharded on output dim over `model`
    row-parallel matmul     = kernel sharded on input dim over `model`
                              (psum of partial products inserted by XLA)
    vocab-parallel embed    = embedding sharded on vocab dim over `model`
    ZeRO-1/2/3              = params/opt-state additionally sharded on `fsdp`
    Megatron SP             = activations sharded on seq dim over `model`

Logical axis vocabulary (model code uses ONLY these names):

    batch      — batch dim of activations
    seq        — sequence dim of activations (sharded over `sep`; over `model`
                 too when Megatron sequence_parallel is on)
    embed      — hidden/residual dim (fsdp-sharded for ZeRO-3)
    mlp        — FFN intermediate dim (model-sharded: column-parallel)
    heads      — attention heads dim (model-sharded)
    kv         — per-head dim (never sharded)
    vocab      — vocabulary dim (model-sharded: vocab-parallel)
    layers     — stacked-layer dim of scanned params (stage-sharded under PP)
    expert     — MoE expert dim (sharded over data×fsdp×sep expert group)
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlefleetx_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_SEP,
    AXIS_STAGES,
)

# Each rule: logical name -> mesh axis (or tuple of axes), or None (replicated)
BASE_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", (AXIS_DATA, AXIS_FSDP)),
    ("seq", AXIS_SEP),
    ("embed", None),
    ("mlp", AXIS_MODEL),
    # heads spread over model AND sep: with sep>1 this is Ulysses — outside
    # attention the seq dim is sep-sharded, inside attention heads are; the
    # reshard between them is the DAP/Ulysses all-to-all (reference
    # protein_folding/dap.py:244-398), inserted by XLA
    ("heads", (AXIS_MODEL, AXIS_SEP)),
    ("kv", None),
    ("vocab", AXIS_MODEL),
    ("table", None),
    ("layers", AXIS_STAGES),
    ("expert", (AXIS_DATA, AXIS_FSDP, AXIS_SEP)),
)


def make_rules(
    fsdp_enabled: bool = False,
    sequence_parallel: bool = False,
    mesh: Optional[Mesh] = None,
    num_experts: int = 0,
) -> Tuple[Tuple[str, Any], ...]:
    """Build logical->mesh rules for the configured strategies.

    fsdp_enabled: shard the `embed` dim of params over `fsdp` (ZeRO-3-style
    param sharding; ZeRO-1/2 are handled by sharding optimizer states /
    gradients with the same rule set, see optims.build_optimizer).

    sequence_parallel: activations' `seq` dim additionally sharded over
    `model` between attention/MLP blocks (Megatron SP,
    reference sequence_parallel_utils.py) — with GSPMD this is just a
    different activation-sharding rule; all_gather/reduce_scatter fall out.
    """
    rules = dict(BASE_RULES)
    if fsdp_enabled:
        rules["embed"] = AXIS_FSDP
        # lookup tables (word/position/type embeddings) fsdp-shard their
        # TABLE dim, not the feature dim: their backward is a scatter-add
        # from batch-sharded [b,s,h], and a feature-dim-sharded target
        # forces the SPMD partitioner into replicate-then-repartition.
        # Megatron shards embeddings along vocab for the same reason.
        # (logical_to_spec dedups: "embed" then yields fsdp to the table
        # dim on these params and leaves the feature dim whole)
        rules["vocab"] = (AXIS_MODEL, AXIS_FSDP)
        rules["table"] = AXIS_FSDP
    if sequence_parallel:
        rules["seq"] = (AXIS_SEP, AXIS_MODEL)
    if mesh is not None and num_experts > 1:
        # expert-parallel degree must divide num_experts: greedily take
        # expert-group axes whose combined size still divides E (experts
        # replicate over the rest — EP degree <= E, reference moe semantics)
        chosen = []
        prod = 1
        for ax in (AXIS_DATA, AXIS_FSDP, AXIS_SEP):
            size = mesh.shape[ax]
            if size > 1 and num_experts % (prod * size) == 0:
                chosen.append(ax)
                prod *= size
        rules["expert"] = tuple(chosen) if chosen else None
    return tuple(rules.items())


def logical_to_spec(
    logical_axes: Sequence[Optional[str]], rules: Sequence[Tuple[str, Any]]
) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    table = dict(rules)
    used: set = set()
    spec = []
    for name in logical_axes:
        if name is None:
            spec.append(None)
            continue
        axes = table.get(name)
        if axes is None:
            spec.append(None)
            continue
        # one mesh axis may appear at most once in a spec
        if isinstance(axes, str):
            axes = (axes,)
        free = tuple(a for a in axes if a not in used)
        used.update(free)
        spec.append(free if len(free) > 1 else (free[0] if free else None))
    return P(*spec)


def tree_logical_to_sharding(
    logical_tree: Any, mesh: Mesh, rules: Sequence[Tuple[str, Any]]
) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(a is None or isinstance(a, str) for a in x),
    )


def drop_small_fsdp(shardings: Any, shapes: Any, min_size: int = 1 << 16) -> Any:
    """Replicate (over `fsdp`) params smaller than ``min_size`` elements.

    Standard FSDP practice (the reference's group_sharded wrap keeps tiny
    tensors whole for the same reason): fsdp-sharding a LayerNorm-sized
    vector saves no memory worth having, and the fsdp-sharded *gradient*
    target forces the SPMD partitioner to reshard batch-sharded backward
    reductions hidden-dim-wise — an involuntary-full-rematerialization
    (replicate-then-repartition) on every layer.  ``shardings`` and
    ``shapes`` are matching pytrees (NamedSharding leaves / ShapeDtypeStruct
    leaves)."""
    import numpy as np

    def fix(sh, shape):
        if not isinstance(sh, NamedSharding):
            return sh
        if int(np.prod(shape.shape)) >= int(min_size):
            return sh
        spec = []
        changed = False
        for entry in sh.spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            kept = tuple(a for a in axes if a != AXIS_FSDP)
            changed = changed or (len(kept) != len(axes))
            spec.append(kept if len(kept) > 1 else (kept[0] if kept else None))
        return NamedSharding(sh.mesh, P(*spec)) if changed else sh

    return jax.tree.map(fix, shardings, shapes)


def _ambient_abstract_mesh():
    """The active abstract mesh, or None when there is none.

    ``jax.sharding.get_abstract_mesh`` is only re-exported on jax >= 0.5;
    older jaxlibs keep it under ``jax._src.mesh`` (and return an empty
    placeholder instead of a real mesh when no context is active), so
    normalize both spellings here instead of crashing every TP
    constraint on the public-attribute lookup."""
    getter = getattr(jax.sharding, "get_abstract_mesh", None)
    if getter is None:
        try:
            from jax._src.mesh import get_abstract_mesh as getter
        except ImportError:  # pragma: no cover — future jax w/o either
            return None
    mesh = getter()
    return mesh if getattr(mesh, "axis_names", None) else None


def _strip_manual_axes(spec: P, manual) -> P:
    """Drop mesh axes in ``manual`` from a PartitionSpec (constraints may
    not name Manual axes inside a shard_map body)."""
    entries = []
    for entry in spec:
        axes = entry if isinstance(entry, tuple) else (entry,)
        kept = tuple(a for a in axes if a is not None and a not in manual)
        entries.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    return P(*entries)


def with_logical_constraint(x: jax.Array, logical_axes, rules, mesh: Mesh):
    """`lax.with_sharding_constraint` via logical names (activation sharding).

    Inside an active mesh context (incl. partially-manual shard_map bodies,
    where some axes are Manual) the bare PartitionSpec form must be used —
    a NamedSharding would pin the all-Auto outer mesh and mismatch.

    Inside a *manual* mapped region (shard_map_compat), axes that are
    Manual must not appear in the constraint at all: 0.4.x full-manual
    shard_map rejects them outright, and on 0.9 they are meaningless (the
    body already holds the per-shard block).  Such axes are stripped; a
    constraint with nothing left is a no-op — the sharding moves to the
    in_specs/out_specs boundary of the enclosing map, which is the 0.4.x
    port contract (docs/parallelism.md)."""
    spec = logical_to_spec(logical_axes, rules)
    from paddlefleetx_tpu.parallel.shard_map_compat import current_manual_axes

    manual = current_manual_axes()
    if manual:
        spec = _strip_manual_axes(spec, manual)
        if all(entry is None for entry in spec):
            return x
        return jax.lax.with_sharding_constraint(x, spec)
    if _ambient_abstract_mesh() is not None:
        return jax.lax.with_sharding_constraint(x, spec)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
