"""Parallelism layer: one device mesh + sharding rules replace the reference's
HCG/SCG comm-group zoo (ppfleetx/distributed/apis/comm_groups.py,
protein_folding/scg.py).  All collectives are XLA-inserted via pjit shardings
or explicit psum/all_gather/ppermute/all_to_all inside shard_map."""

from paddlefleetx_tpu.parallel.mesh import MeshConfig, build_mesh, get_mesh, set_mesh
from paddlefleetx_tpu.parallel.seed import SeedTracker, init_seed, get_seed_tracker
