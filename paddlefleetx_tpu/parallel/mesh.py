"""Device-mesh construction from ``Distributed`` config degrees.

TPU-native replacement for the reference's hybrid communicate group (HCG)
bootstrap (``ppfleetx/distributed/apis/env.py:121-151`` and
``comm_groups.py:27-153``): instead of building NCCL process groups for
dp / mp / pp / sharding / moe, we build ONE ``jax.sharding.Mesh`` with named
axes and let pjit/GSPMD insert collectives.

Axis names (fixed vocabulary, see SURVEY.md §5.8):

    data    — data parallel (reference dp_degree)
    fsdp    — ZeRO/sharding axis (reference sharding_degree; params/opt states
              sharded here, gradients reduce-scattered)
    stages  — pipeline axis (reference pp_degree)
    sep     — sequence/expert alltoall axis (Ulysses / DAP generalization)
    model   — tensor-model-parallel axis (reference mp_degree)

The MoE expert axis reuses ``data``×``fsdp``×``sep`` (reference
HybridCommGroupForMoE fuses dp×mp, comm_groups.py:149-153; we keep experts
off the ``model`` axis so TP still shards each expert's FFN).

Axis order puts ``model`` innermost so TP collectives ride the
fastest ICI links, then ``sep``, then ``stages``; ``data``/``fsdp`` outermost
(can span DCN for multi-slice).  Multi-host: call
``jax.distributed.initialize()`` before ``build_mesh`` (see
``paddlefleetx_tpu.parallel.env.init_dist_env``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_STAGES = "stages"
AXIS_SEP = "sep"
AXIS_MODEL = "model"

# Outer→inner device-assignment order: model innermost (highest-bandwidth
# neighbours), data outermost (DCN-tolerant).
MESH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_STAGES, AXIS_SEP, AXIS_MODEL)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp_degree: int = 1
    sharding_degree: int = 1
    pp_degree: int = 1
    sep_degree: int = 1
    mp_degree: int = 1

    @property
    def world_size(self) -> int:
        return (
            self.dp_degree
            * self.sharding_degree
            * self.pp_degree
            * self.sep_degree
            * self.mp_degree
        )

    @staticmethod
    def from_config(cfg) -> "MeshConfig":
        dist = cfg.get("Distributed", {})
        sharding = dist.get("sharding", {})
        return MeshConfig(
            dp_degree=int(dist.get("dp_degree", 1)),
            sharding_degree=int(sharding.get("sharding_degree", 1)),
            pp_degree=int(dist.get("pp_degree", 1)),
            sep_degree=int(dist.get("sep_degree", 1)),
            mp_degree=int(dist.get("mp_degree", 1)),
        )


_GLOBAL_MESH: Optional[Mesh] = None


def _dcn_shape(shape: Sequence[int], num_hosts: int) -> Optional[Sequence[int]]:
    """Factor the host count across the OUTER axes (data, fsdp, stages) so
    cross-host (DCN) hops carry only dp/fsdp/pp traffic while mp/sep stay
    on intra-host ICI — the layout the reference achieves by rank order in
    its HCG topology (comm_groups.py:27-80) and the scaling-book recipe."""
    dcn = [1, 1, 1, 1, 1]
    remaining = num_hosts
    for i in range(3):  # data, fsdp, stages may span hosts
        if remaining == 1:
            break
        take = int(np.gcd(shape[i], remaining))
        dcn[i] = take
        remaining //= take
    return dcn if remaining == 1 else None


def build_mesh(
    mesh_cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the global 5-axis mesh from parallel degrees.

    On TPU the device assignment is topology-aware: single-slice meshes go
    through ``mesh_utils.create_device_mesh`` (ICI-nearest-neighbour
    placement for the inner axes) and multi-host/multi-slice meshes through
    ``create_hybrid_device_mesh`` with the host factor on the outer
    (DCN-tolerant) axes.  Non-TPU backends and odd shapes fall back to
    plain row-major assignment."""
    if devices is None:
        devices = jax.devices()
    if len(devices) != mesh_cfg.world_size:
        raise ValueError(
            f"mesh degrees {dataclasses.asdict(mesh_cfg)} need "
            f"{mesh_cfg.world_size} devices, have {len(devices)}"
        )
    shape = (
        mesh_cfg.dp_degree,
        mesh_cfg.sharding_degree,
        mesh_cfg.pp_degree,
        mesh_cfg.sep_degree,
        mesh_cfg.mp_degree,
    )
    devices = list(devices)
    if devices and devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            # DCN granule = slice (create_hybrid_device_mesh's default
            # grouping); multi-host single-slice pods stay on the pure-ICI
            # path, which handles them correctly
            num_slices = len({getattr(d, "slice_index", 0) for d in devices})
            if num_slices > 1:
                dcn = _dcn_shape(shape, num_slices)
                if dcn is not None:
                    ici = tuple(s // d for s, d in zip(shape, dcn))
                    arr = mesh_utils.create_hybrid_device_mesh(
                        ici, dcn, devices=devices
                    )
                    return Mesh(arr, MESH_AXES)
            else:
                arr = mesh_utils.create_device_mesh(shape, devices=devices)
                return Mesh(arr, MESH_AXES)
        except Exception as e:  # topology helper rejected the shape
            from paddlefleetx_tpu.utils.log import logger

            logger.warning(
                f"topology-aware mesh placement failed ({e!r}); "
                "falling back to row-major device assignment"
            )
    arr = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(arr, MESH_AXES)


def set_mesh(mesh: Mesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Mesh:
    if _GLOBAL_MESH is None:
        raise RuntimeError("mesh not initialised; call init_dist_env / build_mesh first")
    return _GLOBAL_MESH


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def data_parallel_world(mesh: Mesh) -> int:
    """Batch-sharding world = data x fsdp (reference env.py:158-178: the
    'data world' spans dp and sharding ranks for batch slicing)."""
    return mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
