"""Version-split ``shard_map`` adapter: one entry point, two lowerings.

The parallel schedules (``parallel/pipeline.py`` 1F1B/GPipe,
``parallel/ring_attention.py``) are written against the jax>=0.9
``jax.shard_map(axis_names=, check_vma=)`` *partially-manual* API: manual
over the schedule's own axis (``stages`` / ``sep``) with every other mesh
axis left to GSPMD.  jax 0.4.x only ships ``jax.experimental.shard_map``,
and its partial-auto mode (``auto=``) is unusable for these schedules: the
lowering emits a ``PartitionId`` instruction XLA's SPMD partitioner rejects
(UNIMPLEMENTED), and with a sharding constraint in the body it dies in a
hard ``spmd_partitioner.cc`` CHECK (``target.IsManualSubgroup() ==
sharding().IsManualSubgroup()``) — verified on jax 0.4.37, see
docs/parallelism.md.  A shim cannot paper over that; the port contract is:

* **jax >= 0.9** — route to ``jax.shard_map`` with ``axis_names=
  manual_axes`` (partial manual, the original spelling).  Specs pass
  through verbatim: they may only name manual axes.

* **jax 0.4.x** — route to ``jax.experimental.shard_map.shard_map`` in
  **full-manual** mode (every mesh axis manual, ``check_rep=False``).
  Mapped bodies must then be *valid full-manual programs*: all cross-shard
  communication is explicit in-body collectives (``ppermute`` neighbour
  hops, ``psum``/``all_gather`` seams), and no in-body sharding constraint
  may name a mesh axis (``sharding.with_logical_constraint`` drops such
  constraints inside manual regions — constrain at the in_specs/out_specs
  boundary instead).  Mesh axes a spec does not name are *replicated at
  the boundary*: XLA gathers inputs sharded along them, the body computes
  identically at every coordinate of those axes, and outputs are truly
  replicated (which is what makes ``check_rep=False`` sound here).
  Callers that can shard more axes without in-body communication (ring
  attention: batch/heads) pass richer ``full_specs`` used only on this
  branch.

Both branches record the body's manual axis set in a thread-local while
the body traces, so code deep inside a mapped region (sharding
constraints, nested ring attention) can ask :func:`current_manual_axes`
instead of guessing from jax internals.  On 0.4.x nesting a second
shard_map inside a full-manual region is impossible (the inner map's axes
are already manual — jax raises); nested schedules use the ambient manual
axes directly (``ring_attention._ring_nested_manual``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, FrozenSet, Iterable, Optional, Tuple

import jax

__all__ = [
    "HAS_JAX09_SHARD_MAP",
    "shard_map",
    "current_manual_axes",
    "in_manual_region",
]


def _has_jax09_shard_map() -> bool:
    """True when this jax carries the 0.9-era ``jax.shard_map(axis_names=,
    check_vma=)`` API (same detection the test harness uses)."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        return False
    try:
        import inspect

        return "check_vma" in inspect.signature(fn).parameters
    except (TypeError, ValueError):  # builtins/uninspectable: assume new
        return True


HAS_JAX09_SHARD_MAP: bool = _has_jax09_shard_map()

_TLS = threading.local()


def current_manual_axes() -> FrozenSet[str]:
    """Mesh axes that are Manual in the innermost shard_map body currently
    being traced on this thread (empty outside any mapped region).

    On the 0.4.x branch this is *every* axis of the mapped mesh (full
    manual); on >=0.9 it is the ``manual_axes`` the caller requested."""
    return getattr(_TLS, "axes", frozenset())


def in_manual_region() -> bool:
    return bool(current_manual_axes())


def _with_manual_axes(body: Callable, axes: FrozenSet[str]) -> Callable:
    """Wrap ``body`` so the thread-local manual set is ``axes`` while it
    traces (restored on exit; nesting overwrites, which matches jax: the
    innermost map's manual set is what in-body code must respect)."""

    def wrapped(*args):
        prev = getattr(_TLS, "axes", frozenset())
        _TLS.axes = frozenset(axes)
        try:
            return body(*args)
        finally:
            _TLS.axes = prev

    return wrapped


def shard_map(
    body: Callable,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    manual_axes: Iterable[str],
    *,
    full_specs: Optional[Tuple[Any, Any]] = None,
) -> Callable:
    """Map ``body`` over ``mesh`` manually along ``manual_axes``.

    ``in_specs``/``out_specs`` name only ``manual_axes`` (the 0.9 partial
    spelling).  ``full_specs``, when given, is an ``(in_specs, out_specs)``
    pair that may additionally name non-manual axes along which the body is
    elementwise-independent (no in-body communication needed); it is used
    on the 0.4.x full-manual branch to keep those axes sharded instead of
    boundary-replicated.  Returns the mapped callable.
    """
    manual = frozenset(manual_axes)
    missing = manual - set(mesh.axis_names)
    if missing:
        raise ValueError(
            f"manual axes {sorted(missing)} not in mesh axes {mesh.axis_names}"
        )
    if HAS_JAX09_SHARD_MAP:
        return jax.shard_map(
            _with_manual_axes(body, manual),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            axis_names=set(manual),
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map_04x

    if full_specs is not None:
        in_specs, out_specs = full_specs
    # Full manual: every mesh axis.  check_rep=False because out_specs
    # deliberately leave replicated axes unnamed and the 0.4.x rep checker
    # cannot see through the masked ppermute/psum schedules.
    return _shard_map_04x(
        _with_manual_axes(body, frozenset(mesh.axis_names)),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )
